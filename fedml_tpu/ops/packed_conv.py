"""fedpack: K co-scheduled clients' same-shape convs as ONE contraction.

The flagship's MFU story (docs/mfu_experiments.md H1/H4/H6, docs/perf.md
roofline) is that ResNet-56's C=16/32/64 stages fill at most 12.5/25/50% of
the 128-wide MXU output lanes, while the same stack measures 66% MFU at
width 128 — and the per-lane ``vmap`` the packed schedule inherits leaves
each client's conv a partial-lane GEMM (XLA lowers the batched-kernel vmap
to a grouped conv and expands it block-diagonally on TPU, H4). This module
uses the one dimension the federation has in abundance — clients — to fill
the lanes the model can't: the K lanes of a packed cohort train through ONE
MXU-shaped contraction per conv instead of K partial-lane ones.

Primary lowering (``impl='blockdiag'``): im2col block-diagonal GEMM,

    Y[P, K*Co] = P2[P, K*R] @ W_bd[K*R, K*Co],   R = kh*kw*Cin,

with P = batch*out-pixels streaming the MXU, output lanes K*Co (>= 128 at
K >= 8 for C=16) and reduction lanes K*R always full. ``W_bd`` is built
INSIDE the forward from the stacked per-client kernels via an einsum with
``eye(K)`` — off-diagonal blocks are structural zeros, so autodiff routes
gradients only to each client's own kernel, and the dgrad/wgrad dots of the
backward pass inherit the same full-lane shapes for free. The price is
explicit: the GEMM streams K x the useful FLOPs (the off-diagonal zeros)
and the patch matrix pays up to kh*kw x activation traffic —
``obs/cost.py`` reports ``packing_factor``/useful-FLOP columns so MFU
claims stay honest, and the A/B against the per-lane vmap (bench.py,
tools/lanes_probe.py ``--mode packed``) adjudicates on the chip.

Alternate lowering (``impl='grouped'``): one ``feature_group_count=K``
convolution over channel-concatenated lanes — useful FLOPs only, but the
MXU mapping is whatever XLA's grouped lowering picks (H4 measured the TPU
backend expanding it block-diagonally anyway). Both lowerings are selected
by ``--packed_conv {off,blockdiag,grouped}``; ``off`` keeps today's
per-lane vmap.

Layout contract: packed activations travel as [K, N, H, W, C] (lane-major
NHWC) and packed parameters are the STANDARD parameter tree with a leading
K axis on every leaf (:func:`stack_variables` / :func:`unstack_variables`
are bit-exact inverses). The flax modules below are named ``Conv`` /
``BatchNorm`` / ``Dense`` so auto-naming produces the same parameter paths
as the standard NHWC models — ``conv_impl='packed'`` models share their
per-client parameter pytree with the standard models leaf-for-leaf
(mirroring the ``_w2``/``_w2_inv`` contract of ops/conv_lanes.py).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "stack_variables", "unstack_variables",
    "block_diag_weight", "block_diag_unstack",
    "conv_blockdiag", "conv_grouped", "conv_vmap", "resolve_impl",
    "seed_dropout", "lane_dropout",
    "Conv", "BatchNorm", "Dense",
]

#: salt folded (plus the per-model layer index) into the explicit dropout
#: key so distinct dropout layers in one step draw independent masks —
#: the same fold-a-constant derivation the packed replay tables use
#: (parallel/local.EPOCH_KEY_SALT)
DROPOUT_KEY_SALT = 0xD120


def seed_dropout(x, key, rate: float, layer: int, deterministic: bool):
    """Explicit-key dropout — ONE derivation shared by the per-client and
    the packed lane-major lowerings, so the joint form can replay a lane's
    masks bit-for-bit from the lane's own batch key (flax's ``nn.Dropout``
    derives its key from internal module-path folding, which the packed
    twin cannot reproduce per lane). ``layer`` is the call site's static
    index within the model; ``key`` is the step's batch key (models
    receive it as ``dropout_rng``; see ModelBundle.explicit_dropout)."""
    if deterministic or rate <= 0.0:
        return x
    if key is None:
        # same contract as flax's missing-rng error: a train-mode apply
        # without a key must fail loudly, not silently skip regularization
        raise ValueError(
            "seed_dropout: train-mode apply without a dropout key — pass "
            "dropout_rng (ModelBundle.explicit_dropout threads it)")
    k = jax.random.fold_in(key, DROPOUT_KEY_SALT + layer)
    keep = jax.random.bernoulli(k, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def lane_dropout(xs, keys, rate: float, layer: int, deterministic: bool):
    """Packed (lane-major) form of :func:`seed_dropout`: ``xs`` is
    [K, N, ...], ``keys`` the [K] vector of per-lane batch keys — lane
    ``l``'s mask is exactly ``seed_dropout(xs[l], keys[l], ...)``'s, so
    packed-vs-vmap dropout parity is bit-exact per lane."""
    if deterministic or rate <= 0.0:
        return xs
    if keys is None:
        raise ValueError(
            "lane_dropout: train-mode apply without the [K] lane key "
            "vector (the joint form passes the member batch keys)")
    return jax.vmap(
        lambda x, k: seed_dropout(x, k, rate, layer, False))(xs, keys)


# -- stacked-tree helpers (the packing contract, DESIGN.md §15) ---------------

def stack_variables(variables: dict, k: int) -> dict:
    """Standard variable tree -> packed tree: every leaf gains a leading
    lane axis holding ``k`` identical copies (each lane starts the round
    from the same global model)."""
    return jax.tree.map(
        lambda v: jnp.broadcast_to(v[None], (k,) + v.shape), variables)


def unstack_variables(stacked: dict, lane: int) -> dict:
    """Packed tree -> lane ``lane``'s standard tree (bit-exact inverse of
    :func:`stack_variables` for any lane)."""
    return jax.tree.map(lambda v: v[lane], stacked)


# -- block weight stack/unstack (mirrors _w2/_w2_inv in conv_lanes.py) -------

def _w2p(w: jnp.ndarray) -> jnp.ndarray:
    """[kh,kw,Ci,Co] -> [Co, Ci*kh*kw] in PATCH row order (channel-major:
    row index = c*kh*kw + tap, matching lax.conv_general_dilated_patches)."""
    kh, kw, ci, co = w.shape
    return w.transpose(3, 2, 0, 1).reshape(co, ci * kh * kw)


def _w2p_inv(w2: jnp.ndarray, kh: int, kw: int, ci: int, co: int) -> jnp.ndarray:
    """[Co, Ci*kh*kw] -> [kh,kw,Ci,Co] (inverse of :func:`_w2p`)."""
    return w2.reshape(co, ci, kh, kw).transpose(2, 3, 1, 0)


def block_diag_weight(ws: jnp.ndarray) -> jnp.ndarray:
    """Stacked per-client kernels [K,kh,kw,Ci,Co] -> the block weight
    W_bd[K*R, K*Co] (R = Ci*kh*kw) whose diagonal blocks are the clients'
    im2col kernels and whose off-diagonal blocks are structural zeros.

    Built with an ``eye(K)`` einsum rather than scatter so gradients flow
    ONLY to the diagonal blocks: client separation survives SGD exactly.
    """
    k, kh, kw, ci, co = ws.shape
    w2s = jax.vmap(_w2p)(ws)                       # [K, Co, R]
    eye = jnp.eye(k, dtype=w2s.dtype)
    # W_bd[j*R + r, k*Co + o] = w2s[k, o, r] * eye[k, j] — a broadcast
    # multiply, NOT an einsum: an einsum would lower as one more (spurious)
    # dot in the HLO and pollute fedcost's GEMM census
    wbd = eye.T[:, None, :, None] * w2s.transpose(2, 0, 1)[None, :, :, :]
    return wbd.reshape(k * ci * kh * kw, k * co)


def block_diag_unstack(wbd: jnp.ndarray, k: int, kh: int, kw: int,
                       ci: int, co: int) -> jnp.ndarray:
    """Block weight [K*R, K*Co] -> stacked kernels [K,kh,kw,Ci,Co]: the
    bit-exact inverse of :func:`block_diag_weight` (extracts the diagonal
    blocks; off-diagonal content is discarded by contract)."""
    r = ci * kh * kw
    b = wbd.reshape(k, r, k, co)
    diag = b[jnp.arange(k), :, jnp.arange(k), :]   # [K, R, Co]
    return jax.vmap(
        lambda w2: _w2p_inv(w2.T, kh, kw, ci, co))(diag)


# -- the lowerings ------------------------------------------------------------

def _patches(xs: jnp.ndarray, kh: int, kw: int, strides: int,
             padding: str) -> jnp.ndarray:
    """[K,N,H,W,Ci] -> im2col patches [K,N,Ho,Wo,Ci*kh*kw] (channel-major
    feature order — the order :func:`_w2p` assumes)."""
    return jax.vmap(lambda x: lax.conv_general_dilated_patches(
        x, (kh, kw), (strides, strides), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC")))(xs)


def conv_blockdiag(xs: jnp.ndarray, ws: jnp.ndarray, strides: int = 1,
                   padding: str = "SAME") -> jnp.ndarray:
    """K clients' convs as ONE block-diagonal GEMM.

    xs: [K, N, H, W, Ci]   (lane-major NHWC)
    ws: [K, kh, kw, Ci, Co] (stacked per-client HWIO kernels)
    returns [K, N, Ho, Wo, Co].

    The contraction is written im2col-style — M = batch*pixels streams,
    N = K*Co output lanes, K_red = K*R reduction lanes — so the fwd dot and
    both its autodiff transposes (dgrad: N = K*R; wgrad: N = K*Co) keep at
    least one full MXU dimension at any K*C >= 128.
    """
    k, n, _h, _w, ci = xs.shape
    kh, kw, co = ws.shape[1], ws.shape[2], ws.shape[4]
    p = _patches(xs, kh, kw, strides, padding)     # [K,N,Ho,Wo,R]
    ho, wo, r = p.shape[2], p.shape[3], p.shape[4]
    p2 = p.transpose(1, 2, 3, 0, 4).reshape(n * ho * wo, k * r)
    wbd = block_diag_weight(ws).astype(xs.dtype)
    y2 = lax.dot_general(p2, wbd, (((1,), (0,)), ((), ())))
    return y2.reshape(n, ho, wo, k, co).transpose(3, 0, 1, 2, 4)


def conv_grouped(xs: jnp.ndarray, ws: jnp.ndarray, strides: int = 1,
                 padding: str = "SAME") -> jnp.ndarray:
    """K clients' convs as ONE grouped convolution
    (``feature_group_count=K`` over channel-concatenated lanes): useful
    FLOPs only; the MXU mapping is XLA's choice (H4: the TPU backend
    expands it block-diagonally itself). Same signature/contract as
    :func:`conv_blockdiag`."""
    k, n, h, w, ci = xs.shape
    kh, kw, co = ws.shape[1], ws.shape[2], ws.shape[4]
    xg = xs.transpose(1, 2, 3, 0, 4).reshape(n, h, w, k * ci)
    wg = ws.transpose(1, 2, 3, 0, 4).reshape(kh, kw, ci, k * co)
    y = lax.conv_general_dilated(
        xg, wg, (strides, strides), padding, feature_group_count=k,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    ho, wo = y.shape[1], y.shape[2]
    return y.reshape(n, ho, wo, k, co).transpose(3, 0, 1, 2, 4)


def conv_vmap(xs: jnp.ndarray, ws: jnp.ndarray, strides: int = 1,
              padding: str = "SAME") -> jnp.ndarray:
    """Per-lane reference lowering (the A/B control): plain vmap of the
    standard conv — numerics anchor for both packed lowerings and the
    probe's baseline arm."""
    return jax.vmap(lambda x, w: lax.conv_general_dilated(
        x, w, (strides, strides), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC")))(xs, ws)


_IMPLS = {"blockdiag": conv_blockdiag, "grouped": conv_grouped,
          "vmap": conv_vmap, "off": conv_vmap}


def resolve_impl(impl, k: int, kernel_size: int, ci: int, co: int,
                 strides: int, h: int, w: int) -> str:
    """One conv call site's lowering name from a model-global string OR a
    per-stage :class:`~fedml_tpu.obs.plan.LoweringPlan` (fedplan): plans
    resolve by the call site's static stage shape, so ONE packed module
    tree can mix blockdiag/grouped/off convs per stage. 'off' per stage
    means the per-lane vmap for that conv only — bit-exact vs the global
    'off' path because conv_vmap IS that path's lowering."""
    del k
    if isinstance(impl, str):
        return impl
    return impl.impl_for(kernel_size, kernel_size, ci, co, strides, h, w)


# -- flax modules (auto-named to match the standard models' param paths) -----

class Conv(nn.Module):
    """Packed drop-in for ``nn.Conv(features, (k,k), strides, padding)`` on
    lane-major input [K, N, H, W, Ci]. Parameter paths and per-lane shapes
    match nn.Conv ('kernel' [K,k,k,Ci,Co], optional 'bias' [K,Co], f32) —
    the leading K axis is the packing axis of stack_variables. ``impl`` is
    a lowering name ('blockdiag' | 'grouped' | 'off'/'vmap') or a fedplan
    :class:`~fedml_tpu.obs.plan.LoweringPlan` resolved per stage shape."""

    features: int
    kernel_size: int = 3
    strides: int = 1
    padding: str = "SAME"
    use_bias: bool = True
    impl: Any = "blockdiag"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs):
        k, ci = xs.shape[0], xs.shape[-1]
        ks = self.kernel_size
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (k, ks, ks, ci, self.features), jnp.float32)
        xs = xs.astype(self.dtype)
        impl = resolve_impl(self.impl, k, ks, ci, self.features,
                            self.strides, xs.shape[2], xs.shape[3])
        y = _IMPLS[impl](xs, kernel.astype(self.dtype),
                         self.strides, self.padding)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (k, self.features), jnp.float32)
            y = y + bias.astype(self.dtype)[:, None, None, None, :]
        return y


class BatchNorm(nn.Module):
    """Per-lane BatchNorm on [K, N, ..., C]: stats reduce over each lane's
    own (N, spatial) axes, parameters/batch_stats are the standard (C,)
    leaves with a leading K axis. Mirrors flax nn.BatchNorm's numerics
    (f32 stats as E[x^2]-E[x]^2, momentum running update, rsqrt(var+eps)
    normalize, cast to ``dtype``)."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs):
        k, c = xs.shape[0], xs.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((k, c), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((k, c), jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (k, c), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (k, c), jnp.float32)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            red = tuple(range(1, xs.ndim - 1))
            xf = xs.astype(jnp.float32)
            mean = jnp.mean(xf, axis=red)
            mean2 = jnp.mean(jnp.square(xf), axis=red)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1.0 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1.0 - self.momentum) * var)
        shape = (k,) + (1,) * (xs.ndim - 2) + (c,)
        y = (xs.astype(jnp.float32) - mean.reshape(shape)) \
            * lax.rsqrt(var.reshape(shape) + self.epsilon)
        y = y * scale.reshape(shape) + bias.reshape(shape)
        return y.astype(self.dtype)


class Dense(nn.Module):
    """Packed drop-in for ``nn.Dense(features)`` on [K, N, D]: one batched
    dot per call ('kernel' [K,D,F], 'bias' [K,F], f32 params)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xs):
        k, d = xs.shape[0], xs.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (k, d, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (k, self.features), jnp.float32)
        y = jnp.einsum("knd,kdf->knf", xs.astype(self.dtype),
                       kernel.astype(self.dtype))
        return y + bias.astype(self.dtype)[:, None, :]
