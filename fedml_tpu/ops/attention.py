"""Blockwise (flash) attention for TPU.

No counterpart exists in the reference — its only sequence models are tiny
LSTMs (fedml_api/model/nlp/rnn.py:4-70, seq len 80/20). This op is what makes
long-context federated NLP first-class on TPU: one fused kernel streams K/V
blocks through VMEM with an online softmax, so attention never materializes
the [T, T] score matrix in HBM, and the partial-result form (unnormalized
output + running rowmax/rowsum) is exactly what ring attention over an 'sp'
mesh axis needs to merge chunks arriving over ICI
(:mod:`fedml_tpu.parallel.sequence`).

Shapes: ``q, k, v`` are ``[B, H, Tq, D]`` / ``[B, H, Tk, D]``. Causal
masking uses GLOBAL positions ``q_offset + i >= k_offset + j`` so the same
code serves single-device attention (offsets 0) and ring steps (offsets are
shard starts, traced scalars).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _pick_impl(impl: str) -> str:
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return impl


# ---------------------------------------------------------------------------
# XLA path: same online-softmax math in pure jnp. XLA fuses this into a few
# kernels; it is the CPU/GPU fallback and the reference for kernel tests.
# ---------------------------------------------------------------------------

def _xla_block_partial(q, k, v, q_offset, k_offset, causal, sm_scale):
    """One Q-shard vs one K/V-chunk -> unnormalized (o, m, l). [B,H,T,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        qpos = q_offset + jnp.arange(tq)
        kpos = k_offset + jnp.arange(tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B,H,Tq]
    # rows that saw only masked keys: keep m at NEG_INF, contribute l=0
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                                   # [B,H,Tq]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o, m, l


# ---------------------------------------------------------------------------
# Pallas path
# ---------------------------------------------------------------------------

def _flash_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                  o_ref, m_ref, l_ref, m_s, l_s, acc_s, *,
                  causal: bool, sm_scale: float,
                  block_q: int, block_k: int, nk: int):
    """Grid point = (batch*heads, q_block, k_block) with the k dimension
    'arbitrary' (sequential): running rowmax/rowsum/accumulator live in
    VMEM scratch across the k sweep, so VMEM holds only one (bq, d) query
    tile and one (bk, d) K/V tile at a time — sequence length is bounded
    by HBM, not by VMEM (the previous full-K/V-resident block spec OOMed
    scoped vmem at T=8192)."""
    import jax.experimental.pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    q_start = qoff_ref[0] + qb * block_q
    k_start = koff_ref[0] + kb * block_k
    # causal: skip k blocks entirely above the diagonal (their mask is all
    # -inf); scratch then carries through unchanged.
    live = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(live)
    def _update():
        q = q_ref[0].astype(jnp.float32)                      # [bq, D]
        kblk = k_ref[0].astype(jnp.float32)                   # [bk, D]
        vblk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                          # [bq, bk]
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev = m_s[:, :1]                                   # [bq, 1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(kb == nk - 1)
    def _emit():
        o_ref[0] = acc_s[...]
        # m/l are row-broadcast across the 128-lane dim of their outputs
        m_ref[0] = m_s[...]
        l_ref[0] = l_s[...]


def _pallas_block_partial(q, k, v, q_offset, k_offset, causal, sm_scale,
                          block_q: int, block_k: int, interpret: bool):
    import jax.experimental.pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    bq = min(block_q, tq)
    bk = min(block_k, tk)
    while tq % bq:
        bq //= 2
    while tk % bk:
        bk //= 2
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(k_offset, jnp.int32).reshape(1)

    nk = tk // bk
    grid = (b * h, tq // bq, nk)
    kernel = functools.partial(
        _flash_kernel, causal=causal, sm_scale=sm_scale,
        block_q=bq, block_k=bk, nk=nk)
    from jax.experimental.pallas import tpu as pltpu
    smem = pltpu.SMEM
    vmem = pltpu.VMEM

    def spec(block, index_map):
        return pl.BlockSpec(block, index_map, memory_space=vmem)

    o, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=smem),
            pl.BlockSpec(memory_space=smem),
            spec((1, bq, d), lambda bh, qb, kb: (bh, qb, 0)),
            spec((1, bk, d), lambda bh, qb, kb: (bh, kb, 0)),
            spec((1, bk, d), lambda bh, qb, kb: (bh, kb, 0)),
        ],
        out_specs=[
            spec((1, bq, d), lambda bh, qb, kb: (bh, qb, 0)),
            spec((1, bq, 128), lambda bh, qb, kb: (bh, qb, 0)),
            spec((1, bq, 128), lambda bh, qb, kb: (bh, qb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, tq, 128), jnp.float32),
            jax.ShapeDtypeStruct((b * h, tq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running rowmax
            pltpu.VMEM((bq, 128), jnp.float32),   # running rowsum
            pltpu.VMEM((bq, d), jnp.float32),     # unnormalized output
        ],
        compiler_params=pltpu.CompilerParams(
            # only the kb sweep carries scratch state (re-initialized at
            # kb==0), so bh and qb may split across Megacore cores
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qoff, koff, qr, kr, vr)
    return (o.reshape(b, h, tq, d),
            m[..., 0].reshape(b, h, tq),
            l[..., 0].reshape(b, h, tq))


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _partial_with_vjp(causal: bool, sm_scale: float, impl: str,
                      block_q: int, block_k: int, interpret: bool):
    """Partial-attention fn with a custom VJP: forward = fused pallas kernel
    (or the XLA block math), backward = recompute via the XLA math (the
    standard flash-attention trade: no [Tq, Tk] tensor saved in fwd; bwd
    rebuilds scores once). Offsets travel as float32 scalars so custom_vjp
    can hand back ordinary zero cotangents for them."""

    def run_fwd(q, k, v, qoff, koff):
        qi = qoff.astype(jnp.int32)
        ki = koff.astype(jnp.int32)
        if impl == "xla":
            return _xla_block_partial(q, k, v, qi, ki, causal, sm_scale)
        return _pallas_block_partial(q, k, v, qi, ki, causal, sm_scale,
                                     block_q, block_k, interpret)

    @jax.custom_vjp
    def f(q, k, v, qoff, koff):
        return run_fwd(q, k, v, qoff, koff)

    def fwd(q, k, v, qoff, koff):
        return f(q, k, v, qoff, koff), (q, k, v, qoff, koff)

    def bwd(res, ct):
        q, k, v, qoff, koff = res
        qi = qoff.astype(jnp.int32)
        ki = koff.astype(jnp.int32)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _xla_block_partial(q_, k_, v_, qi, ki,
                                                  causal, sm_scale),
            q, k, v)
        dq, dk, dv = vjp(ct)
        return dq, dk, dv, jnp.zeros_like(qoff), jnp.zeros_like(koff)

    f.defvjp(fwd, bwd)
    return f


def attention_block_partial(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    q_offset=0, k_offset=0, causal: bool = True,
    sm_scale: Optional[float] = None, impl: str = "auto",
    block_q: int = 128, block_k: int = 128, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Attention of a Q shard against one K/V chunk -> partial result
    ``(o_unnormalized, rowmax m, rowsum l)``, each fp32. Merge partials from
    several chunks with :func:`merge_partials`, finish with
    :func:`normalize_partial`. Differentiable (custom VJP, recompute-style
    backward)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    impl = _pick_impl(impl)
    f = _partial_with_vjp(causal, float(sm_scale), impl, block_q, block_k,
                          interpret)
    return f(q, k, v, jnp.asarray(q_offset, jnp.float32),
             jnp.asarray(k_offset, jnp.float32))


def merge_partials(a, b):
    """Online-softmax merge of two partial results (associative)."""
    oa, ma, la = a
    ob, mb, lb = b
    m = jnp.maximum(ma, mb)
    wa = jnp.where(ma <= NEG_INF / 2, 0.0, jnp.exp(ma - m))
    wb = jnp.where(mb <= NEG_INF / 2, 0.0, jnp.exp(mb - m))
    return (oa * wa[..., None] + ob * wb[..., None], m, la * wa + lb * wb)


def normalize_partial(o, m, l, out_dtype=None):
    """Finish: divide the accumulated unnormalized output by the rowsum."""
    den = jnp.where(l == 0.0, 1.0, l)[..., None]
    out = o / den
    return out.astype(out_dtype) if out_dtype is not None else out


def attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    causal: bool = True, sm_scale: Optional[float] = None,
    impl: str = "auto", block_q: int = 128, block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Full fused attention, ``[B, H, T, D] -> [B, H, T, D]`` (q.dtype)."""
    o, m, l = attention_block_partial(
        q, k, v, causal=causal, sm_scale=sm_scale, impl=impl,
        block_q=block_q, block_k=block_k, interpret=interpret)
    return normalize_partial(o, m, l, out_dtype=q.dtype)
