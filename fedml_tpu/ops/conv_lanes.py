"""Spatial-in-lanes 3x3 convolution as a Pallas TPU kernel.

COMMITTED NEGATIVE RESULT — kept as the measured experiment + instrument
(docs/mfu_experiments.md H6; bench A/B: 14.2k vs 28.3k real img/s).

Hypothesis (docs/mfu_experiments.md H1/H4, VERDICT r4 #1): XLA's TPU conv
lowering maps C_out to the MXU's 128-wide lane dimension, so the flagship
ResNet-56's stage-1/2 convs (C=16/32) idle 7/8 and 3/4 of the lanes; this
kernel transposes the mapping:

    Y'[C_out, P] = W2[C_out, 9*C_in] @ Patches[9*C_in, P]

with P = output PIXELS in the lane dimension (always full) and C_out in
the SUBLANE dimension (granularity 8). Pass-count arithmetic promised 8x
at C=16 / 4x at C=32; C=64 breaks even, so stage 3 stays on XLA.

What measurement showed (tools/lanes_probe.py): the patch build is cheap
(6.5 us of 33) but the GEMM's STREAMED dimension is now M = C_out = 16,
so every MXU tile pays pipeline fill/drain over 2 registers — the conv's
output matrix [C_out, pixels] has one small dimension in ANY single-GEMM
mapping, and XLA's choice (stream pixels, idle lanes) is the faster
corner: 12 us/conv = 12.7% MFU at C=16, vs 33 us for this kernel. The
hardware floor at small C is streaming geometry, not lane occupancy.

The patch matrix is built in VMEM per grid step from 9 shifted lane-slices
of a row-padded image buffer — nothing is materialized in HBM (an im2col
through HBM would be bandwidth-dead: 9x activation traffic). Row padding
(one zero image-row before and after, plus one lane each end) makes every
tap a simple in-bounds slice; the x-direction edge wrap is masked with a
static (lane mod W) mask per dx.

Layout contract: activations travel as [N, C, H*W] ("lanes layout") so
the abundant H*W axis owns the lanes for every surrounding elementwise/BN
op too (flax BatchNorm with axis=1). models/resnet.py opts in via
``conv_impl='lanes'``.

Counterpart in the reference: none — fedml_api's torch models call cuDNN
(reference fedml_api/model/cv/resnet.py); this is the TPU-native answer to
the same conv workload.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TAPS = tuple((dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1))

# Largest pixel-tile (lane-dim length) per grid step. 2048 keeps the patch
# scratch comfortably in VMEM at C=32 (9*32 x 2048 bf16 = 1.1 MB).
MAX_TILE = 2048


from fedml_tpu.ops.common import interpret as _interpret
from fedml_tpu.ops.common import sds as _sds


def supported(c_in: int, h: int, w: int) -> bool:
    """Shapes the kernel handles; callers fall back to XLA otherwise.
    C_in must respect sublane granularity (patch rows sit at offsets
    t*C_in). Images must fit one lane tile (hw <= MAX_TILE): the
    multi-tile path would need dynamic lane offsets of program_id(1)*t
    plus non-128-aligned tap shifts, which Mosaic rejects ("cannot
    statically prove index is a multiple of 128") — single-tile keeps
    every tap offset static."""
    hw = h * w
    return c_in % 8 == 0 and hw % 128 == 0 and hw <= MAX_TILE


def _tile(hw: int) -> int:
    t = hw
    while t > MAX_TILE:
        t //= 2
    return t


def _w2(w: jnp.ndarray) -> jnp.ndarray:
    """[3,3,Ci,Co] -> [Co, 9*Ci] matching patch-row order (tap-major)."""
    k3, _, ci, co = w.shape
    taps = k3 * k3
    return w.reshape(taps, ci, co).transpose(2, 0, 1).reshape(co, taps * ci)


def _w2_inv(dw2: jnp.ndarray, ci: int, co: int) -> jnp.ndarray:
    """[Co, 9*Ci] -> [3,3,Ci,Co] (inverse of _w2)."""
    return dw2.reshape(co, 9, ci).transpose(1, 2, 0).reshape(3, 3, ci, co)


def _pad_rows(xf: jnp.ndarray, w: int) -> jnp.ndarray:
    """[N, C, H*W] -> [N, C, (H+2)*W + 2]: one zero image-row before and
    after plus one lane each end, so every tap offset is in-bounds.
    B[1 + W + p] = X[p]."""
    return jnp.pad(xf, ((0, 0), (0, 0), (w + 1, w + 1)))


def _col_masks(w: int, t: int):
    """Static edge masks over the lane dim: lane l has x-coord l%W because
    tile starts are multiples of W."""
    x = jax.lax.broadcasted_iota(jnp.int32, (1, t), 1) % w
    return {-1: x != 0, 0: None, 1: x != (w - 1)}


def _build_patches(x_ref, p_scr, base, masks, w: int, t: int, ci: int):
    """Fill p_scr[9*Ci, T] from the padded image buffer: patch row
    (tap*Ci + c), lane l  <-  B[c, base + (dy+1)*W + dx + 1 + l]."""
    for tap, (dy, dx) in enumerate(TAPS):
        off = base + (dy + 1) * w + dx + 1
        sl = x_ref[0, :, pl.ds(off, t)]
        m = masks[dx]
        if m is not None:
            sl = jnp.where(m, sl, jnp.zeros_like(sl))
        p_scr[tap * ci:(tap + 1) * ci, :] = sl


def _fwd_kernel(x_ref, w2_ref, y_ref, p_scr, *, w: int, t: int, ci: int,
                groups: int):
    base = 0 if groups == 1 else pl.program_id(1) * t
    masks = _col_masks(w, t)
    _build_patches(x_ref, p_scr, base, masks, w, t, ci)
    y = jnp.dot(w2_ref[...], p_scr[...], preferred_element_type=jnp.float32)
    y_ref[0, :, :] = y.astype(y_ref.dtype)


def _wgrad_kernel(x_ref, dy_ref, dw2_ref, p_scr, acc_ref, *, w: int, t: int,
                  ci: int, groups: int):
    n = pl.program_id(0)
    g = pl.program_id(1) if groups > 1 else 0
    base = 0 if groups == 1 else pl.program_id(1) * t

    @pl.when((n == 0) & (g == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    masks = _col_masks(w, t)
    _build_patches(x_ref, p_scr, base, masks, w, t, ci)
    dy = dy_ref[0, :, :]
    # dW2[o, r] += sum_l dY[o, l] * P[r, l] — contraction over the lane dim
    acc_ref[...] += jax.lax.dot_general(
        dy, p_scr[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    last = (n == pl.num_programs(0) - 1) & (g == (groups - 1))

    @pl.when(last)
    def _emit():
        dw2_ref[...] = acc_ref[...]


def _conv_fwd(xf: jnp.ndarray, w2: jnp.ndarray, h: int, w: int):
    """xf [N, Ci, H*W], w2 [Co, 9*Ci] -> [N, Co, H*W]."""
    n, ci, hw = xf.shape
    co = w2.shape[0]
    t = _tile(hw)
    groups = hw // t
    xp = _pad_rows(xf, w)
    kernel = partial(_fwd_kernel, w=w, t=t, ci=ci, groups=groups)
    return pl.pallas_call(
        kernel,
        grid=(n, groups),
        in_specs=[
            pl.BlockSpec((1, ci, xp.shape[-1]), lambda i, g: (i, 0, 0)),
            pl.BlockSpec((co, w2.shape[-1]), lambda i, g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, co, t), lambda i, g: (i, 0, g)),
        out_shape=_sds((n, co, hw), xf.dtype, xf),
        scratch_shapes=[pltpu.VMEM((9 * ci, t), xf.dtype)],
        interpret=_interpret(),
    )(xp, w2)


def _conv_wgrad(xf: jnp.ndarray, dyf: jnp.ndarray, h: int, w: int):
    """xf [N, Ci, HW], dyf [N, Co, HW] -> dW2 [Co, 9*Ci] (f32)."""
    n, ci, hw = xf.shape
    co = dyf.shape[1]
    t = _tile(hw)
    groups = hw // t
    xp = _pad_rows(xf, w)
    kernel = partial(_wgrad_kernel, w=w, t=t, ci=ci, groups=groups)
    return pl.pallas_call(
        kernel,
        grid=(n, groups),
        in_specs=[
            pl.BlockSpec((1, ci, xp.shape[-1]), lambda i, g: (i, 0, 0)),
            pl.BlockSpec((1, co, t), lambda i, g: (i, 0, g)),
        ],
        out_specs=pl.BlockSpec((co, 9 * ci), lambda i, g: (0, 0)),
        out_shape=_sds((co, 9 * ci), jnp.float32, xf),
        scratch_shapes=[
            pltpu.VMEM((9 * ci, t), xf.dtype),
            pltpu.VMEM((co, 9 * ci), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp, dyf)


def _xla_conv_nchw(xf, w, h, w_):
    """Numerics reference / fallback: plain XLA conv on the lanes layout."""
    n, ci, hw = xf.shape
    x4 = xf.reshape(n, ci, h, w_)
    y4 = jax.lax.conv_general_dilated(
        x4, w, (1, 1), "SAME",
        dimension_numbers=("NCHW", "HWIO", "NCHW"))
    return y4.astype(xf.dtype).reshape(n, w.shape[-1], hw)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv3x3_lanes(xf: jnp.ndarray, w: jnp.ndarray, h: int, w_: int):
    """SAME-padded stride-1 3x3 conv in lanes layout.

    xf: [N, C_in, H*W]  (pixels in the trailing/lane dim)
    w:  [3, 3, C_in, C_out]  (flax HWIO kernel)
    returns [N, C_out, H*W].
    """
    return _conv_fwd(xf, _w2(w).astype(xf.dtype), h, w_)


def _vjp_fwd(xf, w, h, w_):
    y = _conv_fwd(xf, _w2(w).astype(xf.dtype), h, w_)
    return y, (xf, w)


def _vjp_bwd(h, w_, res, dyf):
    xf, w = res
    # dX: SAME conv of dY with the spatially-flipped, channel-transposed
    # kernel (exact transpose of stride-1 SAME 3x3).
    wt = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2)
    dx = _conv_fwd(dyf, _w2(wt).astype(dyf.dtype), h, w_)
    ci, co = w.shape[2], w.shape[3]
    dw2 = _conv_wgrad(xf, dyf, h, w_)
    dw = _w2_inv(dw2, ci, co).astype(w.dtype)
    return dx, dw


conv3x3_lanes.defvjp(_vjp_fwd, _vjp_bwd)


def subsample2(xf: jnp.ndarray, h: int, w: int, offset: int = 0) -> jnp.ndarray:
    """Stride-2 spatial subsample in lanes layout: [N,C,H*W] -> [N,C,HW/4].

    ``offset=1`` (with the stride-1 3x3 kernel) reproduces XLA's SAME
    stride-2 semantics for even H/W: SAME s2 pads (0,1), so its windows
    are centered at 2i+1 — the ODD positions of the stride-1 output.
    1x1 stride-2 convs keep offset=0 (their SAME windows sit at 2i)."""
    assert h % 2 == 0 and w % 2 == 0, "stride-2 lanes path needs even H/W"
    n, c, _ = xf.shape
    return (xf.reshape(n, c, h, w)[:, :, offset::2, offset::2]
            .reshape(n, c, (h // 2) * (w // 2)))


def to_lanes(x_nhwc: jnp.ndarray) -> jnp.ndarray:
    n, h, w, c = x_nhwc.shape
    return x_nhwc.transpose(0, 3, 1, 2).reshape(n, c, h * w)


def from_lanes(xf: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    n, c, _ = xf.shape
    return xf.reshape(n, c, h, w).transpose(0, 2, 3, 1)


# flax module: class is literally named Conv so flax auto-naming produces
# the same 'Conv_k' parameter paths as nn.Conv — conv_impl='lanes' models
# share their parameter pytree with the standard NHWC models bit-for-bit.
class Conv(nn.Module):
    """Drop-in for ``nn.Conv(features, (k,k), strides, 'SAME',
    use_bias=False)`` operating in lanes layout [N, C, H*W].

    kernel_size 3 runs the Pallas spatial-in-lanes kernel (stride 2 =
    stride-1 kernel + subsample); kernel_size 1 is a plain einsum whose
    GEMM already has pixels in lanes. Parameter name/shape match nn.Conv
    ('kernel', [k,k,Ci,Co], f32)."""

    features: int
    hw: Tuple[int, int]
    kernel_size: int = 3
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xf):
        h, w_ = self.hw
        ci = xf.shape[1]
        k = self.kernel_size
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (k, k, ci, self.features), jnp.float32)
        xf = xf.astype(self.dtype)
        kd = kernel.astype(self.dtype)
        if k == 1:
            if self.strides == 2:
                xf, h, w_ = subsample2(xf, h, w_), h // 2, w_ // 2
            return jnp.einsum("io,nip->nop", kd[0, 0], xf)
        if not supported(ci, h, w_):
            y = _xla_conv_nchw(xf, kd, h, w_)
        else:
            y = conv3x3_lanes(xf, kd, h, w_)
        if self.strides == 2:
            y = subsample2(y, h, w_, offset=1)
        return y
