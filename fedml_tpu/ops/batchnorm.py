"""Fused train-mode BatchNorm(+ReLU) as a Pallas TPU kernel.

Motivation (docs/mfu_experiments.md H2): at the flagship's widths the round
program is VPU/HBM-bound, and removing BatchNorm entirely measures +18%
throughput. XLA lowers train-mode BN to a stats reduction pass plus a
normalize pass (plus their backward), each streaming the activation through
HBM. This kernel performs BOTH passes per invocation with the activation
resident in VMEM between them — phase 0 of a two-phase sequential grid
accumulates the batch statistics, phase 1 normalizes (+ReLU) and writes —
and its backward fuses the three reductions (dbeta, dgamma, the dx
projection terms) with the dx elementwise pass the same way.

Numerics match flax ``nn.BatchNorm(use_running_average=False)``: biased
variance over all leading axes, f32 statistics, scale/bias applied in f32,
output cast back to the input dtype.

The custom_vjp wrapper makes it a drop-in for the train path; models opt in
via ``bn_impl='pallas'`` (models/resnet.py) so the A/B against the XLA
lowering is one flag (measured results: docs/mfu_experiments.md H2-pallas).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fedml_tpu.ops.common import interpret as _interpret
from fedml_tpu.ops.common import sds as _sds


def _fwd_kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, rstd_ref,
                acc_ref, *, n_rows: float, eps: float, relu: bool,
                groups: int):
    """``groups`` row-groups are folded into the lane dim (x blocks are
    [chunk, groups*C]) so narrow channel counts still fill the VPU's 128
    lanes; statistics combine the groups per channel."""
    phase = pl.program_id(0)
    chunk = pl.program_id(1)
    n_chunks = pl.num_programs(1)
    C = mean_ref.shape[-1]

    @pl.when((phase == 0) & (chunk == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(phase == 0)
    def _accumulate():
        x = x_ref[...].astype(jnp.float32)
        acc_ref[0, :] += jnp.sum(x, axis=0)
        acc_ref[1, :] += jnp.sum(x * x, axis=0)

    @pl.when((phase == 0) & (chunk == n_chunks - 1))
    def _stats():
        # combine the row-groups per channel with static slices (Mosaic has
        # no general vector reshape)
        s = acc_ref[0, 0:C]
        ss = acc_ref[1, 0:C]
        for g in range(1, groups):
            s = s + acc_ref[0, g * C:(g + 1) * C]
            ss = ss + acc_ref[1, g * C:(g + 1) * C]
        mean = s / n_rows
        var = ss / n_rows - mean * mean
        rstd = jax.lax.rsqrt(jnp.maximum(var, 0.0) + eps)
        acc_ref[0, :] = jnp.concatenate([mean] * groups) if groups > 1 else mean
        acc_ref[1, :] = jnp.concatenate([rstd] * groups) if groups > 1 else rstd
        mean_ref[0, :] = mean
        rstd_ref[0, :] = rstd

    @pl.when(phase == 1)
    def _normalize():
        x = x_ref[...].astype(jnp.float32)
        mean = acc_ref[0, :]
        rstd = acc_ref[1, :]
        y = (x - mean) * rstd * gamma_ref[0, :].astype(jnp.float32) \
            + beta_ref[0, :].astype(jnp.float32)
        if relu:
            y = jnp.maximum(y, 0.0)
        y_ref[...] = y.astype(y_ref.dtype)


def _bwd_kernel(x_ref, y_ref, dy_ref, gamma_ref, mean_ref, rstd_ref,
                dx_ref, dgamma_ref, dbeta_ref, acc_ref,
                *, n_rows: float, relu: bool, groups: int):
    """Inputs gamma/mean/rstd arrive pre-tiled to [1, groups*C]; the
    per-channel dgamma/dbeta outputs are [1, C]."""
    phase = pl.program_id(0)
    chunk = pl.program_id(1)
    n_chunks = pl.num_programs(1)
    C = dgamma_ref.shape[-1]

    @pl.when((phase == 0) & (chunk == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(phase == 0)
    def _reduce():
        dy = dy_ref[...].astype(jnp.float32)
        if relu:
            dy = dy * (y_ref[...].astype(jnp.float32) > 0.0)
        xhat = (x_ref[...].astype(jnp.float32) - mean_ref[0, :]) * rstd_ref[0, :]
        acc_ref[0, :] += jnp.sum(dy, axis=0)          # dbeta (per group-lane)
        acc_ref[1, :] += jnp.sum(dy * xhat, axis=0)   # dgamma (per group-lane)

    @pl.when((phase == 0) & (chunk == n_chunks - 1))
    def _finish_reduce():
        dbeta = acc_ref[0, 0:C]
        dgamma = acc_ref[1, 0:C]
        for g in range(1, groups):
            dbeta = dbeta + acc_ref[0, g * C:(g + 1) * C]
            dgamma = dgamma + acc_ref[1, g * C:(g + 1) * C]
        dbeta_ref[0, :] = dbeta
        dgamma_ref[0, :] = dgamma
        acc_ref[0, :] = jnp.concatenate([dbeta] * groups) if groups > 1 else dbeta
        acc_ref[1, :] = jnp.concatenate([dgamma] * groups) if groups > 1 else dgamma

    @pl.when(phase == 1)
    def _dx():
        dy = dy_ref[...].astype(jnp.float32)
        if relu:
            dy = dy * (y_ref[...].astype(jnp.float32) > 0.0)
        xhat = (x_ref[...].astype(jnp.float32) - mean_ref[0, :]) * rstd_ref[0, :]
        g = gamma_ref[0, :].astype(jnp.float32)
        dbeta = acc_ref[0, :]
        dgamma = acc_ref[1, :]
        dx = (g * rstd_ref[0, :]) * (dy - dbeta / n_rows - xhat * dgamma / n_rows)
        dx_ref[...] = dx.astype(dx_ref.dtype)


def _chunk_for(n: int):
    """Largest supported row chunk dividing n (None -> XLA fallback)."""
    for c in (2048, 1024, 512, 256, 128):
        if n % c == 0:
            return c
    return None


def _xla_bn_relu(xf, gamma, beta, eps, relu):
    """Plain-XLA body used when the row count doesn't tile; also the
    numerics reference the kernel is tested against."""
    x32 = xf.astype(jnp.float32)
    mean = x32.mean(axis=0)
    var = ((x32 - mean) ** 2).mean(axis=0)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x32 - mean) * rstd * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(xf.dtype), mean, rstd


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_bn_relu(x, gamma, beta, eps: float = 1e-5, relu: bool = True):
    """Train-mode BN(+ReLU) over all leading axes of ``x`` (channels last).

    Returns ``(y, mean, var)`` — mean/var are the BIASED batch statistics
    (what flax BN uses for both normalization and running-stat updates).
    """
    y, mean, rstd, _ = _fwd(x, gamma, beta, eps, relu)
    var = (1.0 / (rstd * rstd)) - eps
    return y, mean, var


def _fwd(x, gamma, beta, eps, relu):
    orig_shape = x.shape
    C = orig_shape[-1]
    n = int(np.prod(orig_shape[:-1]))
    # fold G row-groups into the lane dim so narrow C still fills the VPU's
    # 128 lanes ([n, C] -> [n/G, G*C]); stats recombine per channel in-kernel
    G = max(1, 128 // C)
    while G > 1 and n % G:
        G //= 2
    rows = n // G
    Ce = G * C
    xf = x.reshape(rows, Ce)
    chunk = _chunk_for(rows)
    if chunk is None:
        y, mean, rstd = _xla_bn_relu(x.reshape(n, C), gamma, beta, eps, relu)
        return (y.reshape(orig_shape), mean, rstd,
                (x.reshape(n, C), gamma, mean, rstd, y, 1, None))
    n_chunks = rows // chunk

    kernel = partial(_fwd_kernel, n_rows=float(n), eps=float(eps), relu=relu,
                     groups=G)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(2, n_chunks),
        in_specs=[
            pl.BlockSpec((chunk, Ce), lambda p, i: (i, 0)),
            pl.BlockSpec((1, Ce), lambda p, i: (0, 0)),
            pl.BlockSpec((1, Ce), lambda p, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((chunk, Ce), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_shape=[
            _sds((rows, Ce), x.dtype, xf),
            _sds((1, C), jnp.float32, xf),
            _sds((1, C), jnp.float32, xf),
        ],
        scratch_shapes=[pltpu.VMEM((2, Ce), jnp.float32)],
        interpret=_interpret(),
    )(xf, jnp.tile(gamma, G).reshape(1, Ce), jnp.tile(beta, G).reshape(1, Ce))
    return (y.reshape(orig_shape), mean.reshape(C), rstd.reshape(C),
            (xf, gamma, mean.reshape(C), rstd.reshape(C), y, G, chunk))


def _fused_fwd(x, gamma, beta, eps, relu):
    y, mean, rstd, res = _fwd(x, gamma, beta, eps, relu)
    var = (1.0 / (rstd * rstd)) - eps
    return (y, mean, var), res


def _fused_bwd(eps, relu, res, cts):
    dy_full, _dmean, _dvar = cts   # stats gradients are not propagated
    # ``chunk`` is the forward's own tiling decision (None = XLA fallback,
    # which stores G=1 and [n, C] residuals) — recorded rather than
    # re-derived so the two passes cannot disagree (advisor r4 #2).
    xf, gamma, mean, rstd, y, G, chunk = res
    rows, Ce = xf.shape
    C = gamma.shape[-1]
    n = rows * G
    orig_shape = dy_full.shape
    dyf = dy_full.reshape(rows, Ce)
    if chunk is None:   # fwd used the XLA fallback
        dy = dyf.astype(jnp.float32)
        if relu:
            dy = dy * (y.astype(jnp.float32) > 0.0)
        xhat = (xf.astype(jnp.float32) - mean) * rstd
        dbeta = dy.sum(axis=0)
        dgamma = (dy * xhat).sum(axis=0)
        dx = (gamma.astype(jnp.float32) * rstd) * (
            dy - dbeta / n - xhat * dgamma / n)
        return (dx.astype(dy_full.dtype).reshape(orig_shape),
                dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))
    n_chunks = rows // chunk

    kernel = partial(_bwd_kernel, n_rows=float(n), relu=relu, groups=G)
    dx, dgamma, dbeta = pl.pallas_call(
        kernel,
        grid=(2, n_chunks),
        in_specs=[
            pl.BlockSpec((chunk, Ce), lambda p, i: (i, 0)),
            pl.BlockSpec((chunk, Ce), lambda p, i: (i, 0)),
            pl.BlockSpec((chunk, Ce), lambda p, i: (i, 0)),
            pl.BlockSpec((1, Ce), lambda p, i: (0, 0)),
            pl.BlockSpec((1, Ce), lambda p, i: (0, 0)),
            pl.BlockSpec((1, Ce), lambda p, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((chunk, Ce), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_shape=[
            _sds((rows, Ce), dy_full.dtype, dyf),
            _sds((1, C), jnp.float32, dyf),
            _sds((1, C), jnp.float32, dyf),
        ],
        scratch_shapes=[pltpu.VMEM((2, Ce), jnp.float32)],
        interpret=_interpret(),
    )(xf, y.reshape(rows, Ce), dyf, jnp.tile(gamma, G).reshape(1, Ce),
      jnp.tile(mean, G).reshape(1, Ce), jnp.tile(rstd, G).reshape(1, Ce))
    return (dx.reshape(orig_shape),
            dgamma.reshape(gamma.shape).astype(gamma.dtype),
            dbeta.reshape(gamma.shape).astype(gamma.dtype))


fused_bn_relu.defvjp(_fused_fwd, _fused_bwd)
