"""TPU kernel ops (Pallas) with XLA fallbacks.

The reference has no custom kernels (SURVEY.md §2: 100% Python/torch); its
hot loop is eager per-batch SGD. Here the hot ops get TPU-native fused
implementations:

- :mod:`fedml_tpu.ops.attention` — blockwise (flash) attention: online
  softmax over K/V blocks, MXU-shaped matmuls, partial (o, m, l) outputs so
  sequence-parallel ring attention can merge chunks across devices.
- :mod:`fedml_tpu.ops.xent` — fused masked softmax cross-entropy over large
  vocabularies without materializing log-softmax in HBM.

Every op has an ``impl`` switch: ``'pallas'`` (TPU kernel), ``'xla'``
(pure-jnp, fuses well enough on any backend), ``'auto'`` (pallas on TPU,
xla elsewhere). Tests run both paths and assert parity.
"""

from fedml_tpu.ops.attention import (  # noqa: F401
    attention,
    attention_block_partial,
    merge_partials,
    normalize_partial,
)
from fedml_tpu.ops.xent import masked_cross_entropy  # noqa: F401
