"""Shared helpers for the Pallas TPU kernels in this package."""

from __future__ import annotations

import jax


def sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-manual-axes of ``like`` — under
    shard_map (the cross-silo mesh round) pallas outputs must declare how
    they vary across the mesh; outside shard_map vma is empty and harmless.
    The try/except shims over JAX versions without the ``vma`` kwarg."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(like).vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)


def interpret() -> bool:
    """Pallas TPU kernels run in interpret mode on CPU backends (unit
    tests / virtual meshes); compiled on real TPUs."""
    return jax.default_backend() != "tpu"
