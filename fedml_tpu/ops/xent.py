"""Fused masked softmax cross-entropy.

Counterpart of the per-trainer loss code in the reference
(my_model_trainer_classification.py:19-53 uses ``nn.CrossEntropyLoss``
eagerly per batch). On TPU the large-vocab case (stackoverflow NWP, 10k+
vocab; transformer LM heads) wants the log-softmax fused with the gold-label
gather so the [N, V] probabilities never round-trip HBM: one pass computes
rowmax, logsumexp and the label logit per 2-D tile.

``impl='xla'`` is the jnp reference (classification losses in
fedml_tpu/core/tasks.py use the same math); ``'pallas'`` is the TPU kernel;
``'auto'`` picks by backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from fedml_tpu.ops.attention import _pick_impl


def _xla_xent(logits, labels):
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(logz, labels[..., None].astype(jnp.int32), axis=-1)
    return -gold[..., 0]


def _xent_kernel(logits_ref, labels_ref, out_ref, *, block_n: int, block_v: int):
    """One grid point handles block_n rows; V is streamed in block_v slices
    with a running (rowmax, sum-exp, gold-logit) triple."""
    import jax.experimental.pallas as pl

    v_total = logits_ref.shape[1]
    nv = v_total // block_v
    labels = labels_ref[0].reshape(block_n, 1)

    m0 = jnp.full((block_n, 1), -1e30, jnp.float32)
    s0 = jnp.zeros((block_n, 1), jnp.float32)
    g0 = jnp.zeros((block_n, 1), jnp.float32)

    def body(i, carry):
        m, s, g = carry
        blk = logits_ref[pl.ds(0, block_n), pl.ds(i * block_v, block_v)]
        blk = blk.astype(jnp.float32)
        m_new = jnp.maximum(m, jnp.max(blk, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        s = s * alpha + jnp.sum(jnp.exp(blk - m_new), axis=-1, keepdims=True)
        vids = i * block_v + jax.lax.broadcasted_iota(
            jnp.int32, (block_n, block_v), 1)
        hit = (vids == labels).astype(jnp.float32)
        g = g + jnp.sum(blk * hit, axis=-1, keepdims=True)
        return m_new, s, g

    m, s, g = jax.lax.fori_loop(0, nv, body, (m0, s0, g0))
    loss = m + jnp.log(s) - g                                # [bn, 1]
    out_ref[0] = jnp.broadcast_to(loss, (block_n, 128))


def _pallas_xent(logits, labels, block_n: int, block_v: int, interpret: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, v = logits.shape
    bn = min(block_n, n)
    while n % bn:
        bn //= 2
    # Keep the vocab block wide regardless of V's factorization (a 10004
    # vocab must not collapse the block to 4 lanes): pad V up to a block
    # multiple with -1e30 columns — exp(-1e30 - m) == 0, so padding columns
    # never perturb the running (max, sumexp) and labels never hit them.
    bv = min(block_v, -(-v // 128) * 128)
    v_pad = -(-v // bv) * bv
    if v_pad != v:
        logits = jnp.pad(logits, ((0, 0), (0, v_pad - v)),
                         constant_values=-1e30)
    v = v_pad

    out = pl.pallas_call(
        functools.partial(_xent_kernel, block_n=bn, block_v=bv),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, v), lambda i: (i, 0), memory_space=pltpu.VMEM),
            # labels ride as [n/bn, 1, bn] so the block's trailing dims
            # (1, bn) EQUAL the array's — TPU lowering requires trailing
            # block dims divisible by (8, 128) or exactly the array dims
            # (a (1, bn) block over a [n/bn, bn] array fails that check;
            # interpret mode never enforces it)
            pl.BlockSpec((1, 1, bn), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bn, 128), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n // bn, bn, 128), jnp.float32),
        interpret=interpret,
    )(logits, labels.astype(jnp.int32).reshape(n // bn, 1, bn))
    return out[..., 0].reshape(n)


@functools.lru_cache(maxsize=None)
def _xent_with_vjp(impl: str, block_n: int, block_v: int, interpret: bool):
    """CE with custom VJP. Backward is the closed form
    ``d loss_i / d logits = softmax(logits_i) - onehot(label_i)`` — no
    recompute of the forward reduction. Labels travel as float32 so
    custom_vjp hands back an ordinary zero cotangent."""

    @jax.custom_vjp
    def f(logits, labels_f):
        labels = labels_f.astype(jnp.int32)
        if impl == "xla":
            return _xla_xent(logits, labels)
        return _pallas_xent(logits, labels, block_n, block_v, interpret)

    def fwd(logits, labels_f):
        return f(logits, labels_f), (logits, labels_f)

    def bwd(res, ct):
        logits, labels_f = res
        labels = labels_f.astype(jnp.int32)
        p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
        dlogits = (ct[..., None] * (p - onehot)).astype(logits.dtype)
        return dlogits, jnp.zeros_like(labels_f)

    f.defvjp(fwd, bwd)
    return f


def masked_cross_entropy(
    logits: jax.Array, labels: jax.Array, mask=None, *,
    impl: str = "auto", block_n: int = 64, block_v: int = 2048,
    interpret: bool = False,
) -> jax.Array:
    """Per-example CE loss ``[...,]`` fp32; masked entries are zeroed.

    ``logits [..., V]``, integer ``labels [...]``, optional ``mask [...]``.
    Differentiable w.r.t. ``logits`` (closed-form custom VJP).
    """
    shape = labels.shape
    v = logits.shape[-1]
    flat_logits = logits.reshape(-1, v)
    flat_labels = labels.reshape(-1)
    f = _xent_with_vjp(_pick_impl(impl), block_n, block_v, interpret)
    per = f(flat_logits, flat_labels.astype(jnp.float32))
    per = per.reshape(shape)
    if mask is not None:
        per = per * mask.astype(per.dtype)
    return per
