"""fedml_tpu — a TPU-native federated learning framework.

A from-scratch reimplementation of the capabilities of FedML
(reference: /root/reference, arXiv:2007.13518) designed for TPU hardware:

- models are pure-functional flax modules (pytrees of params instead of
  ``nn.Module.state_dict()``),
- per-client local training is a jit-compiled ``lax.scan`` over batches
  instead of a Python epoch/batch loop,
- the standalone simulator runs clients with ``vmap`` on one chip,
- the cross-silo distributed paradigm shards clients over a
  ``jax.sharding.Mesh`` with ``shard_map`` and aggregates with a weighted
  ``psum`` over ICI, replacing the reference's MPI/gRPC/MQTT state-dict
  message passing (reference fedml_core/distributed/communication/),
- a Message/Observer gRPC edge transport is kept only for genuinely
  off-pod (mobile / external silo) clients.

Layer map (mirrors SURVEY.md §1):

    experiments/   entry points (argparse mains, --ci fast path)
    algorithms/    FL algorithm zoo (FedAvg .. FedNAS)
    models/ data/  model zoo + federated data layer
    parallel/      mesh, sim (vmap), cross-silo (shard_map) paradigms
    distributed/   node runtimes + topology (edge federation)
    comm/          Message, Observer, backends (in-proc, gRPC, MQTT)
    core/          pytree aggregation, partitioners, config, serialization
"""

__version__ = "0.1.0"

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.6 ships shard_map under experimental; the codebase (and its
    # tests) import the stable ``jax.shard_map`` spelling everywhere, so
    # alias it once here — every module imports this package first. The
    # experimental version spells today's check_vma kwarg check_rep, so the
    # alias must translate or every check_vma=False call site TypeErrors.
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def _shard_map_compat(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "pcast"):
    # jax < 0.7 has no varying/replicated cast op: its shard_map tracks
    # replication itself, so marking a value "varying" is the identity there
    def _pcast(x, axis_name=None, to=None):  # noqa: ARG001 - newer-jax sig
        return x

    _jax.lax.pcast = _pcast

from fedml_tpu.core import aggregation, partition, pytree  # noqa: F401
