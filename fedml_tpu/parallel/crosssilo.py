"""Cross-silo distributed FedAvg: one (or more) clients per device over a
Mesh, aggregation by weighted psum on ICI.

This replaces the reference's entire distributed stack for in-datacenter
runs — the rank-0 Aggregator + ServerManager / rank-i Trainer + ClientManager
star protocol with pickled state dicts over MPI (SURVEY.md §3.2,
FedAvgAPI.py:20-28, FedAVGAggregator.py:58-87, com_manager.py:71-93). One
``shard_map``-ped jit program per round:

    device d: vmap(local_train) over its clients -> weighted partial sums
    all-reduce: psum(sum_i w_i * params_i) / psum(sum_i w_i)

No server rank, no message passing, no 0.3 s poll loops; the collective IS
the aggregation. Multi-host pods run the same code under jax.distributed.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from fedml_tpu.core.rng import server_key
from fedml_tpu.parallel.local import LocalResult


def weighted_psum_tree_mean(tree, w, axis, denom):
    """The one weighted-mean-by-all-reduce used by every mesh aggregation:
    per-leaf ``psum_over(axis)(sum_i w_i * x_i) / denom`` with f32
    accumulation and a cast back to the leaf dtype. ``denom`` must already
    be the psum'd total weight (epsilon-guarded by the caller) so callers
    with different reduction scopes (global vs per-group) share this one
    numerically sensitive body."""

    def reduce_leaf(x):
        wb = w.reshape((-1,) + (1,) * (x.ndim - 1))
        s = jax.lax.psum(jnp.sum(x.astype(jnp.float32) * wb, axis=0), axis)
        return (s / denom).astype(x.dtype)

    return jax.tree.map(reduce_leaf, tree)


def make_crosssilo_round(
    local_train: Callable,
    mesh: Mesh,
    axis: str = "clients",
    client_transform: Callable | None = None,
    reduce_extras: Callable | None = None,
    server_update: Callable | None = None,
    lens: bool = False,
):
    """Build the jitted cross-silo round function.

    The three hooks are how the whole algorithm zoo runs on the mesh path —
    the reference deploys each algorithm as its own Aggregator subclass over
    MPI (FedOptAggregator.py:70-120, FedAvgRobustAggregator.py:14-60); here
    an algorithm is (per-client transform, extra reductions, post-collective
    server transform) around the one weighted-psum program:

      client_transform(global_vars, stacked_vars) -> stacked_vars
        per-device, applied to the locally-trained client variables BEFORE
        the psum (AGC / norm clipping of updates).
      reduce_extras(global_vars, res, w) -> pytree of f32 partial SUMS
        per-device weighted partial sums that ride the same all-reduce as
        the parameters (FedNova's normalized-update sums); psum'd leafwise.
      server_update(vars0, agg, extras, total, server_state, rng)
        -> (new_vars, new_server_state)
        applied identically on every device AFTER the psum, on replicated
        values only (FedOpt server optimizer, weak-DP noise). ``extras`` is
        the psum of reduce_extras (or None), ``total`` the psum'd weight.

    Args:
      local_train: per-client function from make_local_train_fn.
      mesh: 1-D mesh with ``axis``.

    Returns round_fn(variables, server_state, cx, cy, cm, counts, keys, rng)
    -> (variables, server_state, loss) where cx/cy/cm/counts/keys are stacked
    over sampled clients (leading axis divisible by mesh size) and variables /
    server_state / rng are replicated.
    """

    finish = _make_mesh_finish(axis, client_transform, reduce_extras,
                               server_update, lens=lens)

    def shard_fn(variables, server_state, cx, cy, cm, counts, keys, rng):
        variables0 = variables  # replicated original (all-failed fallback)
        # Mark the replicated global weights as device-varying before local
        # training. Without this, JAX's varying-manual-axes autodiff treats
        # the loss as a GLOBAL objective and auto-psums the gradient across
        # devices — every client would train on the sum of all gradients.
        variables = jax.tree.map(
            lambda x: jax.lax.pcast(x, axis_name=axis, to="varying"), variables
        )
        res: LocalResult = jax.vmap(local_train, in_axes=(None, 0, 0, 0, 0, 0))(
            variables, cx, cy, cm, counts, keys
        )
        return finish(variables0, variables, server_state, res, counts, rng)

    out_specs = ((P(), P(), P(), P(axis)) if lens else (P(), P(), P()))
    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=out_specs,
    )
    return jax.jit(mapped)


def _make_mesh_finish(axis, client_transform, reduce_extras, server_update,
                      lens: bool = False):
    """The shared post-local-training tail of a mesh round: per-client hook →
    weighted psum mean → extra reductions → loss → server hook → elastic
    all-failed rollback. One definition so the plain and grouped round
    programs cannot drift (``variables`` is the pcast device-varying copy the
    local training consumed; ``variables0`` the replicated original)."""

    def finish(variables0, variables, server_state, res: LocalResult, counts, rng):
        stacked = res.variables
        if client_transform is not None:
            stacked = client_transform(variables, stacked)
        w = counts.astype(jnp.float32)
        total = jax.lax.psum(jnp.sum(w), axis)
        denom = jnp.maximum(total, 1e-12)
        agg = weighted_psum_tree_mean(stacked, w, axis, denom)
        extras = None
        if reduce_extras is not None:
            extras = jax.tree.map(
                lambda x: jax.lax.psum(x, axis),
                reduce_extras(variables, res, w),
            )
        loss = jax.lax.psum(jnp.sum(res.train_loss * w), axis) / denom
        new_vars, new_state = apply_server_and_rollback(
            variables0, agg, extras, total, server_state, rng, server_update)
        if lens:
            # fedlens on the mesh: per-shard norms/dots against the GLOBAL
            # raw weighted-mean update (its own f32 psum — the agg above is
            # post-client_transform and dtype-cast, deliberately not reused
            # so robust clipping can't hide an attacker and the alignment
            # definition matches obs/lens.stacked_lens bit-for-bit in sim).
            # Output-only: nothing below feeds new_vars/new_state, so an
            # armed program aggregates bit-identically.
            f32 = jnp.float32
            upd = jax.tree.leaves(jax.tree.map(
                lambda s, v: s.astype(f32) - v.astype(f32)[None],
                res.variables["params"], variables0["params"]))
            n = upd[0].shape[0]
            flat = [u.reshape((n, -1)) for u in upd]
            n2 = sum(jnp.sum(u * u, axis=1) for u in flat)
            wb = w.reshape((-1, 1)).astype(f32)
            mean = [jax.lax.psum(jnp.sum(u * wb, axis=0), axis) / denom
                    for u in flat]
            m2 = sum(jnp.sum(m * m) for m in mean)
            dots = sum(u @ m for u, m in zip(flat, mean))
            norm = jnp.sqrt(n2)
            ldict = {"update_norm": norm,
                     "align": dots / jnp.maximum(norm * jnp.sqrt(m2), 1e-12)}
            first = getattr(res, "first_loss", None)
            if first is not None:
                ldict["loss_delta"] = (first.astype(f32)
                                       - res.train_loss.astype(f32))
            return new_vars, new_state, loss, ldict
        return new_vars, new_state, loss

    return finish


def apply_server_and_rollback(variables0, agg, extras, total, server_state,
                              rng, server_update):
    """The ONE post-aggregation tail every non-vmap round shares — the
    mesh rounds (plain, grouped, and packed — parallel/packed.py) AND,
    since packed-everywhere, the packed SIMULATION round
    (FedAvgAPI.build_round_step_packed), which passes already-summed
    (psum-free) values: the server hook on replicated values with the
    round's server key, then the elastic all-failed rollback. Zero-count clients (failed/dropped, counts*live=0)
    contribute nothing to ``agg``; if EVERY client failed the round is a
    full no-op — weights AND server state roll back (matching the
    simulation paradigm's _finish_round guard), else the server optimizer
    would absorb the garbage zero-aggregate pseudo-gradient."""
    if server_update is not None:
        new_vars, new_state = server_update(
            variables0, agg, extras, total, server_state, server_key(rng)
        )
    else:
        new_vars, new_state = agg, server_state
    keep = total > 0
    new_vars = jax.tree.map(lambda n, o: jnp.where(keep, n, o), new_vars, variables0)
    new_state = jax.tree.map(lambda n, o: jnp.where(keep, n, o), new_state, server_state)
    return new_vars, new_state


def make_crosssilo_round_grouped(
    local_train: Callable,
    mesh: Mesh,
    n_groups: int,
    axis: str = "clients",
    client_transform: Callable | None = None,
    reduce_extras: Callable | None = None,
    server_update: Callable | None = None,
):
    """Grouped cross-silo round: the mesh counterpart of the simulation
    paradigm's ``bucket_groups`` schedule (algorithms/fedavg.py
    build_round_step_gather_groups). Clients are dealt to devices so that
    every device's group ``g`` shares ONE static scan length (see
    CrossSiloFedAvgAPI._mesh_group_plan); the round program then runs one
    vmapped local-training scan per group — small clients stop burning the
    biggest client's masked padding steps — and ONE psum tail aggregates all
    groups together. SPMD-safe by construction: group sizes and scan lengths
    are trace-time constants identical on every device.

    Returns round_fn(variables, server_state, groups, counts, keys, rng)
    -> (variables, server_state, loss) where ``groups`` is a tuple over g of
    (cx, cy, cm) stacked [n_g, len_g, ...] sharded along ``axis`` (len_g is
    the group's truncated record axis), ``counts``/``keys`` matching tuples
    of [n_g] arrays, and variables/server_state/rng are replicated.
    """
    finish = _make_mesh_finish(axis, client_transform, reduce_extras, server_update)

    def shard_fn(variables, server_state, groups, counts, keys, rng):
        variables0 = variables
        variables = jax.tree.map(
            lambda x: jax.lax.pcast(x, axis_name=axis, to="varying"), variables
        )
        parts = [
            jax.vmap(local_train, in_axes=(None, 0, 0, 0, 0, 0))(
                variables, cx, cy, cm, cnt, k
            )
            for (cx, cy, cm), cnt, k in zip(groups, counts, keys)
        ]
        # group order is irrelevant to the weighted mean; concatenate the
        # per-group cohorts back into one stacked axis for the shared tail
        res = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        counts_all = jnp.concatenate(counts, axis=0)
        return finish(variables0, variables, server_state, res, counts_all, rng)

    g_spec = tuple((P(axis), P(axis), P(axis)) for _ in range(n_groups))
    v_spec = tuple(P(axis) for _ in range(n_groups))
    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(), g_spec, v_spec, v_spec, P()),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(mapped)


def make_hierarchical_round(
    local_train: Callable,
    mesh: Mesh,
    group_rounds: int = 1,
    group_axis: str = "group",
    client_axis: str = "clients",
):
    """Two-tier aggregation on a 2-D ('group', 'clients') mesh — the
    distributed form of hierarchical FL (SURVEY.md §2.6.5, reference
    hierarchical_fl/trainer.py:43-69 runs it as nested Python loops over
    processes).

    Topology mapping: the ``clients`` axis should be ICI-adjacent (within a
    pod slice) because the group aggregation psums over it every group
    round; the ``group`` axis can ride DCN across slices because it is
    reduced ONCE per global round. Each device holds a stack of its group's
    clients; semantics match HierarchicalFedAvgAPI with grouping
    gid = mesh row (see tests).

    Returns round_fn(variables, cx, cy, cm, counts, keys) -> (vars, loss)
    where cx/cy/cm/counts are stacked [G, C/G, ...] sharded over both axes
    and keys is [group_rounds, G, C/G] per-client PRNG keys (same sharding
    on its trailing two axes), so every client's randomness is independent.
    """

    def shard_fn(variables, cx, cy, cm, counts, keys):
        # local shards arrive [1, c_local, ...] — flatten the group dim
        cx, cy, cm = (a.reshape((-1,) + a.shape[2:]) for a in (cx, cy, cm))
        counts = counts.reshape((-1,))
        keys = keys.reshape((keys.shape[0], -1))          # [rounds, c_local]
        variables0 = variables
        variables = jax.tree.map(
            lambda x: jax.lax.pcast(x, axis_name=(group_axis, client_axis),
                                    to="varying"), variables
        )
        w = counts.astype(jnp.float32)
        gmass = jax.lax.psum(jnp.sum(w), client_axis)     # this group's mass
        gden = jnp.maximum(gmass, 1e-12)

        def one_group_round(gvars, keys_local):
            res: LocalResult = jax.vmap(local_train, in_axes=(None, 0, 0, 0, 0, 0))(
                gvars, cx, cy, cm, counts, keys_local
            )
            # reduce over the client axis only: ICI within the group
            gvars = weighted_psum_tree_mean(res.variables, w, client_axis, gden)
            loss = jax.lax.psum(jnp.sum(res.train_loss * w), client_axis) / gden
            return gvars, loss

        gvars, losses = jax.lax.scan(one_group_round, variables, keys)
        # global: group models weighted by group mass — one reduce over the
        # group axis (DCN on a real pod)
        total = jax.lax.psum(gmass, group_axis)
        keep = total > 0

        def global_leaf(x):
            s = jax.lax.psum(x.astype(jnp.float32) * gmass, group_axis)
            return (s / jnp.maximum(total, 1e-12)).astype(x.dtype)

        new_vars = jax.tree.map(global_leaf, gvars)
        new_vars = jax.tree.map(lambda n, o: jnp.where(keep, n, o),
                                new_vars, variables0)
        loss = jax.lax.psum(losses[-1] * gmass, group_axis) / jnp.maximum(total, 1e-12)
        return new_vars, loss

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(group_axis, client_axis), P(group_axis, client_axis),
                  P(group_axis, client_axis), P(group_axis, client_axis),
                  P(None, group_axis, client_axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped)


def place_round_inputs(mesh: Mesh, variables, cx, cy, cm, counts, keys, axis="clients"):
    """Device placement for one round: variables replicated, client-stacked
    arrays sharded along the client axis (the round's single host->device
    transfer)."""
    from fedml_tpu.parallel.mesh import global_put, replicated, shard_client_batch

    variables = global_put(variables, replicated(mesh))
    return (variables,) + shard_client_batch(mesh, (cx, cy, cm, counts, keys), axis)
