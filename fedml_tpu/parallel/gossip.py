"""Multi-device gossip: DSGD/PushSum with nodes sharded over a mesh.

The simulation form (algorithms/decentralized.py) mixes the stacked node
models with one einsum on a single device. This is its mesh counterpart —
the distributed deployment the reference runs as per-neighbor MPI sends
(fedml_api/distributed/decentralized_framework/
decentralized_worker_manager.py:41-46): each device holds N/D nodes, trains
them under vmap, and the gossip mix runs as a masked partial-sum all-reduce.

TPU-first design note: the mixing matrix W of a realistic topology (ring +
Watts-Strogatz shortcuts) is SPARSE but irregular; rather than translate
per-edge sends into point-to-point ppermutes (one hop per edge, poor ICI
utilization for irregular graphs), every device computes its nodes'
weighted contribution to ALL nodes — an [N, n_local] x [n_local, model]
einsum on the MXU — and one psum over the node axis completes
``new_i = sum_j W[i,j] x_j`` exactly. One collective per round, identical
math to the einsum simulator (same f32 accumulation, psum adds only a
reduction-order difference), and the all-reduce rides ICI at full
bandwidth instead of serializing per-edge hops.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from fedml_tpu.parallel.local import LocalResult


def make_gossip_round(
    local_train: Callable,
    mesh: Mesh,
    axis: str = "nodes",
    pushsum: bool = False,
):
    """Build the jitted sharded gossip round.

    Returns ``round_fn(node_vars, ps_weights, W, cx, cy, cm, counts, keys)
    -> (node_vars, ps_weights, loss)`` where ``node_vars`` / ``cx`` / ... are
    stacked over the node axis (leading dim N divisible by the mesh size),
    ``W`` is the [N, N] mixing matrix (column-stochastic for pushsum,
    matching DecentralizedFedAPI), and ``ps_weights`` is the [N] PushSum
    mass vector (ignored for plain DSGD but threaded for API parity).
    """

    def shard_fn(node_vars, ps_weights, W, cx, cy, cm, counts, keys):
        # shards arrive [n_local, ...]; W arrives column-sharded [N, n_local]
        n_local = cx.shape[0]
        start = jax.lax.axis_index(axis) * n_local
        res: LocalResult = jax.vmap(local_train)(
            node_vars, cx, cy, cm, counts, keys
        )

        def mix_leaf(x):
            # this device's nodes' contribution to EVERY node, then one
            # all-reduce completes the mix; slice back out our own rows
            part = jnp.einsum("ij,j...->i...", W, x.astype(jnp.float32))
            full = jax.lax.psum(part, axis)
            return jax.lax.dynamic_slice_in_dim(
                full, start, n_local, axis=0).astype(x.dtype)

        mixed = jax.tree.map(mix_leaf, res.variables)
        if pushsum:
            full_w = jax.lax.psum(W @ ps_weights, axis)
            new_ps = jax.lax.dynamic_slice_in_dim(full_w, start, n_local, 0)
        else:
            new_ps = ps_weights
        w = counts.astype(jnp.float32)
        loss = (jax.lax.psum(jnp.sum(res.train_loss * w), axis)
                / jnp.maximum(jax.lax.psum(jnp.sum(w), axis), 1e-12))
        return mixed, new_ps, loss

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(None, axis),
                  P(axis), P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P()),
    )
    return jax.jit(mapped)


def place_gossip_inputs(mesh: Mesh, W, node_vars, ps_weights, arrays,
                        axis: str = "nodes"):
    """Shard the node-stacked state over the mesh: W by columns, everything
    else by its leading node axis."""
    from jax.sharding import NamedSharding

    node_sh = NamedSharding(mesh, P(axis))
    col_sh = NamedSharding(mesh, P(None, axis))
    return (
        jax.device_put(W, col_sh),
        jax.device_put(node_vars, node_sh),
        jax.device_put(ps_weights, node_sh),
        tuple(jax.device_put(a, node_sh) for a in arrays),
    )
