"""Sequence/context parallelism: ring attention over an 'sp' mesh axis.

The reference has NO sequence parallelism (SURVEY.md §5.7 — its sequence
models are 80-token LSTMs). For a TPU-native framework long context is
first-class: a sequence is sharded over the mesh's 'sp' axis, every device
holds the full model and one sequence shard, and attention runs as a ring —
each device's K/V shard hops around the ring via ``ppermute`` over ICI while
queries stay put, with partial softmax results merged online
(:func:`fedml_tpu.ops.attention.merge_partials`). Compute overlaps the
collective naturally: XLA pipelines the next hop's ppermute against the
current block's flash kernel.

The same function composes with federated axes: a ('clients', 'sp') 2-D mesh
trains each client's long-sequence model with its own ring, and the weighted
psum aggregation rides the 'clients' axis (fedml_tpu/parallel/crosssilo.py).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.ops.attention import (
    NEG_INF,
    attention,
    attention_block_partial,
    merge_partials,
    normalize_partial,
)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    axis_name: str, axis_size: int, causal: bool = True,
    sm_scale: Optional[float] = None, impl: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """Attention over a sequence sharded along ``axis_name``.

    Call INSIDE ``shard_map``; ``q/k/v`` are the local shards ``[B, H, Tl,
    D]`` of a global ``[B, H, axis_size*Tl, D]`` sequence laid out in order
    of mesh position. Runs ``axis_size`` ring steps: local K/V chunks rotate
    to the next device each step (``ppermute``), partial (o, m, l) results
    merge online, one normalization at the end. Causal masking uses global
    positions, so fully-future chunks contribute nothing (their rows stay at
    -inf / l=0).
    """
    idx = jax.lax.axis_index(axis_name)
    tl = q.shape[2]
    q_off = idx * tl
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    o0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)

    def compute(acc, k_cur, v_cur, i):
        src = (idx - i) % axis_size          # whose shard we hold this step
        part = attention_block_partial(
            q, k_cur, v_cur, q_offset=q_off, k_offset=src * tl,
            causal=causal, sm_scale=sm_scale, impl=impl, interpret=interpret)
        return merge_partials(acc, part)

    # step 0 on the resident shard, then permute-then-compute for the rest:
    # exactly axis_size-1 ppermutes (no dead final rotation on the wire).
    acc = compute((o0, m0, l0), k, v, 0)

    def step(carry, i):
        acc, k_cur, v_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        return (compute(acc, k_cur, v_cur, i), k_cur, v_cur), None

    (acc, _, _), _ = jax.lax.scan(step, (acc, k, v),
                                  jnp.arange(1, axis_size))
    return normalize_partial(*acc, out_dtype=q.dtype)


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    axis_name: str, axis_size: int, causal: bool = True,
    sm_scale: Optional[float] = None, impl: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Call INSIDE ``shard_map`` with the same layout as :func:`ring_attention`
    (local shards ``[B, H, Tl, D]`` of a sequence sharded along
    ``axis_name``). Two ``all_to_all`` reshards instead of a ring of
    ppermutes: heads scatter / sequence gathers, so each device runs FULL
    attention for ``H/axis_size`` heads over the whole sequence, then the
    inverse reshard restores sequence sharding. Communication volume is
    O(T·D·H/n) per device independent of step count — cheaper than the ring
    when heads are plentiful and ICI all-to-all bandwidth is good; the ring
    wins when H < axis_size or memory for the full-T K/V is tight. Both are
    exact (tests assert equality with single-device dense attention).

    Requires ``H % axis_size == 0``.
    """
    B, H, tl, D = q.shape
    if H % axis_size:
        raise ValueError(
            f"ulysses needs heads ({H}) divisible by the sp axis ({axis_size}); "
            "use ring_attention for head counts below the axis size"
        )

    def scatter_heads(x):
        # [B, H, Tl, D] -> [B, H/n, n*Tl, D]: head groups scatter, seq gathers
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = attention(qg, kg, vg, causal=causal, sm_scale=sm_scale,
                    impl=impl, interpret=interpret)
    # inverse: sequence scatters back, head groups gather
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def sequence_attention(
    q, k, v, *, axis_name: str, axis_size: int, mode: str = "ring", **kw
) -> jax.Array:
    """Dispatch between the two exact sequence-parallel attention schemes."""
    if mode == "ring":
        return ring_attention(q, k, v, axis_name=axis_name, axis_size=axis_size, **kw)
    if mode == "ulysses":
        return ulysses_attention(q, k, v, axis_name=axis_name, axis_size=axis_size, **kw)
    raise ValueError(f"unknown sequence-parallel mode {mode!r} (ring|ulysses)")


# ---------------------------------------------------------------------------
# Sequence-parallel LM training step
# ---------------------------------------------------------------------------

def sp_mesh(n_dp: int, n_sp: int) -> Mesh:
    """2-D (dp, sp) mesh: batch over dp, sequence over sp (ICI-adjacent)."""
    devs = jax.devices()
    need = n_dp * n_sp
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(n_dp, n_sp), ("dp", "sp"))


def make_sp_lm_train_step(
    module, tx, mesh: Mesh, *, attn_impl: str = "auto",
    interpret: bool = False,
) -> Callable:
    """Build a jitted LM train step over a ('dp', 'sp') mesh.

    ``module`` is a TransformerLM (fedml_tpu/models/transformer.py) built
    with ``ring_axis='sp'`` and ``ring_size=mesh.shape['sp']``; ``tx`` an
    optax transformation. Returns ``step(variables, opt_state, x, y, mask,
    rng) -> (variables, opt_state, loss)`` where ``x/y [B, T]`` global
    arrays get sharded P('dp', 'sp'); params replicated; grads psum over
    both axes.
    """
    from jax import shard_map

    n_sp = mesh.shape["sp"]

    def local_step(variables, opt_state, x, y, mask, rng):
        tl = x.shape[1]                      # local seq shard length
        pos_off = jax.lax.axis_index("sp") * tl
        # global token count, computed OUTSIDE the differentiated graph: a
        # scalar psum inside loss_fn would transpose to another psum and
        # scale every cotangent by the mesh size (8x grads on an 8-device
        # mesh — exactness-tested against the single-device step).
        total = jax.lax.psum(jnp.sum(mask.astype(jnp.float32)), ("dp", "sp"))

        def loss_fn(params):
            vars_in = dict(variables)
            vars_in["params"] = params
            logits = module.apply(vars_in, x, train=True, pos_offset=pos_off,
                                  rngs={"dropout": rng})
            from fedml_tpu.ops.xent import masked_cross_entropy

            per = masked_cross_entropy(logits, y, mask, impl=attn_impl,
                                       interpret=interpret)
            return jnp.sum(per) / jnp.maximum(total, 1.0)

        local_loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        # local_loss divides by the GLOBAL token count, so each device's
        # grad is its local contribution to the true mean — sum, not mean.
        loss = jax.lax.psum(local_loss, ("dp", "sp"))
        grads = jax.lax.psum(grads, ("dp", "sp"))
        import optax

        updates, new_opt = tx.update(grads, opt_state, variables["params"])
        new_params = optax.apply_updates(variables["params"], updates)
        out_vars = dict(variables)
        out_vars["params"] = new_params
        return out_vars, new_opt, loss

    repl = P()
    sharded = P("dp", "sp")
    step = shard_map(
        local_step, mesh=mesh,
        in_specs=(repl, repl, sharded, sharded, sharded, repl),
        out_specs=(repl, repl, repl),
        check_vma=False,
    )
    jitted = jax.jit(step, donate_argnums=(0, 1))

    def run(variables, opt_state, x, y, mask, rng):
        xs = jax.device_put(x, NamedSharding(mesh, sharded))
        ys = jax.device_put(y, NamedSharding(mesh, sharded))
        ms = jax.device_put(mask, NamedSharding(mesh, sharded))
        return jitted(variables, opt_state, xs, ys, ms, rng)

    run.mesh = mesh
    run.n_sp = n_sp
    return run
