"""Batch-sharded (intra-node data-parallel) training with synchronized
BatchNorm.

Counterpart of the reference's two intra-node DP mechanisms:

- ``nn.DataParallel`` over 4 GPUs for the FedGKT server model
  (fedml_api/distributed/fedgkt/GKTServerTrainer.py:28-29), and
- the sync-BN helpers shipped for segmentation
  (fedml_api/model/cv/batchnorm_utils.py, ~462 LoC of hand-rolled
  cross-GPU mean/var broadcast + replicate/gather plumbing).

On TPU neither needs a subsystem, because GSPMD already is one. The train
step is written exactly like the single-device step — global batch, global
mean loss, BatchNorm over the whole batch — and ``jit`` with
``in_shardings`` placing the batch axis over a 1-D ``('batch',)`` mesh
partitions it: XLA shards the convolutions, turns BatchNorm's batch
moments into cross-device all-reduces (sync-BN for free — the whole
batchnorm_utils file dissolves into the partitioner), and all-reduces the
gradients. Parameters and optimizer state are replicated. The result is
bit-comparable to running the same step on one device with the full batch
(tests/test_dataparallel.py asserts it).

Models that need sync-BN under EXPLICIT shard_map code instead (where
each program instance only sees its shard) accept ``bn_axis=<axis name>``
(e.g. resnet.py), which flax wires to a psum of the batch moments. The
federated paths deliberately do NOT use it: in cross-silo training each
device holds a different client whose BN must stay local.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.tasks import Task
from fedml_tpu.models import ModelBundle

BATCH_AXIS = "batch"


def batch_mesh(n_devices: Optional[int] = None, axis: str = BATCH_AXIS) -> Mesh:
    """1-D mesh over the batch axis (all local devices by default)."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def make_dp_train_step(
    bundle: ModelBundle,
    task: Task,
    tx: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    axis: str = BATCH_AXIS,
    compute_dtype=None,
    grad_clip: Optional[float] = None,
) -> Callable:
    """Build ``step(variables, opt_state, x, y, mask, rng) -> (variables,
    opt_state, loss)``; with a ``mesh`` the global batch is sharded over it.

    The body is the plain single-device step; GSPMD distributes it when a
    mesh is given (``mesh=None`` compiles the same body unsharded, so one
    builder serves both the single-chip and data-parallel paths). BN
    stats, the mask-weighted mean loss, and gradients are all global by
    construction. Shard-degenerate batches are fine (the mask handles
    ragged tails); the batch size should be a multiple of the mesh size
    for an even split. Params/opt state are donated each step.
    """

    def step(variables, opt_state, x, y, mask, rng):
        if compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(compute_dtype)

        def loss_fn(p):
            vars_in = dict(variables)
            vars_in["params"] = p
            logits, new_vars = bundle.apply_train(vars_in, x, rng)
            return task.loss(logits, y, mask), new_vars

        (loss, new_vars), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            variables["params"]
        )
        if grad_clip:
            gnorm = optax.global_norm(grads)
            scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-12))
            grads = jax.tree.map(lambda g: g * scale, grads)
        updates, new_opt_state = tx.update(grads, opt_state, variables["params"])
        params = optax.apply_updates(variables["params"], updates)
        out_vars = dict(new_vars)
        out_vars["params"] = params
        return out_vars, new_opt_state, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis))
    return jax.jit(
        step,
        in_shardings=(repl, repl, shard, shard, shard, repl),
        out_shardings=(repl, repl, repl),
        donate_argnums=(0, 1),
    )


def make_dp_eval_fn(
    bundle: ModelBundle,
    task: Task,
    mesh: Mesh,
    axis: str = BATCH_AXIS,
) -> Callable:
    """Build ``evaluate(variables, x, y, mask) -> metric-sum dict`` with the
    eval pool sharded over the mesh; sums are global."""
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis))

    def ev(variables, x, y, mask):
        logits = bundle.apply_eval(variables, x)
        return task.metrics(logits, y, mask)

    return jax.jit(ev, in_shardings=(repl, shard, shard, shard), out_shardings=repl)


def place_batch(mesh: Mesh, *arrays, axis: str = BATCH_AXIS):
    """device_put arrays with their leading (batch) axis sharded."""
    shard = NamedSharding(mesh, P(axis))
    out = tuple(jax.device_put(a, shard) for a in arrays)
    return out if len(out) > 1 else out[0]
