"""Client-packing schedule: many small clients share one scan lane.

The bucketed/grouped schedules (algorithms/fedavg.py `_round_groups`,
`_mesh_group_plan`) cut padding by giving count-sorted client groups their
own scan lengths — but every client in a group still pads to the group max,
which left 15% (sim) / 21% (mesh) of executed slots dead in round 3's
bench. This module removes the group-max: the cohort is packed into a few
fixed-length lanes (LPT balancing), each lane running its clients
BACK-TO-BACK in one `lax.scan` with optimizer-state reset at client
boundaries. Padding shrinks to the final partial batch of each client plus
the lane tail — one-batch granularity instead of group-max granularity.

Exactness: each client's trajectory REPLAYS the canonical unbucketed
program (`make_local_train_fn` at full n_pad) bit-for-bit — the same
per-epoch `jax.random.permutation(ekey, n_pad)` + real-first stable sort
and the same per-step batch keys, of which the packed lane simply executes
only the `ceil(count/bs)` real steps. The round aggregate is the same
weighted mean up to float summation order (lanes accumulate
`sum(w_i * vars_i)` locally).

The reference has no analogue: its clients are OS processes; padding is a
TPU-ism (SURVEY.md §7 hard part (a)) and packing is the TPU-native answer.
"""

from __future__ import annotations

import logging
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.tasks import Task
from fedml_tpu.models import ModelBundle
from fedml_tpu.parallel.local import (EPOCH_KEY_SALT as _EPOCH_KEY_SALT,
                                      make_batch_sgd_step, make_optimizer)

log = logging.getLogger(__name__)


class PackPlan(NamedTuple):
    """Static lane schedule for one cohort. Shapes (n_lanes, k_max, T) are
    the compile signature; the arrays are runtime data, so rounds with the
    same shapes share one XLA program."""

    n_lanes: int
    k_max: int
    T: int                 # scan steps per lane
    epochs: int
    # [n_lanes, T] per-step metadata
    slot: np.ndarray       # which member slot trains this step (0 on dead steps)
    epoch: np.ndarray      # epoch index
    sie: np.ndarray        # step within the epoch
    reset: np.ndarray      # 1.0 at a client's first step
    emit: np.ndarray       # 1.0 at a client's last step
    live: np.ndarray       # 0.0 on dead lane-tail steps
    # [n_lanes, k_max] per-member metadata
    member_pos: np.ndarray   # position in the sampled cohort (0-padded)
    member_valid: np.ndarray  # 1.0 for real members
    steps_real: np.ndarray   # ceil(count/bs) per member (>=1 for real members)

    @property
    def shape_key(self) -> tuple:
        return (self.n_lanes, self.k_max, self.T, self.epochs)

    @property
    def executed_slots(self) -> int:
        """Batch slots the schedule executes (for padded-throughput
        accounting): lanes x steps x batch — without the batch factor."""
        return self.n_lanes * self.T


def plan_packing(counts: np.ndarray, batch_size: int, epochs: int,
                 n_lanes: int, t_quantum: int = 1) -> Optional[PackPlan]:
    """LPT-pack the cohort (client j costs ``epochs * ceil(count_j/bs)``
    consecutive steps) into ``n_lanes`` lanes; T = max lane load rounded up
    to ``t_quantum`` steps. Returns None when the cohort is empty."""
    counts = np.asarray(counts, np.float64)
    steps = np.ceil(np.maximum(counts, 0.0) / batch_size).astype(np.int64)
    members = np.nonzero(steps > 0)[0]
    if members.size == 0 or n_lanes < 1:
        return None
    n_lanes = int(min(n_lanes, members.size))
    cost = epochs * steps[members]
    order = np.argsort(-cost, kind="stable")          # LPT: biggest first
    lanes: list[list[int]] = [[] for _ in range(n_lanes)]
    loads = np.zeros(n_lanes, np.int64)
    for j in order:
        l = int(np.argmin(loads))
        lanes[l].append(int(members[j]))
        loads[l] += cost[j]
    T = int(np.ceil(loads.max() / max(t_quantum, 1)) * max(t_quantum, 1))
    k_max = max(len(l) for l in lanes)

    slot = np.zeros((n_lanes, T), np.int32)
    epoch = np.zeros((n_lanes, T), np.int32)
    sie = np.zeros((n_lanes, T), np.int32)
    reset = np.zeros((n_lanes, T), np.float32)
    emit = np.zeros((n_lanes, T), np.float32)
    live = np.zeros((n_lanes, T), np.float32)
    member_pos = np.zeros((n_lanes, k_max), np.int32)
    member_valid = np.zeros((n_lanes, k_max), np.float32)
    steps_real = np.ones((n_lanes, k_max), np.int32)

    for l, mem in enumerate(lanes):
        t = 0
        for k, pos in enumerate(mem):
            member_pos[l, k] = pos
            member_valid[l, k] = 1.0
            s = int(steps[pos])
            steps_real[l, k] = s
            reset[l, t] = 1.0
            for e in range(epochs):
                for si in range(s):
                    slot[l, t] = k
                    epoch[l, t] = e
                    sie[l, t] = si
                    live[l, t] = 1.0
                    t += 1
            emit[l, t - 1] = 1.0
        # steps t..T-1 stay dead (slot 0, live 0)

    return PackPlan(n_lanes, k_max, T, epochs, slot, epoch, sie, reset, emit,
                    live, member_pos, member_valid, steps_real)


def _member_replay_tables(mask_rows, epochs: int, n_pad: int,
                          steps_full: int):
    """The canonical per-member replay tables — EXACTLY
    make_local_train_fn's per-epoch ``permutation`` over the global n_pad,
    real-first stable sort, and ``fold_in(ekey, EPOCH_KEY_SALT)`` batch
    keys. ONE definition shared by the vmapped lane form and the fedpack
    joint form, so the bit-exact replay contract cannot drift between the
    two lowerings. Returns ``member_tables(key, row) -> (orders [E,n_pad],
    bkeys [E,steps_full])``; vmap it over members (and lanes)."""

    def member_tables(key, row):
        mask_row = mask_rows[row]
        ekeys = jax.random.split(key, epochs)

        def per_epoch(ek):
            perm = jax.random.permutation(ek, n_pad)
            order = perm[jnp.argsort(-mask_row[perm], stable=True)]
            bkeys = jax.random.split(
                jax.random.fold_in(ek, _EPOCH_KEY_SALT), steps_full)
            return order, bkeys

        return jax.vmap(per_epoch)(ekeys)

    return member_tables


def make_lane_train(
    bundle: ModelBundle,
    task: Task,
    n_pad: int,
    *,
    optimizer: str = "sgd",
    lr: float = 0.01,
    momentum: float = 0.0,
    wd: float = 0.0,
    epochs: int = 1,
    batch_size: int = 32,
    grad_clip: Optional[float] = None,
    prox_mu: float = 0.0,
    compute_dtype=None,
    scan_unroll: int = 1,
    client_transform: Optional[Callable] = None,
    reduce_extras: Optional[Callable] = None,
    lens: bool = False,
) -> Callable:
    """Build the single-lane program both execution forms share: the
    simulation paradigm vmaps it over all lanes
    (:func:`make_packed_cohort_train`), the cross-silo mesh shard_maps it
    with a psum tail (:func:`make_crosssilo_packed_round`).

    ``client_transform`` / ``reduce_extras`` are the per-client halves of
    the cross-silo hook contract (crosssilo.make_crosssilo_round): both
    take STACKED client results, so the lane applies them at each client's
    emit step with a singleton leading axis — this is how the whole
    algorithm zoo (FedOpt/FedNova/AGC/robust) rides the packed schedule."""
    del compute_dtype  # callers pre-cast the stacked arrays once
    from fedml_tpu.parallel.local import LocalResult
    tx_opt = make_optimizer(optimizer, lr, momentum, wd)
    batch_step = make_batch_sgd_step(
        bundle, task, tx_opt, grad_clip=grad_clip, prox_mu=prox_mu,
        compute_dtype=None,
    )
    steps_full = n_pad // batch_size
    bs = batch_size

    def lane_train(variables0, x_flat, y_flat, m_flat, mask_rows,
                   member_row, member_keys, member_w, steps_real,
                   slot, epoch_a, sie, reset, emit, live):
        """One lane. x_flat/y_flat/m_flat: [C*n_pad, ...] flattened stacks
        (shared, unbatched); mask_rows [C, n_pad]; member_* are this lane's
        [k_max] arrays; per-step metadata [T]."""
        params0 = variables0["params"]
        opt_state0 = tx_opt.init(params0)

        # Exact replay of make_local_train_fn's per-epoch order and batch
        # keys, per member (shared definition — see _member_replay_tables)
        member_tables = _member_replay_tables(mask_rows, epochs, n_pad,
                                              steps_full)
        orders, bkeys = jax.vmap(member_tables)(member_keys, member_row)

        def step_fn(carry, xs):
            (variables, opt_state, loss_acc, acc_vars, acc_w, acc_loss,
             acc_tau, acc_extras) = carry[:8]
            k, e, s, rs, em, lv = xs
            variables = jax.tree.map(
                lambda v, z: jnp.where(rs > 0, z, v), variables, variables0)
            opt_state = jax.tree.map(
                lambda v, z: jnp.where(rs > 0, z, v), opt_state, opt_state0)
            loss_acc = jnp.where(rs > 0, 0.0, loss_acc)
            if lens:
                upd_stack, l_first, l_last, floss_acc = carry[8]
                floss_acc = jnp.where(rs > 0, 0.0, floss_acc)

            row = member_row[k]
            oseg = jax.lax.dynamic_slice(
                orders, (k, e, s * bs), (1, 1, bs)).reshape(bs)
            flat = row * n_pad + oseg
            bx = jnp.take(x_flat, flat, axis=0)
            by = jnp.take(y_flat, flat, axis=0)
            bm = jnp.take(m_flat, flat, axis=0)
            bkey = bkeys[k, e, s]

            new_vars, new_opt, l = batch_step(
                variables, opt_state, params0, bx, by, bm, bkey)

            def freeze_if_dead(new, old):
                return jax.tree.map(
                    lambda n, o: lv * n + (1.0 - lv) * o
                    if jnp.issubdtype(n.dtype, jnp.floating)
                    else jnp.where(lv > 0, n, o),
                    new, old,
                )

            new_opt = freeze_if_dead(new_opt, opt_state)
            out_vars = dict(freeze_if_dead(new_vars, variables))

            lastep = (e == epochs - 1).astype(jnp.float32)
            loss_acc = loss_acc + l * lv * lastep

            w = member_w[k] * em
            sr = jnp.maximum(steps_real[k].astype(jnp.float32), 1.0)
            if lens:
                # fedlens member scatter (obs/lens.py): each member emits
                # exactly once, so .add at its slot is a masked set, and
                # off-emit steps (em = 0) contribute exactly nothing — the
                # same linear-in-w contract the accumulators above rely on.
                # RAW update (pre-client_transform): a robust clip must not
                # hide the attacker from the lens.
                floss_acc = floss_acc + l * lv * (e == 0).astype(jnp.float32)
                upd_stack = jax.tree.map(
                    lambda b, v, p: b.at[k].add(
                        em * (v.astype(jnp.float32) - p.astype(jnp.float32))),
                    upd_stack, out_vars["params"], params0)
                l_first = l_first.at[k].add(em * floss_acc / sr)
                l_last = l_last.at[k].add(em * loss_acc / sr)
            acc_out = out_vars
            if client_transform is not None:
                # hook contract is stacked-clients; singleton axis at emit
                acc_out = jax.tree.map(
                    lambda v: v[0],
                    client_transform(
                        variables0,
                        jax.tree.map(lambda v: v[None], out_vars)))
            acc_vars = jax.tree.map(lambda a, v: a + w * v, acc_vars, acc_out)
            acc_w = acc_w + w
            acc_loss = acc_loss + w * loss_acc / sr
            acc_tau = acc_tau + w * epochs * sr
            if reduce_extras is not None:
                res1 = LocalResult(
                    jax.tree.map(lambda v: v[None], out_vars),
                    (loss_acc / sr)[None], (epochs * sr)[None])
                # the hook returns WEIGHTED partial sums; w = 0 off-emit,
                # so non-emit steps contribute exactly nothing. The hook
                # (like client_transform above) COMPUTES every step even
                # though only emit steps land — that is O(params) of
                # elementwise work per step against the step's O(batch x
                # model) training FLOPs, <0.1% for conv models; buffering
                # emitted trees and hooking once per member would trade it
                # for a k_max-sized model buffer per lane and more HBM
                # traffic than it saves.
                ex = reduce_extras(variables0, res1, w[None])
                acc_extras = jax.tree.map(lambda a, b: a + b, acc_extras, ex)
            out = (out_vars, new_opt, loss_acc, acc_vars, acc_w, acc_loss,
                   acc_tau, acc_extras)
            if lens:
                out = out + ((upd_stack, l_first, l_last, floss_acc),)
            return out, None

        # zeros DERIVED from inputs, not constants: under shard_map the
        # inputs are device-varying, and a constant-zero carry init would
        # type-clash with the varying carry the scan body produces
        z = jnp.sum(member_w) * 0.0
        acc0 = jax.tree.map(lambda v: v.astype(jnp.float32) * 0.0, variables0)
        if reduce_extras is not None:
            ex0 = reduce_extras(
                variables0,
                LocalResult(jax.tree.map(lambda v: (v * 0.0)[None], variables0),
                            z[None], z[None]),
                z[None])
            acc_extras0 = jax.tree.map(lambda e: e * 0.0, ex0)
        else:
            acc_extras0 = {}
        carry0 = (variables0, opt_state0, z, acc0, z, z, z, acc_extras0)
        if lens:
            # zeros derived from inputs (shard_map type consistency): the
            # per-member update stack [k_max, *param] plus first/last mean
            # losses [k_max]; same memory class as the vmap fallback's
            # stacked per-client variables
            zk = member_w * 0.0
            upd0 = jax.tree.map(
                lambda p: zk.reshape(zk.shape + (1,) * p.ndim)
                * p.astype(jnp.float32)[None], params0)
            carry0 = carry0 + ((upd0, zk, zk, z),)
        final, _ = jax.lax.scan(
            step_fn, carry0, (slot, epoch_a, sie, reset, emit, live),
            unroll=max(int(scan_unroll), 1),
        )
        (_, _, _, acc_vars, acc_w, acc_loss, acc_tau, acc_extras) = final[:8]
        if lens:
            return (acc_vars, acc_w, acc_loss, acc_tau, acc_extras,
                    final[8][:3])
        return acc_vars, acc_w, acc_loss, acc_tau, acc_extras

    return lane_train


# --- fedpack: the joint (stacked-lane) execution form -----------------------

# Fallback bookkeeping: warn-once keys plus a registry counter lane
# ("packed" namespace) so pulse snapshots and trace_report surface WHICH
# programs fell back, not just a one-shot process log line. State is
# process-scoped but resettable: obs.reset() (the per-federation teardown
# tests already call between runs) clears both, so a second federation in
# one process re-warns and counts from zero instead of inheriting the
# first federation's suppression.
_FALLBACK_STATE: dict = {"seen": set(), "group": None}


def _fallback_group():
    g = _FALLBACK_STATE["group"]
    if g is None:
        from fedml_tpu.obs import default_registry

        g = _FALLBACK_STATE["group"] = default_registry().group("packed")
    return g


def reset_fallback_warnings() -> None:
    """Clear the warn-once set and drop the registry counter group (called
    by obs.reset so fallback accounting is per-federation in tests/tools
    that reset the plane between runs)."""
    _FALLBACK_STATE["seen"].clear()
    _FALLBACK_STATE["group"] = None


def impl_label(packed_conv) -> str:
    """Short string form of a lowering selector for counter keys, log
    lines and cost hints: the flag string itself, or 'auto' for a fedplan
    :class:`~fedml_tpu.obs.plan.LoweringPlan` (its per-stage detail rides
    ``cost_hints['plan']``, not the label)."""
    return packed_conv if isinstance(packed_conv, str) else "auto"


def resolve_packed_conv(packed_conv, bundle: ModelBundle, n_lanes: int,
                        dtype=None, optimizer: str = "sgd"):
    """Resolve the ``--packed_conv`` flag to what the builders consume at
    program-build time: concrete flags pass through; ``'auto'`` becomes
    the fedplan :class:`~fedml_tpu.obs.plan.LoweringPlan` for this bundle
    at the schedule's ACTUAL lane count — or ``'off'`` (with the
    documented :func:`packed_fallback_reason` warning downstream) when the
    joint form cannot apply (no packed twin, flax-rng dropout, or a
    single-lane schedule with nothing to co-schedule)."""
    if packed_conv != "auto":
        return packed_conv
    if n_lanes < 2 or packed_fallback_reason(
            bundle, "auto", optimizer) is not None:
        return "off"
    from fedml_tpu.obs.plan import plan_lowering

    return plan_lowering(bundle, int(n_lanes), dtype=dtype)


def packed_fallback_reason(bundle: ModelBundle, packed_conv,
                           optimizer: str = "sgd") -> Optional[str]:
    """Why the joint form does NOT apply (None = it does). After the
    packed-everywhere refactor the only remaining reasons are genuinely
    unpackable shapes — the DESIGN.md §15 exception table:

    - ``packed_conv=off`` (the flag, not a capability gap);
    - the model family ships no lane-major packed twin
      (``packed_variant is None`` — mixed per-lane architectures, rnn/
      transformer/moe);
    - the model uses flax-rng dropout and its packed twin does not opt in
      to the explicit per-lane key stream (``explicit_dropout``).

    Client optimizer choice no longer disqualifies: optimizer state is
    held per-lane (``[L]``-leading leaves via a vmapped optax init/update),
    so adam's scalar step count and friends reset and freeze per lane like
    any other leaf. ``optimizer`` stays in the signature for call-site
    symmetry and future optimizers with genuinely unliftable state."""
    del optimizer
    if packed_conv in (None, "", "off"):
        return "packed_conv=off"
    if bundle.packed_variant is None:
        return f"model {bundle.name!r} has no packed conv variant"
    if bundle.uses_dropout:
        pb = bundle.packed_variant(packed_conv)
        if not getattr(pb, "explicit_dropout", False):
            return (f"model {bundle.name!r} uses flax-rng dropout and its "
                    "packed twin has no explicit per-lane key stream")
    return None


def _packed_model_bundle(bundle: ModelBundle, packed_conv: str,
                         optimizer: str) -> Optional[ModelBundle]:
    """Resolve the fedpack joint-lane lowering: the packed twin bundle, or
    None when the per-lane vmap must stay (:func:`packed_fallback_reason`).
    A real fallback (flag ON but joint form inapplicable) is warned once
    per (model, lowering) and counted in the "packed" registry lane."""
    reason = packed_fallback_reason(bundle, packed_conv, optimizer)
    if reason is not None:
        if packed_conv not in (None, "", "off"):
            label = impl_label(packed_conv)
            g = _fallback_group()
            ck = f"fallback:{bundle.name}:{label}"
            g[ck] = g.get(ck, 0) + 1
            key = (bundle.name, label, reason)
            if key not in _FALLBACK_STATE["seen"]:
                _FALLBACK_STATE["seen"].add(key)
                log.warning(
                    "packed_conv=%r falls back to the per-lane vmap: %s",
                    label, reason)
        return None
    return bundle.packed_variant(packed_conv)


def packed_conv_active(bundle: ModelBundle, packed_conv: str,
                       optimizer: str = "sgd") -> bool:
    """Whether :func:`make_lanes_train` will use the fedpack joint form for
    this (bundle, flag, optimizer) — callers use it to attach fedcost
    packing hints only to programs that really carry the packed GEMMs."""
    return packed_fallback_reason(bundle, packed_conv, optimizer) is None


def make_lanes_train(
    bundle: ModelBundle,
    task: Task,
    n_pad: int,
    *,
    packed_conv: str = "off",
    **lane_kwargs,
) -> Callable:
    """The all-lanes program both packed round builders share: by default
    ``vmap`` of :func:`make_lane_train` over the lane axis (XLA lowers the
    batched-kernel convs to a grouped conv, docs/mfu_experiments.md H4);
    with ``packed_conv`` on and a capable model, the fedpack JOINT form
    (:func:`make_packed_lanes_train`) whose convs are ONE block-diagonal/
    grouped contraction across lanes (ops/packed_conv.py). Same signature
    and stacked-accumulator return either way."""
    pb = _packed_model_bundle(bundle, packed_conv,
                              lane_kwargs.get("optimizer", "sgd"))
    if pb is None:
        lane_train = make_lane_train(bundle, task, n_pad, **lane_kwargs)
        return jax.vmap(lane_train, in_axes=(None,) * 5 + (0,) * 10)
    return make_packed_lanes_train(bundle, pb, task, n_pad, **lane_kwargs)


def make_packed_lanes_train(
    bundle: ModelBundle,
    packed_bundle: ModelBundle,
    task: Task,
    n_pad: int,
    *,
    optimizer: str = "sgd",
    lr: float = 0.01,
    momentum: float = 0.0,
    wd: float = 0.0,
    epochs: int = 1,
    batch_size: int = 32,
    grad_clip: Optional[float] = None,
    prox_mu: float = 0.0,
    compute_dtype=None,
    scan_unroll: int = 1,
    client_transform: Optional[Callable] = None,
    reduce_extras: Optional[Callable] = None,
    lens: bool = False,
) -> Callable:
    """The fedpack JOINT form of ``vmap(lane_train)``: all lanes advance
    through ONE scan whose per-step model apply sees the stacked lane axis
    explicitly, so every conv lowers as one client-packed contraction
    (``packed_bundle``, ops/packed_conv.py) instead of K per-lane
    partial-lane GEMMs. Everything per-lane — replay tables, reset/freeze
    masks, weighted accumulation, grad clipping, OPTIMIZER STATE — is
    computed with an explicit [L] lane vector exactly as the vmap form
    computes it per lane, so the two forms agree up to GEMM summation
    order (pinned by tests/test_packed_conv.py and the per-paradigm pins
    in tests/test_packed_everywhere.py).

    Optimizer state is stacked per lane: ``vmap(tx.init)`` over the
    stacked params gives every optax leaf — including adam/amsgrad's
    scalar step count and adagrad/yogi accumulators — a leading ``[L]``
    axis, and ``vmap(tx.update)`` keeps the update per-lane, so the
    reset-at-client-boundary and dead-step-freeze masks address ALL state
    uniformly. This is what lets every client optimizer the reference
    library ships ride the packed convs instead of forcing the vmap
    fallback.

    Dropout models ride via the explicit per-lane key stream: the packed
    twin opts in with ``explicit_dropout`` (ops/packed_conv.seed_dropout /
    lane_dropout) and the joint form hands the model apply the whole
    ``[L]`` vector of this step's member batch keys — lane ``l``'s mask
    derives from exactly the key the vmap form's lane ``l`` consumes, so
    the two lowerings draw bit-identical masks per lane.

    Same call signature as the vmapped lane program (variables unstacked;
    member/plan arrays carrying the leading lane axis) and the same stacked
    returns, except ``acc_extras`` comes back with a singleton leading axis:
    the hooks' stacked-clients contract already sums over the lane axis
    inside one call, and the callers' ``sum(axis=0)`` tail must stay a
    no-op rather than a reduction over a parameter axis.
    """
    del compute_dtype  # callers pre-cast the stacked arrays once
    from fedml_tpu.ops.packed_conv import stack_variables
    from fedml_tpu.parallel.local import LocalResult

    tx_opt = make_optimizer(optimizer, lr, momentum, wd)
    steps_full = n_pad // batch_size
    bs = batch_size
    pb = packed_bundle

    def bcast(vec, leaf):
        """[L] lane vector -> broadcastable against a stacked leaf."""
        return vec.reshape(vec.shape + (1,) * (leaf.ndim - 1))

    def lanes_train(variables0, x_flat, y_flat, m_flat, mask_rows,
                    member_row, member_keys, member_w, steps_real,
                    slot, epoch_a, sie, reset, emit, live):
        L = slot.shape[0]
        stack0 = stack_variables(variables0, L)
        sparams0 = stack0["params"]
        # per-LANE optimizer state: vmap(init) gives every optax leaf a
        # leading [L] axis (adam's scalar count becomes [L]), so the
        # reset/freeze masks below address adaptive state per lane
        opt_state0 = jax.vmap(tx_opt.init)(sparams0)

        # Exact replay of make_local_train_fn's per-epoch order and batch
        # keys, per (lane, member) — the SAME shared definition the vmap
        # form uses (_member_replay_tables), so the two lowerings cannot
        # drift on the replay contract
        member_tables = _member_replay_tables(mask_rows, epochs, n_pad,
                                              steps_full)
        orders, bkeys = jax.vmap(jax.vmap(member_tables))(
            member_keys, member_row)     # [L,k_max,E,n_pad], [L,k_max,E,S]

        def batch_step_packed(svars, sopt, bx, by, bm, bkey_l):
            """One joint minibatch step: per-lane losses summed so the grad
            of the stacked params IS the per-lane grads (the block weight's
            off-diagonal zeros are structural — ops/packed_conv)."""

            def loss_fn(sp):
                vars_in = dict(svars)
                vars_in["params"] = sp
                # the FULL [L] key vector: explicit-dropout packed twins
                # draw lane l's mask from bkey_l[l] — the very key the
                # vmap form's lane l consumes (non-dropout twins ignore it)
                logits, new_vars = pb.apply_train(vars_in, bx, bkey_l)
                per_lane = jax.vmap(task.loss)(logits, by, bm)      # [L]
                if prox_mu:
                    # per-LANE prox term, folded into per_lane so the
                    # REPORTED loss matches the vmap form (whose batch_step
                    # returns loss WITH prox); summing per-lane terms gives
                    # the same total the grads need (== tree_dot(d, d))
                    from fedml_tpu.core.pytree import tree_sub
                    d = tree_sub(sp, sparams0)
                    prox_l = sum(
                        jnp.sum(jnp.square(g), axis=tuple(range(1, g.ndim)))
                        for g in jax.tree.leaves(d))                # [L]
                    per_lane = per_lane + 0.5 * prox_mu * prox_l
                return jnp.sum(per_lane), (new_vars, per_lane)

            (_, (new_vars, per_lane)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(svars["params"])
            if grad_clip:
                # per-LANE clip (lane == one client's step), the joint form
                # of the vmap path's per-lane optax.global_norm
                sq = [jnp.sum(jnp.square(g), axis=tuple(range(1, g.ndim)))
                      for g in jax.tree.leaves(grads)]
                gnorm = jnp.sqrt(sum(sq))                            # [L]
                scale = jnp.minimum(
                    1.0, grad_clip / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree.map(
                    lambda g: g * bcast(scale, g).astype(g.dtype), grads)
            # per-lane update mirrors the per-lane init: adaptive moments,
            # step counts and accumulators advance lane-by-lane exactly as
            # the vmap form's per-lane tx.update does
            updates, new_opt = jax.vmap(tx_opt.update)(
                grads, sopt, svars["params"])
            out_vars = dict(new_vars)
            out_vars["params"] = optax.apply_updates(
                svars["params"], updates)
            return out_vars, new_opt, per_lane

        def step_fn(carry, xs):
            (svars, sopt, loss_acc, acc_vars, acc_w, acc_loss, acc_tau,
             acc_extras) = carry[:8]
            k, e, s, rs, em, lv = xs                    # each [L]
            svars = jax.tree.map(
                lambda v, z: jnp.where(bcast(rs, v) > 0, z, v), svars, stack0)
            sopt = jax.tree.map(
                lambda v, z: jnp.where(bcast(rs, v) > 0, z, v),
                sopt, opt_state0)
            loss_acc = jnp.where(rs > 0, 0.0, loss_acc)
            if lens:
                upd_stack, l_first, l_last, floss_acc = carry[8]
                floss_acc = jnp.where(rs > 0, 0.0, floss_acc)

            rows = jnp.take_along_axis(member_row, k[:, None], axis=1)[:, 0]
            oseg = jax.vmap(
                lambda o, kk, ee, ss: jax.lax.dynamic_slice(
                    o, (kk, ee, ss * bs), (1, 1, bs)).reshape(bs)
            )(orders, k, e, s)                          # [L, bs]
            flat = rows[:, None] * n_pad + oseg
            bx = jnp.take(x_flat, flat, axis=0)
            by = jnp.take(y_flat, flat, axis=0)
            bm = jnp.take(m_flat, flat, axis=0)
            bkey_l = jax.vmap(
                lambda bk, kk, ee, ss: bk[kk, ee, ss])(bkeys, k, e, s)

            new_vars, new_opt, per_lane = batch_step_packed(
                svars, sopt, bx, by, bm, bkey_l)

            def freeze_if_dead(new, old):
                return jax.tree.map(
                    lambda n, o: bcast(lv, n) * n + (1.0 - bcast(lv, n)) * o
                    if jnp.issubdtype(n.dtype, jnp.floating)
                    else jnp.where(bcast(lv, n) > 0, n, o),
                    new, old,
                )

            new_opt = freeze_if_dead(new_opt, sopt)
            out_vars = dict(freeze_if_dead(new_vars, svars))

            lastep = (e == epochs - 1).astype(jnp.float32)
            loss_acc = loss_acc + per_lane * lv * lastep

            w = jnp.take_along_axis(member_w, k[:, None], axis=1)[:, 0] * em
            sr = jnp.maximum(jnp.take_along_axis(
                steps_real, k[:, None], axis=1)[:, 0].astype(jnp.float32),
                1.0)
            if lens:
                # fedlens member scatter, joint form: lane l's member k[l]
                # slot takes the masked set (each member emits once); same
                # RAW-update/linear-in-emit contract as the vmap lane form
                floss_acc = (floss_acc
                             + per_lane * lv * (e == 0).astype(jnp.float32))
                lidx = jnp.arange(k.shape[0])
                upd_stack = jax.tree.map(
                    lambda b, v, p: b.at[lidx, k].add(
                        bcast(em, v)
                        * (v.astype(jnp.float32) - p.astype(jnp.float32))),
                    upd_stack, out_vars["params"], sparams0)
                l_first = l_first.at[lidx, k].add(em * floss_acc / sr)
                l_last = l_last.at[lidx, k].add(em * loss_acc / sr)
            acc_out = out_vars
            if client_transform is not None:
                # the hook contract is stacked-clients; the joint form IS
                # stacked — one call covers every lane
                acc_out = client_transform(variables0, out_vars)
            acc_vars = jax.tree.map(
                lambda a, v: a + bcast(w, v) * v, acc_vars, acc_out)
            acc_w = acc_w + w
            acc_loss = acc_loss + w * loss_acc / sr
            acc_tau = acc_tau + w * epochs * sr
            if reduce_extras is not None:
                # w = 0 off-emit, so non-emit lanes contribute exactly
                # nothing (the same linear-in-w contract the vmap form
                # relies on); the hook's return is already the lane sum
                res = LocalResult(out_vars, loss_acc / sr, epochs * sr)
                ex = reduce_extras(variables0, res, w)
                acc_extras = jax.tree.map(
                    lambda a, b: a + b, acc_extras, ex)
            out = (out_vars, new_opt, loss_acc, acc_vars, acc_w, acc_loss,
                   acc_tau, acc_extras)
            if lens:
                out = out + ((upd_stack, l_first, l_last, floss_acc),)
            return out, None

        # zeros DERIVED from inputs (shard_map type consistency, as in the
        # vmap form)
        zl = jnp.sum(member_w, axis=1) * 0.0            # [L]
        acc0 = jax.tree.map(lambda v: v.astype(jnp.float32) * 0.0, stack0)
        if reduce_extras is not None:
            ex0 = reduce_extras(
                variables0,
                LocalResult(jax.tree.map(lambda v: v * 0.0, stack0),
                            zl, zl), zl)
            acc_extras0 = jax.tree.map(lambda e: e * 0.0, ex0)
        else:
            acc_extras0 = {}
        carry0 = (stack0, opt_state0, zl, acc0, zl, zl, zl, acc_extras0)
        if lens:
            zk2 = member_w * 0.0                        # [L, k_max]
            upd0 = jax.tree.map(
                lambda p: zk2.reshape(zk2.shape + (1,) * (p.ndim - 1))
                * p.astype(jnp.float32)[:, None], sparams0)
            carry0 = carry0 + ((upd0, zk2, zk2, zl),)
        final, _ = jax.lax.scan(
            step_fn, carry0,
            (slot.T, epoch_a.T, sie.T, reset.T, emit.T, live.T),
            unroll=max(int(scan_unroll), 1),
        )
        (_, _, _, acc_vars, acc_w, acc_loss, acc_tau, acc_extras) = final[:8]
        # singleton lane axis on the extras: the hook summed lanes already,
        # and the caller's sum(axis=0) must reduce THIS axis, not a real one
        acc_extras = jax.tree.map(lambda e: e[None], acc_extras)
        if lens:
            # [L, k_max, ...] member stacks — the exact shapes the vmapped
            # lane form returns, so callers handle both forms identically
            return acc_vars, acc_w, acc_loss, acc_tau, acc_extras, final[8][:3]
        return acc_vars, acc_w, acc_loss, acc_tau, acc_extras

    return lanes_train


def make_packed_cohort_train(
    bundle: ModelBundle,
    task: Task,
    n_pad: int,
    shape_key: tuple,
    *,
    compute_dtype=None,
    packed_conv: str = "off",
    key_slice: Optional[tuple] = None,
    **lane_kwargs,
) -> Callable:
    """Build the packed-cohort program (simulation paradigm) for one plan
    SHAPE: vmap of the lane program over all lanes.

    ``key_slice=(cohort_total, start)`` derives per-position keys as
    ``split(rng, cohort_total)[start:start + len(rows)]`` instead of
    ``split(rng, len(rows))`` — the streamed sub-cohort chunks (fedsched)
    use it so every client consumes the SAME per-round key it would under
    the whole-cohort program, keeping the canonical-replay contract intact
    across chunk boundaries.

    Returns ``packed_train(variables, tx, ty, tm, sampled_rows, weights_pos,
    rng, plan_arrays) -> (acc_vars, acc_w, acc_loss, acc_tau, extras)``
    summed over all lanes. Aggregate = ``acc_vars / acc_w``
    (elastic-guarded by the caller); ``extras`` is the summed
    ``reduce_extras`` partial tree ({} when the hook is absent) — the sim
    paradigm's counterpart of the mesh psum tail, so the full cross-silo
    hook contract (FedOpt/FedNova/AGC/robust) rides the packed schedule in
    BOTH paradigms. ``packed_conv`` selects the fedpack conv lowering for
    the lane axis (ops/packed_conv.py): 'off' keeps the per-lane vmap."""
    del shape_key  # lane count and shapes come in via the arrays
    lanes_fn = make_lanes_train(bundle, task, n_pad,
                                packed_conv=packed_conv, **lane_kwargs)

    def packed_train(variables, tx, ty, tm, sampled_rows, weights_pos, rng,
                     plan_arrays):
        """``tx/ty/tm``: the full stacked client arrays [C_total, n_pad, ...]
        (device-resident); ``sampled_rows`` [cohort] maps cohort position ->
        stack row; ``weights_pos`` [cohort] aggregation weights (count x
        live) by position; ``rng`` the round key (per-position keys derive
        exactly as in the unpacked paths: split(rng, cohort)[position])."""
        (slot, epoch_a, sie, reset, emit, live,
         member_pos, member_valid, steps_real) = plan_arrays
        if compute_dtype is not None and jnp.issubdtype(tx.dtype, jnp.floating):
            tx = tx.astype(compute_dtype)
        C = tx.shape[0]
        x_flat = tx.reshape((C * n_pad,) + tx.shape[2:])
        y_flat = ty.reshape((C * n_pad,) + ty.shape[2:])
        m_flat = tm.reshape((C * n_pad,))
        if key_slice is None:
            keys_full = jax.random.split(rng, sampled_rows.shape[0])
        else:
            total, start = key_slice
            keys_full = jax.random.split(rng, total)[
                start:start + sampled_rows.shape[0]]
        member_row = sampled_rows[member_pos]      # [n_lanes, k_max]
        member_keys = keys_full[member_pos]
        member_w = weights_pos[member_pos] * member_valid

        lanes = lanes_fn(variables, x_flat, y_flat, m_flat, tm,
                         member_row, member_keys, member_w, steps_real,
                         slot, epoch_a, sie, reset, emit, live)
        lens_out = None
        if len(lanes) == 6:                          # fedlens member stacks
            lens_out = lanes[5]
            lanes = lanes[:5]
        acc_vars, acc_w, acc_loss, acc_tau, extras = lanes
        # extras: [L] stacked (vmap form) or singleton-axis (joint form) —
        # sum(axis=0) reduces either to the cohort partial sums the
        # server_update hook consumes
        out = (jax.tree.map(lambda a: jnp.sum(a, axis=0), acc_vars),
               jnp.sum(acc_w), jnp.sum(acc_loss), jnp.sum(acc_tau),
               jax.tree.map(lambda e: jnp.sum(e, axis=0), extras))
        if lens_out is not None:
            # per-member stacks stay UNsummed ([L, k_max, ...], member_pos
            # order) + the matching member weights for the alignment basis
            out = out + (lens_out + (member_w,),)
        return out

    return packed_train


# --- masked lane freeze/exit (packed Silo early stopping) -------------------

def plan_arrays_tuple(plan: PackPlan) -> tuple:
    """The 9-array runtime tuple every packed round program takes, in the
    one canonical order (slot, epoch, sie, reset, emit, live, member_pos,
    member_valid, steps_real)."""
    return (plan.slot, plan.epoch, plan.sie, plan.reset, plan.emit,
            plan.live, plan.member_pos, plan.member_valid, plan.steps_real)


def mask_plan_arrays(plan: PackPlan, member_active: np.ndarray) -> tuple:
    """Masked plan arrays for per-client lane EXIT (Silo early stopping):
    a member whose ``member_active[lane, k]`` is 0 becomes a STRUCTURAL
    no-op — its steps run with ``live = 0`` (params/opt/stats frozen by
    the existing dead-step masks), its ``emit``/``member_valid`` zero out
    so it contributes nothing to the weighted aggregate, and ``reset`` is
    suppressed so the lane carries frozen state through the dead span to
    the next active member's reset. Shapes are UNCHANGED — the same
    compiled program executes, no recompile, no vmap fallback; the dead
    steps are the price of keeping the XLA program static (a re-pack
    would reclaim them at one recompile per exit wave).

    ``member_active``: [n_lanes, k_max] {0,1} per plan member."""
    act_m = np.asarray(member_active, np.float32)
    # each step's activity = its owning member's activity (dead lane-tail
    # steps index slot 0 but already carry live == 0, so the product below
    # cannot resurrect or kill them incorrectly)
    step_act = np.take_along_axis(act_m, plan.slot.astype(np.int64), axis=1)
    return (plan.slot, plan.epoch, plan.sie,
            (plan.reset * step_act).astype(plan.reset.dtype),
            (plan.emit * step_act).astype(plan.emit.dtype),
            (plan.live * step_act).astype(plan.live.dtype),
            plan.member_pos,
            (plan.member_valid * act_m).astype(plan.member_valid.dtype),
            plan.steps_real)


def mesh_member_active(plan: PackPlan, n_devices: int,
                       active_perm: np.ndarray) -> np.ndarray:
    """Per-(lane, member) activity for the MESH plan, whose ``member_pos``
    index LOCAL rows within each device's client block and whose lane axis
    is device-major [D * lanes_dev]. ``active_perm``: per-client {0,1} in
    plan (device-major perm) order."""
    ap = np.asarray(active_perm, np.float32)
    D = int(n_devices)
    rows = ap.reshape(D, -1)                       # [D, clients_per_device]
    lanes_dev = plan.n_lanes // D
    dev = np.repeat(np.arange(D), lanes_dev)       # lane -> device
    return rows[dev[:, None], plan.member_pos.astype(np.int64)]


# --- cross-silo mesh form ---------------------------------------------------

def pad_plan(plan: PackPlan, T: int, k_max: int, n_lanes: int) -> PackPlan:
    """Pad a plan to shared (n_lanes, k_max, T) so per-device plans form one
    SPMD-uniform program (extra steps/members/lanes are dead: live 0,
    member_valid 0)."""

    def pad2(a, rows, cols, fill=0):
        out = np.full((rows, cols), fill, a.dtype)
        out[: a.shape[0], : a.shape[1]] = a
        return out

    return PackPlan(
        n_lanes, k_max, T, plan.epochs,
        pad2(plan.slot, n_lanes, T), pad2(plan.epoch, n_lanes, T),
        pad2(plan.sie, n_lanes, T), pad2(plan.reset, n_lanes, T),
        pad2(plan.emit, n_lanes, T), pad2(plan.live, n_lanes, T),
        pad2(plan.member_pos, n_lanes, k_max),
        pad2(plan.member_valid, n_lanes, k_max),
        pad2(plan.steps_real, n_lanes, k_max, fill=1),
    )


def plan_packing_mesh(counts: np.ndarray, batch_size: int, epochs: int,
                      n_devices: int, lanes_per_device: int,
                      t_quantum: int = 1):
    """Mesh packing: deal clients to devices by capacity-constrained LPT
    (biggest client first to the least-loaded device with a free row — see
    the inline comment for why this beats the `_mesh_group_plan` strip
    deal here), pack each device's clients into its own lanes, and pad
    every per-device plan to shared shapes (SPMD: one program, all
    devices).

    Returns ``(perm, plan)`` or None: ``perm`` is the device-major client
    order for data placement (device d's block = perm[d*L:(d+1)*L]); the
    plan's lane axis is device-major [D*lanes_dev, ...] to be sharded along
    the mesh axis; ``member_pos`` index LOCAL rows within a device block.
    """
    counts = np.asarray(counts, np.float64)
    C = len(counts)
    D = int(n_devices)
    if C % D or C // D < 1:
        return None
    L = C // D
    # capacity-constrained LPT: biggest client first, to the least-loaded
    # device that still has a free row — the whale client's device gets the
    # smallest co-residents, so T (= max device load = the round's critical
    # path) approaches the whale bound instead of stacking big clients
    # together the way a count-sorted strip deal does
    cost = epochs * np.ceil(np.maximum(counts, 0.0) / batch_size)
    order = np.argsort(-cost, kind="stable")
    loads = np.zeros(D)
    dev_clients = [[] for _ in range(D)]
    for j in order:
        free = [d for d in range(D) if len(dev_clients[d]) < L]
        d = min(free, key=lambda i: loads[i])
        dev_clients[d].append(int(j))
        loads[d] += cost[j]
    dev_clients = [np.asarray(m, np.int64) for m in dev_clients]
    plans = []
    for d in range(D):
        p = plan_packing(counts[dev_clients[d]], batch_size, epochs,
                         lanes_per_device, t_quantum=t_quantum)
        if p is None:
            return None
        plans.append(p)
    T = max(p.T for p in plans)
    k_max = max(p.k_max for p in plans)
    n_lanes_dev = max(p.n_lanes for p in plans)
    plans = [pad_plan(p, T, k_max, n_lanes_dev) for p in plans]

    def cat(field):
        return np.concatenate([getattr(p, field) for p in plans], axis=0)

    plan = PackPlan(
        D * n_lanes_dev, k_max, T, epochs,
        cat("slot"), cat("epoch"), cat("sie"), cat("reset"), cat("emit"),
        cat("live"), cat("member_pos"), cat("member_valid"), cat("steps_real"),
    )
    return np.concatenate(dev_clients), plan


def make_crosssilo_packed_round(
    bundle: ModelBundle,
    task: Task,
    n_pad: int,
    mesh,
    axis: str = "clients",
    *,
    compute_dtype=None,
    packed_conv: str = "off",
    client_transform: Optional[Callable] = None,
    reduce_extras: Optional[Callable] = None,
    server_update: Optional[Callable] = None,
    **lane_kwargs,
) -> Callable:
    """Mesh form of the packed schedule: each device runs its lanes (vmap of
    the SAME lane program the simulation paradigm uses), and ONE weighted
    psum tail aggregates all lanes' accumulators — the packed counterpart of
    `make_crosssilo_round_grouped`, with the group-max padding replaced by
    one-batch-granularity lanes.

    The three hooks are the cross-silo contract (make_crosssilo_round):
    client_transform / reduce_extras apply per client at lane emit;
    server_update runs post-psum on replicated values — so the whole
    algorithm zoo (FedOpt/FedNova/AGC/robust) rides the packed schedule.

    Returns ``round_fn(variables, server_state, tx, ty, tm, weights, perm,
    rng, plan_arrays) -> (variables, server_state, loss)`` where
    tx/ty/tm/weights are stacked in PLAN ORDER (device-major perm from
    `plan_packing_mesh`) and sharded along ``axis``, plan_arrays are the
    PackPlan arrays (lane axis sharded along ``axis``), and
    variables/server_state/rng are replicated.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from fedml_tpu.parallel.crosssilo import apply_server_and_rollback

    # fedpack: the per-device lane block runs the joint stacked-lane form
    # when packed_conv is on (same psum tail either way — the joint form
    # returns the same stacked accumulators)
    lanes_fn = make_lanes_train(bundle, task, n_pad,
                                packed_conv=packed_conv,
                                client_transform=client_transform,
                                reduce_extras=reduce_extras, **lane_kwargs)

    def shard_fn(variables, server_state, tx, ty, tm, weights, keys,
                 plan_arrays, rng):
        (slot, epoch_a, sie, reset, emit, live,
         member_pos, member_valid, steps_real) = plan_arrays
        variables0 = variables
        variables = jax.tree.map(
            lambda x: jax.lax.pcast(x, axis_name=axis, to="varying"), variables
        )
        L = tx.shape[0]
        x_flat = tx.reshape((L * n_pad,) + tx.shape[2:])
        y_flat = ty.reshape((L * n_pad,) + ty.shape[2:])
        m_flat = tm.reshape((L * n_pad,))
        member_keys = keys[member_pos]
        member_w = weights[member_pos] * member_valid

        acc_vars, acc_w, acc_loss, _tau, acc_extras = lanes_fn(
            variables, x_flat, y_flat, m_flat, tm,
            member_pos, member_keys, member_w, steps_real,
            slot, epoch_a, sie, reset, emit, live)

        acc_vars = jax.tree.map(
            lambda a: jax.lax.psum(jnp.sum(a, axis=0), axis), acc_vars)
        total = jax.lax.psum(jnp.sum(acc_w), axis)
        loss_sum = jax.lax.psum(jnp.sum(acc_loss), axis)
        denom = jnp.maximum(total, 1e-12)
        agg = jax.tree.map(
            lambda a, v: (a / denom).astype(v.dtype), acc_vars, variables0)
        extras = None
        if reduce_extras is not None:
            extras = jax.tree.map(
                lambda e: jax.lax.psum(jnp.sum(e, axis=0), axis), acc_extras)
        new_vars, new_state = apply_server_and_rollback(
            variables0, agg, extras, total, server_state, rng, server_update)
        return new_vars, new_state, loss_sum / denom

    p_plan = tuple(P(axis) for _ in range(9))
    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis), P(axis), P(axis),
                  p_plan, P()),
        out_specs=(P(), P(), P()),
    )

    def round_fn(variables, server_state, tx, ty, tm, weights, perm, rng,
                 plan_arrays):
        """``perm``: the device-major client order from plan_packing_mesh —
        every client keeps the per-round key of its ORIGINAL index (same
        rule as the grouped mesh schedule), so the packing changes only the
        padding, never which randomness a client consumes."""
        if compute_dtype is not None and jnp.issubdtype(tx.dtype, jnp.floating):
            tx = tx.astype(compute_dtype)
        keys = jax.random.split(rng, weights.shape[0])[perm]
        return mapped(variables, server_state, tx, ty, tm, weights, keys,
                      plan_arrays, rng)

    jitted = jax.jit(round_fn)
    # the super-step (fedavg.py _packed_superstep_fn) scans the round body;
    # scanning the JITTED form would drag the resident data into the while
    # carry (measured: per-iteration full-tensor copies, 14-28x slower
    # through the remote device) — it must trace the raw body instead
    jitted.raw = round_fn
    return jitted
