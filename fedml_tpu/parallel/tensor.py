"""Tensor (intra-layer model) parallelism for the transformer LM.

The reference predates LLM-era parallelism entirely (SURVEY.md §2.6) — TP
exists here because the TPU-native framework treats long-context/LLM
training as first-class. The scheme is the Megatron split expressed purely
through GSPMD placement: no model surgery, no manual collectives.

- ``qkv`` projection kernel ``[D, 3D]`` shards its OUTPUT dim over 'tp'
  (each device computes a head subset), ``attn.out`` kernel ``[D, D]``
  shards its INPUT dim (row-parallel) so the matmul's partial results
  all-reduce once per attention block.
- MLP up-projection ``[D, 4D]`` is column-parallel, down-projection
  ``[4D, D]`` row-parallel — one all-reduce per MLP.
- everything else (embeddings, layernorms, lm_head, biases of row-parallel
  layers) stays replicated.

XLA's sharding propagation inserts exactly the Megatron communication
pattern from these parameter placements; the step function itself is the
unmodified single-device step, so TP results equal single-device results
to float tolerance (tested).
"""

from __future__ import annotations

import re
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Megatron TP placement: (path regex, spec builder) — first match wins;
#: default replicated.
_TP_RULES = (
    (re.compile(r"attn.*qkv.*kernel"), lambda tp: P(None, tp)),
    (re.compile(r"attn.*qkv.*bias"), lambda tp: P(tp)),
    (re.compile(r"attn.*out.*kernel"), lambda tp: P(tp, None)),
    (re.compile(r"Dense_0.*kernel"), lambda tp: P(None, tp)),   # MLP up
    (re.compile(r"Dense_0.*bias"), lambda tp: P(tp)),
    (re.compile(r"Dense_1.*kernel"), lambda tp: P(tp, None)),   # MLP down
)

#: expert-parallel placement: stacked expert weights [E, ...] shard their
#: leading (expert) axis; router + everything else replicated.
_EP_RULES = (
    (re.compile(r"moe.*w_(up|dn)"), lambda ep: P(ep)),
    (re.compile(r"moe.*b_(up|dn)"), lambda ep: P(ep)),
)


def _spec_for(rules, path: str, axis: str) -> P:
    for rx, spec in rules:
        if rx.search(path):
            return spec(axis)
    return P()


def _shard_params(variables, mesh: Mesh, rules, axis: str):
    def place(path, leaf):
        spec = _spec_for(rules, jax.tree_util.keystr(path), axis)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, variables)


def _mesh2d(n_dp: int, n_other: int, other_axis: str) -> Mesh:
    devs = jax.devices()
    need = n_dp * n_other
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(n_dp, n_other),
                ("dp", other_axis))


def tp_spec(path: str, tp_axis: str = "tp") -> P:
    """Megatron PartitionSpec for one parameter path (default replicated)."""
    return _spec_for(_TP_RULES, path, tp_axis)


def shard_params_tp(variables, mesh: Mesh, tp_axis: str = "tp"):
    """device_put the variable tree with Megatron TP shardings over
    ``mesh``'s 'tp' axis. Heads and MLP hidden must divide the axis size."""
    return _shard_params(variables, mesh, _TP_RULES, tp_axis)


def tp_mesh(n_dp: int, n_tp: int) -> Mesh:
    """2-D (dp, tp) mesh: batch over dp, tensor-parallel over tp (keep tp
    ICI-adjacent — it all-reduces twice per layer)."""
    return _mesh2d(n_dp, n_tp, "tp")


def ep_spec(path: str, ep_axis: str = "ep") -> P:
    """Expert-parallel PartitionSpec for one parameter path."""
    return _spec_for(_EP_RULES, path, ep_axis)


def shard_params_ep(variables, mesh: Mesh, ep_axis: str = "ep"):
    """device_put a MoeTransformerLM variable tree with the expert axis of
    every expert weight sharded over ``mesh``'s 'ep' axis — each device
    stores (and computes) only its experts. num_experts must divide the
    axis size."""
    return _shard_params(variables, mesh, _EP_RULES, ep_axis)


def ep_mesh(n_dp: int, n_ep: int) -> Mesh:
    """2-D (dp, ep) mesh: batch over dp, experts over ep."""
    return _mesh2d(n_dp, n_ep, "ep")


def make_tp_lm_train_step(
    module, tx: optax.GradientTransformation, mesh: Mesh,
) -> Callable:
    """Build an LM train step whose parallelism comes entirely from
    placement: call ``shard_params_tp(variables, mesh)`` once (the optax
    state inherits the shardings via ``tx.init`` on the sharded params) and
    pass batches with the batch axis on 'dp'. Returns
    ``step(variables, opt_state, x, y, mask, rng)``; use
    ``attn_impl='xla'`` modules so attention stays partitionable.
    """
    from fedml_tpu.ops.xent import masked_cross_entropy

    data_shard = NamedSharding(mesh, P("dp", None))

    def step(variables, opt_state, x, y, mask, rng):
        def loss_fn(params):
            vars_in = dict(variables)
            vars_in["params"] = params
            logits = module.apply(vars_in, x, train=True, rngs={"dropout": rng})
            per = masked_cross_entropy(logits, y, mask)
            cnt = jnp.sum(mask.astype(jnp.float32))
            return jnp.sum(per) / jnp.maximum(cnt, 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        updates, new_opt = tx.update(grads, opt_state, variables["params"])
        new_params = optax.apply_updates(variables["params"], updates)
        out = dict(variables)
        out["params"] = new_params
        return out, new_opt, loss

    jitted = jax.jit(step, donate_argnums=(0, 1))

    def run(variables, opt_state, x, y, mask, rng):
        x = jax.device_put(x, data_shard)
        y = jax.device_put(y, data_shard)
        mask = jax.device_put(mask, data_shard)
        return jitted(variables, opt_state, x, y, mask, rng)

    run.mesh = mesh
    return run
