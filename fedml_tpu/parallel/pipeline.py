"""Pipeline (inter-layer model) parallelism for the transformer LM.

The reference's only pipeline cut is the 2-stage SplitNN activation relay
(split_nn/client.py:24-34, server.py:40-60 — per-batch acts/grads over
MPI). The TPU-native generalisation is an N-stage GPipe schedule expressed
inside ONE jitted program over a ('dp', 'pp') mesh:

- the L transformer blocks are stacked on a leading [L] axis and that axis
  is sharded over 'pp' — each device stores and runs ``L / S`` blocks;
- a microbatched forward runs ``M + S - 1`` ticks of ``lax.scan``; every
  tick each stage applies its blocks to its current slot and hands the
  activation to the next stage with a single ``ppermute`` hop (ICI
  neighbour traffic, no host round-trips — the whole schedule is one XLA
  program, unlike the reference's one-message-per-microbatch protocol);
- embeddings/head stay replicated: embedding gradients flow only on stage
  0 and head gradients only on stage S-1 (everything else is masked out of
  the loss), so a final psum over 'pp' reconstructs full replicated grads;
- backward is just ``jax.grad`` through the scan — ``ppermute``'s
  transpose is the reverse rotation, so XLA derives the 1F1B-style reverse
  schedule automatically.

Exactness: with the same params/batch, loss and the updated params equal
the single-device step to float tolerance (tested in
tests/test_pipeline.py) — the pipeline only reorders compute.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import flax.linen as nn


def pp_mesh(n_dp: int, n_pp: int) -> Mesh:
    """2-D (dp, pp) mesh: batch over dp, layer stages over pp."""
    devs = jax.devices()
    need = n_dp * n_pp
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(n_dp, n_pp), ("dp", "pp"))


def stack_pipeline_params(variables, layers: int):
    """Regroup TransformerLM params: per-block subtrees ``block0..block{L-1}``
    stack onto a leading [L] axis (shardable over 'pp'); everything else
    (embeddings, final LayerNorm, lm_head) goes to a replicated 'outer'."""
    outer = dict(variables["params"])
    blocks = [outer.pop(f"block{i}") for i in range(layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return {"outer": outer, "blocks": stacked}


def unstack_pipeline_params(pp_params, layers: int):
    """Inverse of :func:`stack_pipeline_params` → TransformerLM variables."""
    params = dict(pp_params["outer"])
    for i in range(layers):
        params[f"block{i}"] = jax.tree_util.tree_map(
            lambda x, i=i: x[i], pp_params["blocks"])
    return {"params": params}


#: shard_map / device_put spec prefix for the pipeline param pytree.
PP_PARAM_SPECS = {"outer": P(), "blocks": P("pp")}


def place_pp_params(pp_params, mesh: Mesh):
    """Put block stacks on their stages, replicate the outer params."""
    return {
        "outer": jax.device_put(
            pp_params["outer"], NamedSharding(mesh, P())),
        "blocks": jax.device_put(
            pp_params["blocks"], NamedSharding(mesh, P("pp"))),
    }


def make_pp_lm_train_step(
    module, tx, mesh: Mesh, *, n_micro: Optional[int] = None,
    attn_impl: str = "auto",
) -> Callable:
    """Build a jitted GPipe train step over a ('dp', 'pp') mesh.

    ``module`` is a TransformerLM (no ring_axis — the sequence stays whole;
    compose with SP by nesting meshes if both are needed), ``tx`` an optax
    transformation. Returns ``step(pp_params, opt_state, x, y, mask) ->
    (pp_params, opt_state, loss)``; ``x/y/mask [B, T]`` shard over 'dp',
    each dp shard is further split into ``n_micro`` microbatches that flow
    through the stage ring. ``module.layers`` must divide evenly into
    ``mesh.shape['pp']`` stages.
    """
    from jax import shard_map

    from fedml_tpu.ops.xent import masked_cross_entropy

    S = mesh.shape["pp"]
    M = n_micro or S
    if module.layers % S:
        raise ValueError(f"layers ({module.layers}) not divisible by pp ({S})")
    if module.dropout:
        raise ValueError("pipeline step runs eval-mode blocks; dropout "
                         "must be 0 (reference LMs train without dropout)")

    from fedml_tpu.models.transformer import Block as _Block

    block_mod = _Block(module.dim, module.heads, module.mlp_ratio, 0.0,
                       attn_impl, dtype=module.dtype)

    def stage_apply(block_params, h):
        """Run this stage's L/S blocks (stacked leading axis) in order."""
        def body(h, p):
            return block_mod.apply({"params": p}, h, False), None

        h, _ = lax.scan(body, h, block_params)
        return h

    def embed(outer, xm):
        tok = outer["tok_embed"]["embedding"]
        pos = outer["pos_embed"]["embedding"]
        t = xm.shape[-1]
        h = tok[xm.astype(jnp.int32)] + pos[jnp.arange(t)][None]
        return h.astype(module.dtype)

    def head(outer, h):
        h = nn.LayerNorm(dtype=module.dtype).apply(
            {"params": outer["LayerNorm_0"]}, h)
        return (h.astype(jnp.float32) @ outer["lm_head"]["kernel"]
                + outer["lm_head"]["bias"])

    ring = [(i, (i + 1) % S) for i in range(S)]

    def grad_fn(pp_params, x, y, mask):
        stage = lax.axis_index("pp")
        last = (stage == S - 1).astype(jnp.float32)
        # global token count OUTSIDE the differentiated graph: psum's
        # transpose is psum, so a scalar psum inside loss_fn would scale
        # every cotangent by the mesh size (same fix as sequence.py).
        total = lax.psum(last * jnp.sum(mask.astype(jnp.float32)),
                         ("dp", "pp"))

        def loss_fn(pp_params):
            outer, blocks = pp_params["outer"], pp_params["blocks"]
            b, t = x.shape
            if b % M:
                raise ValueError(
                    f"per-dp-shard batch ({b}) not divisible by "
                    f"n_micro ({M}); pick a global batch that is a "
                    f"multiple of n_dp * n_micro")
            mb = b // M
            xm = x.reshape(M, mb, t)
            h0 = embed(outer, xm)                      # [M, mb, T, D]
            state0 = jnp.zeros_like(h0[0])
            ys0 = jnp.zeros_like(h0)

            def tick(carry, tk):
                state, ys = carry
                inp = h0[jnp.minimum(tk, M - 1)]
                sin = jnp.where(stage == 0, inp, state)
                out = stage_apply(blocks, sin)
                oidx = jnp.clip(tk - (S - 1), 0, M - 1)
                write = (stage == S - 1) & (tk >= S - 1)
                cur = lax.dynamic_index_in_dim(ys, oidx, 0, keepdims=False)
                ys = lax.dynamic_update_index_in_dim(
                    ys, jnp.where(write, out, cur), oidx, 0)
                nxt = lax.ppermute(out, "pp", ring)
                return (nxt, ys), None

            (_, ys), _ = lax.scan(tick, (state0, ys0),
                                  jnp.arange(M + S - 1))
            logits = head(outer, ys.reshape(b, t, -1))
            per = masked_cross_entropy(logits, y, mask, impl="xla")
            return last * jnp.sum(per) / jnp.maximum(total, 1.0)

        local_loss, grads = jax.value_and_grad(loss_fn)(pp_params)
        loss = lax.psum(local_loss, ("dp", "pp"))
        # local_loss divides by the GLOBAL token count, so grads are per-device
        # contributions: outer grads live only on their owning stage (embed
        # on 0, head on S-1) — sum over 'pp' replicates them; block grads
        # stay stage-local (their [L/S] shard IS the full grad) and only
        # sum over 'dp'.
        return loss, {
            "outer": lax.psum(grads["outer"], ("dp", "pp")),
            "blocks": lax.psum(grads["blocks"], "dp"),
        }

    grad_shard = shard_map(
        grad_fn, mesh=mesh,
        in_specs=(PP_PARAM_SPECS, P("dp"), P("dp"), P("dp")),
        out_specs=(P(), PP_PARAM_SPECS),
        check_vma=False,
    )

    @jax.jit
    def step(pp_params, opt_state, x, y, mask):
        loss, grads = grad_shard(pp_params, x, y, mask)
        updates, new_opt = tx.update(grads, opt_state, pp_params)
        return optax.apply_updates(pp_params, updates), new_opt, loss

    return step
