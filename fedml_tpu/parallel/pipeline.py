"""Pipeline (inter-layer model) parallelism for the transformer LM.

The reference's only pipeline cut is the 2-stage SplitNN activation relay
(split_nn/client.py:24-34, server.py:40-60 — per-batch acts/grads over
MPI). The TPU-native generalisation is an N-stage GPipe schedule expressed
inside ONE jitted program over a ('dp', 'pp') mesh:

- the L transformer blocks are stacked on a leading [L] axis and that axis
  is sharded over 'pp' — each device stores and runs ``L / S`` blocks;
- a microbatched forward runs ``M + S - 1`` ticks of ``lax.scan``; every
  tick each stage applies its blocks to its current slot and hands the
  activation to the next stage with a single ``ppermute`` hop (ICI
  neighbour traffic, no host round-trips — the whole schedule is one XLA
  program, unlike the reference's one-message-per-microbatch protocol);
- embeddings/head stay replicated: embedding gradients flow only on stage
  0 and head gradients only on stage S-1 (everything else is masked out of
  the loss), so a final psum over 'pp' reconstructs full replicated grads;
- backward is just ``jax.grad`` through the scan — ``ppermute``'s
  transpose is the reverse rotation, so XLA derives the 1F1B-style reverse
  schedule automatically.

Exactness: with the same params/batch, loss and the updated params equal
the single-device step to float tolerance (tested in
tests/test_pipeline.py) — the pipeline only reorders compute.

:func:`make_pp_sp_lm_train_step` extends the same schedule to a 3-D
('dp', 'pp', 'sp') mesh: activations are additionally sequence-sharded
and each stage's blocks run ring/Ulysses attention over 'sp', so K/V hop
the sequence ring while microbatches hop the stage ring — both inside one
program. Both step builders share one implementation (:func:`_make_pp_step`;
the 2-D step is the n_sp=1 case).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import flax.linen as nn


def pp_mesh(n_dp: int, n_pp: int) -> Mesh:
    """2-D (dp, pp) mesh: batch over dp, layer stages over pp."""
    devs = jax.devices()
    need = n_dp * n_pp
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(n_dp, n_pp), ("dp", "pp"))


def stack_pipeline_params(variables, layers: int):
    """Regroup TransformerLM params: per-block subtrees ``block0..block{L-1}``
    stack onto a leading [L] axis (shardable over 'pp'); everything else
    (embeddings, final LayerNorm, lm_head) goes to a replicated 'outer'."""
    outer = dict(variables["params"])
    blocks = [outer.pop(f"block{i}") for i in range(layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return {"outer": outer, "blocks": stacked}


def unstack_pipeline_params(pp_params, layers: int):
    """Inverse of :func:`stack_pipeline_params` → TransformerLM variables."""
    params = dict(pp_params["outer"])
    for i in range(layers):
        params[f"block{i}"] = jax.tree_util.tree_map(
            lambda x, i=i: x[i], pp_params["blocks"])
    return {"params": params}


#: shard_map / device_put spec prefix for the pipeline param pytree.
PP_PARAM_SPECS = {"outer": P(), "blocks": P("pp")}


def place_pp_params(pp_params, mesh: Mesh):
    """Put block stacks on their stages, replicate the outer params."""
    return {
        "outer": jax.device_put(
            pp_params["outer"], NamedSharding(mesh, P())),
        "blocks": jax.device_put(
            pp_params["blocks"], NamedSharding(mesh, P("pp"))),
    }


def _make_pp_step(module, tx, mesh: Mesh, n_micro: Optional[int],
                  attn_impl: str, sp_axis: Optional[str], sp_mode: str,
                  xent_impl: str = "auto"):
    """Shared GPipe schedule builder. With ``sp_axis=None`` this is plain
    (dp, pp); with ``sp_axis='sp'`` every activation tile is additionally
    sequence-sharded and each Block runs ring/Ulysses attention over that
    axis — the 2-D step is exactly the n_sp=1 case."""
    from jax import shard_map

    from fedml_tpu.models.transformer import Block as _Block
    from fedml_tpu.ops.xent import masked_cross_entropy

    S = mesh.shape["pp"]
    n_sp = mesh.shape[sp_axis] if sp_axis else 1
    M = n_micro or S
    if module.layers % S:
        raise ValueError(f"layers ({module.layers}) not divisible by pp ({S})")
    if module.dropout:
        raise ValueError("pipeline step runs eval-mode blocks; dropout "
                         "must be 0 (reference LMs train without dropout)")

    block_mod = _Block(module.dim, module.heads, module.mlp_ratio, 0.0,
                       attn_impl,
                       sp_axis if n_sp > 1 else None, n_sp, sp_mode,
                       dtype=module.dtype)
    axes = ("dp", "pp") + ((sp_axis,) if sp_axis else ())
    block_axes = ("dp",) + ((sp_axis,) if sp_axis else ())

    def stage_apply(block_params, h):
        """Run this stage's L/S blocks (stacked leading axis) in order."""
        def body(h, p):
            return block_mod.apply({"params": p}, h, False), None

        h, _ = lax.scan(body, h, block_params)
        return h

    def embed(outer, xm, pos_start):
        tok = outer["tok_embed"]["embedding"]
        pos = outer["pos_embed"]["embedding"]
        tl = xm.shape[-1]
        h = tok[xm.astype(jnp.int32)] + pos[pos_start + jnp.arange(tl)][None]
        return h.astype(module.dtype)

    def head(outer, h):
        h = nn.LayerNorm(dtype=module.dtype).apply(
            {"params": outer["LayerNorm_0"]}, h)
        return (h.astype(jnp.float32) @ outer["lm_head"]["kernel"]
                + outer["lm_head"]["bias"])

    ring = [(i, (i + 1) % S) for i in range(S)]

    def grad_fn(pp_params, x, y, mask):
        stage = lax.axis_index("pp")
        last = (stage == S - 1).astype(jnp.float32)
        pos_start = (lax.axis_index(sp_axis) * x.shape[1]) if sp_axis else 0
        # global token count OUTSIDE the differentiated graph: psum's
        # transpose is psum, so a scalar psum inside loss_fn would scale
        # every cotangent by the mesh size (same fix as sequence.py).
        total = lax.psum(last * jnp.sum(mask.astype(jnp.float32)), axes)

        def loss_fn(pp_params):
            outer, blocks = pp_params["outer"], pp_params["blocks"]
            b, tl = x.shape            # local: batch/dp rows, seq(/sp) tokens
            if b % M:
                raise ValueError(
                    f"per-dp-shard batch ({b}) not divisible by "
                    f"n_micro ({M}); pick a global batch that is a "
                    f"multiple of n_dp * n_micro")
            mb = b // M
            xm = x.reshape(M, mb, tl)
            h0 = embed(outer, xm, pos_start)           # [M, mb, Tl, D]
            state0 = jnp.zeros_like(h0[0])
            ys0 = jnp.zeros_like(h0)

            def tick(carry, tk):
                state, ys = carry
                inp = h0[jnp.minimum(tk, M - 1)]
                sin = jnp.where(stage == 0, inp, state)
                out = stage_apply(blocks, sin)
                oidx = jnp.clip(tk - (S - 1), 0, M - 1)
                write = (stage == S - 1) & (tk >= S - 1)
                cur = lax.dynamic_index_in_dim(ys, oidx, 0, keepdims=False)
                ys = lax.dynamic_update_index_in_dim(
                    ys, jnp.where(write, out, cur), oidx, 0)
                nxt = lax.ppermute(out, "pp", ring)
                return (nxt, ys), None

            (_, ys), _ = lax.scan(tick, (state0, ys0),
                                  jnp.arange(M + S - 1))

            # The LM head ((b,tl,D) x (D,V) matmul + cross-entropy) only
            # produces signal on the last stage (ys stays zeros elsewhere),
            # but ``stage`` is dynamic inside shard_map so XLA cannot DCE
            # it — run it under lax.cond so the other S-1 stages execute
            # the trivial branch at runtime instead of a junk matmul
            # (matters at real vocab sizes; the fused pallas xent is
            # selected by ``xent_impl`` like everywhere else in the stack).
            def last_stage_loss_sum():
                logits = head(outer, ys.reshape(b, tl, -1))
                per = masked_cross_entropy(logits, y, mask, impl=xent_impl)
                return jnp.sum(per)

            s = lax.cond(stage == S - 1, last_stage_loss_sum,
                         lambda: jnp.zeros((), jnp.float32))
            return s / jnp.maximum(total, 1.0)

        local_loss, grads = jax.value_and_grad(loss_fn)(pp_params)
        loss = lax.psum(local_loss, axes)
        # local_loss divides by the GLOBAL token count, so grads are
        # per-device contributions: outer grads live only on their owning
        # stage (embed on 0, head on S-1) — sum over every axis replicates;
        # block grads stay stage-local (their [L/S] shard IS the full grad
        # for those layers) and sum over the data(+sequence) axes only.
        return loss, {
            "outer": lax.psum(grads["outer"], axes),
            "blocks": lax.psum(grads["blocks"], block_axes),
        }

    data_spec = P("dp", sp_axis) if sp_axis else P("dp")
    grad_shard = shard_map(
        grad_fn, mesh=mesh,
        in_specs=(PP_PARAM_SPECS, data_spec, data_spec, data_spec),
        out_specs=(P(), PP_PARAM_SPECS),
        check_vma=False,
    )

    @jax.jit
    def step(pp_params, opt_state, x, y, mask):
        loss, grads = grad_shard(pp_params, x, y, mask)
        updates, new_opt = tx.update(grads, opt_state, pp_params)
        return optax.apply_updates(pp_params, updates), new_opt, loss

    return step


def make_pp_lm_train_step(
    module, tx, mesh: Mesh, *, n_micro: Optional[int] = None,
    attn_impl: str = "auto", xent_impl: str = "auto",
) -> Callable:
    """Build a jitted GPipe train step over a ('dp', 'pp') mesh.

    ``module`` is a TransformerLM (the sequence stays whole; use
    :func:`make_pp_sp_lm_train_step` to also shard it), ``tx`` an optax
    transformation. Returns ``step(pp_params, opt_state, x, y, mask) ->
    (pp_params, opt_state, loss)``; ``x/y/mask [B, T]`` shard over 'dp',
    each dp shard is further split into ``n_micro`` microbatches that flow
    through the stage ring. ``module.layers`` must divide evenly into
    ``mesh.shape['pp']`` stages.
    """
    return _make_pp_step(module, tx, mesh, n_micro, attn_impl,
                         sp_axis=None, sp_mode="ring", xent_impl=xent_impl)


def pp3d_mesh(n_dp: int, n_pp: int, n_sp: int) -> Mesh:
    """('dp', 'pp', 'sp') mesh: batch x pipeline stages x sequence."""
    devs = jax.devices()
    need = n_dp * n_pp * n_sp
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(n_dp, n_pp, n_sp),
                ("dp", "pp", "sp"))


def make_pp_sp_lm_train_step(
    module, tx, mesh: Mesh, *, n_micro: Optional[int] = None,
    attn_impl: str = "auto", sp_mode: str = "ring", xent_impl: str = "auto",
) -> Callable:
    """GPipe pipeline with sequence-parallel attention INSIDE each stage —
    DeepSpeed-style 3-D (dp, pp, sp) parallelism as ONE jitted program.

    The stacked blocks shard over 'pp' exactly as in
    :func:`make_pp_lm_train_step`; additionally every activation tile is
    sequence-sharded over 'sp', and each Block runs ring (or Ulysses)
    attention whose K/V hop the 'sp' axis while microbatches hop the 'pp'
    axis — both collectives ride ICI neighbours inside the same lax.scan.
    Exact vs the single-device step (tested on a (2,2,2) CPU mesh).

    ``x/y/mask [B, T]`` shard as P('dp', 'sp'); ``module`` is a plain
    TransformerLM config (its ring fields are overridden here).
    """
    return _make_pp_step(module, tx, mesh, n_micro, attn_impl,
                         sp_axis="sp", sp_mode=sp_mode, xent_impl=xent_impl)
