"""Per-client local training as one compiled program.

The reference's innermost hot loop is Python: for epoch / for batch /
loss.backward() / optimizer.step() (my_model_trainer_classification.py:19-53),
with a host->device transfer per batch and a .cpu() state-dict copy per client
(:12-14). Here the WHOLE local training run — E epochs of S minibatch steps
with per-epoch reshuffling — is a single jitted ``lax.scan`` program, so one
dispatch trains a client, and ``vmap``/``shard_map`` of the same function
trains a whole cohort.

Supports every trainer variant the algorithms need:
- plain SGD/momentum/Adam (OptRepo counterpart is optax, fedopt/optrepo.py),
- local gradient clipping (reference clips at 1.0, my_model_trainer:40),
- FedProx proximal term mu/2 ||w - w_global||^2 — the term the reference
  advertises but never implements (SURVEY.md §2.2 FedProx WARNING),
- step counting (tau) for FedNova normalized averaging.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core.pytree import Pytree, tree_dot, tree_sub
from fedml_tpu.core.tasks import Task
from fedml_tpu.models import ModelBundle

# Salt folded into each epoch key to derive the per-step batch keys. The
# packed schedule (parallel/packed.py) replays each client's trajectory
# bit-for-bit and must derive the SAME keys — it imports this constant, so
# the two paths cannot silently desynchronize (advisor r4 #1).
EPOCH_KEY_SALT = 0x5BA7


def make_optimizer(
    name: str, lr: float, momentum: float = 0.0, wd: float = 0.0
) -> optax.GradientTransformation:
    """Client optimizer factory; torch semantics (wd folded into the gradient
    before momentum/moments, like torch.optim.SGD/Adam weight_decay). The
    reference resolves optimizers by reflection over torch.optim subclasses
    (fedopt/optrepo.py:11-39); optax names fill that role."""
    chain = []
    if wd:
        chain.append(optax.add_decayed_weights(wd))
    name = name.lower()
    if name == "sgd":
        chain.append(optax.sgd(lr, momentum=momentum if momentum else None))
    elif name == "adam":
        # reference uses amsgrad=True for client Adam (my_model_trainer.py:28-29)
        chain.append(optax.amsgrad(lr))
    elif name == "adamw":
        chain.append(optax.adamw(lr))
    elif name == "adagrad":
        chain.append(optax.adagrad(lr))
    elif name == "yogi":
        chain.append(optax.yogi(lr))
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    return optax.chain(*chain)


def local_train_kwargs(config) -> dict:
    """The ONE config -> make_local_train_fn kwargs mapping. Every consumer
    of make_local_train_fn (the algorithm APIs via
    FedAvgAPI._local_train_kwargs, the edge trainers, the centralized
    baseline) goes through here so a new config knob cannot be silently
    dropped by one call site."""
    return dict(
        optimizer=config.client_optimizer, lr=config.lr,
        momentum=config.momentum, wd=config.wd,
        epochs=config.epochs, batch_size=config.batch_size,
        grad_clip=config.grad_clip,
        compute_dtype=jnp.bfloat16 if config.dtype == "bfloat16" else None,
        scan_unroll=config.scan_unroll,
    )


class LocalResult(NamedTuple):
    variables: dict       # updated model variables (params [+ batch_stats])
    train_loss: jax.Array  # mean loss over the last epoch
    tau: jax.Array         # number of optimizer steps taken (FedNova)
    #: mean loss over the FIRST local epoch (the fedlens loss-delta basis:
    #: first - last > 0 means local training still makes progress). Optional
    #: so existing positional LocalResult(...) constructions keep working;
    #: jit dead-code-eliminates it wherever the lens is off.
    first_loss: Optional[jax.Array] = None


def make_batch_sgd_step(
    bundle: ModelBundle,
    task: Task,
    tx: optax.GradientTransformation,
    *,
    grad_clip: Optional[float] = None,
    prox_mu: float = 0.0,
    compute_dtype=None,
):
    """ONE minibatch SGD step — the single definition of the per-batch
    update both execution forms share: ``make_local_train_fn`` scans it (with
    dead-step freezing around it) and the streaming paradigm
    (algorithms/streaming_fedavg.py) drives it batch-by-batch, so the two
    paths cannot drift apart numerically.

    Returns ``step(variables, opt_state, params0, bx, by, bm, bkey) ->
    (new_variables, new_opt_state, loss)``; ``params0`` anchors the FedProx
    proximal term (ignored when prox_mu == 0).
    """

    def batch_step(variables, opt_state, params0, bx, by, bm, bkey):
        if compute_dtype is not None and jnp.issubdtype(bx.dtype, jnp.floating):
            bx = bx.astype(compute_dtype)

        def loss_fn(p):
            vars_in = dict(variables)
            vars_in["params"] = p
            logits, new_vars = bundle.apply_train(vars_in, bx, bkey)
            l = task.loss(logits, by, bm)
            if prox_mu:
                d = tree_sub(p, params0)
                l = l + 0.5 * prox_mu * tree_dot(d, d)
            return l, new_vars

        (l, new_vars), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            variables["params"]
        )
        if grad_clip:
            gnorm = optax.global_norm(grads)
            scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-12))
            grads = jax.tree.map(lambda g: g * scale, grads)
        updates, new_opt_state = tx.update(grads, opt_state, variables["params"])
        out_vars = dict(new_vars)
        out_vars["params"] = optax.apply_updates(variables["params"], updates)
        return out_vars, new_opt_state, l

    return batch_step


def make_local_train_fn(
    bundle: ModelBundle,
    task: Task,
    *,
    optimizer: str = "sgd",
    lr: float = 0.01,
    momentum: float = 0.0,
    wd: float = 0.0,
    epochs: int = 1,
    batch_size: int = 32,
    grad_clip: Optional[float] = None,
    prox_mu: float = 0.0,
    compute_dtype=None,
    scan_unroll: int = 1,
) -> Callable[[dict, jax.Array, jax.Array, jax.Array, jax.Array], LocalResult]:
    """Build ``local_train(variables, x, y, mask, count, rng) -> LocalResult``.

    ``x/y/mask`` are one client's padded arrays [n_pad, ...]; n_pad must be a
    multiple of batch_size (loaders guarantee this); ``count`` is the client's
    REAL record count. Shapes are static, so the function vmaps over a
    stacked client axis and shard_maps over a mesh.

    Faithfulness to the reference's ragged execution under static shapes:
    each epoch shuffles the REAL records to the front, and optimizer steps
    beyond ceil(count/batch_size) are masked out (params and optimizer state
    frozen), so a 10-sample client takes the same number of effective SGD
    steps it would in the reference's Python loop — this is also what makes
    the per-client tau in LocalResult honest for FedNova.
    """
    tx = make_optimizer(optimizer, lr, momentum, wd)
    # x is pre-cast once per client below, so the shared step's own cast is
    # a no-op; prox anchors at the round's incoming params (params0)
    batch_step = make_batch_sgd_step(
        bundle, task, tx, grad_clip=grad_clip, prox_mu=prox_mu,
        compute_dtype=None,
    )

    def local_train(variables: dict, x, y, mask, count, rng) -> LocalResult:
        n_pad = x.shape[0]
        steps = n_pad // batch_size
        params0 = variables["params"]
        opt_state = tx.init(variables["params"])
        # effective steps/epoch for this client's real data (traced scalar)
        steps_real = jnp.ceil(count.astype(jnp.float32) / batch_size).astype(jnp.int32)

        if compute_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
            x_cast = x.astype(compute_dtype)
        else:
            x_cast = x

        def epoch_fn(carry, ekey):
            variables, opt_state = carry
            perm = jax.random.permutation(ekey, n_pad)
            # stable-sort shuffled indices so real records come first: batches
            # 0..steps_real-1 are the reference's real minibatches, later
            # batches are pure padding and their steps get masked out.
            order = perm[jnp.argsort(-mask[perm], stable=True)]
            xs = x_cast[order].reshape((steps, batch_size) + x.shape[1:])
            ys = y[order].reshape((steps, batch_size) + y.shape[1:])
            ms = mask[order].reshape((steps, batch_size))
            bkeys = jax.random.split(
                jax.random.fold_in(ekey, EPOCH_KEY_SALT), steps)

            def step_fn(carry, batch):
                variables, opt_state = carry
                bx, by, bm, bkey, step_idx = batch
                live = (step_idx < steps_real).astype(jnp.float32)
                new_vars, new_opt_state, l = batch_step(
                    variables, opt_state, params0, bx, by, bm, bkey
                )

                # freeze params/opt/stats on dead (padding-only) steps
                def freeze_if_dead(new, old):
                    return jax.tree.map(
                        lambda n, o: live * n + (1.0 - live) * o
                        if jnp.issubdtype(n.dtype, jnp.floating) else jnp.where(live > 0, n, o),
                        new, old,
                    )

                new_opt_state = freeze_if_dead(new_opt_state, opt_state)
                out_vars = dict(freeze_if_dead(new_vars, variables))
                return (out_vars, new_opt_state), l * live

            (variables, opt_state), losses = jax.lax.scan(
                step_fn, (variables, opt_state),
                (xs, ys, ms, bkeys, jnp.arange(steps)),
                unroll=max(int(scan_unroll), 1),
            )
            mean_loss = jnp.sum(losses) / jnp.maximum(steps_real.astype(jnp.float32), 1.0)
            return (variables, opt_state), mean_loss

        ekeys = jax.random.split(rng, epochs)
        (variables, opt_state), ep_losses = jax.lax.scan(
            epoch_fn, (variables, opt_state), ekeys
        )
        tau = (epochs * steps_real).astype(jnp.float32)
        return LocalResult(variables, ep_losses[-1], tau, ep_losses[0])

    return local_train


def make_eval_fn(bundle: ModelBundle, task: Task, eval_batch_size: int = 256):
    """Build ``evaluate(variables, x, y, mask) -> dict of metric SUMS`` —
    a scan over fixed-size batches, jitted once. Counterpart of the
    reference's trainer.test (my_model_trainer.py:61-105) without the
    per-batch host loop."""

    @jax.jit
    def evaluate(variables, x, y, mask):
        n = x.shape[0]
        bs = min(eval_batch_size, n)
        steps = -(-n // bs)  # ceil: pad the tail rather than dropping it
        pad = steps * bs - n
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
            y = jnp.concatenate([y, jnp.zeros((pad,) + y.shape[1:], y.dtype)])
            mask = jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)])
        xs = x.reshape((steps, bs) + x.shape[1:])
        ys = y.reshape((steps, bs) + y.shape[1:])
        ms = mask.reshape((steps, bs))

        def body(acc, batch):
            bx, by, bm = batch
            logits = bundle.apply_eval(variables, bx)
            m = task.metrics(logits, by, bm)
            if acc is None:
                return m, None
            return jax.tree.map(jnp.add, acc, m), None

        first = jax.tree.map(
            jnp.zeros_like, task.metrics(bundle.apply_eval(variables, xs[0]), ys[0], ms[0])
        )
        acc, _ = jax.lax.scan(lambda a, b: body(a, b), first, (xs, ys, ms))
        return acc

    return evaluate


def finalize_metrics(sums: dict) -> dict:
    """Metric sums -> human metrics (acc, loss, precision/recall; for
    segmentation sums, Acc/mIoU/FWIoU via the confusion matrix)."""
    out = {}
    if "confusion" in sums:
        from fedml_tpu.core.tasks import segmentation_scores

        scores = {k: float(v) for k, v in segmentation_scores(sums["confusion"]).items()}
        scores["acc"] = scores["Acc"]
        scores["loss"] = 1.0 - scores["mIoU"]
        return scores
    count = float(sums.get("count", 1.0))
    if "correct" in sums:
        out["acc"] = float(sums["correct"]) / max(count, 1.0)
    if "loss_sum" in sums:
        out["loss"] = float(sums["loss_sum"]) / max(count, 1.0)
    if "true_pos" in sums:
        tp, fp, fn = (float(sums[k]) for k in ("true_pos", "false_pos", "false_neg"))
        out["precision"] = tp / max(tp + fp, 1.0)
        out["recall"] = tp / max(tp + fn, 1.0)
    return out
