"""Parallel execution paradigms.

- ``local``: the jit-compiled per-client local training step (lax.scan over
  epochs x batches) — replaces the reference's Python epoch/batch hot loop
  (my_model_trainer_classification.py:19-53).
- ``sim``: vmap-over-clients standalone simulation (replaces the sequential
  client loop, fedavg_api.py:55-66).
- ``crosssilo``: shard_map client-per-device over a Mesh with psum
  aggregation (replaces the MPI star protocol, SURVEY.md §3.2).
- ``mesh``: mesh construction helpers (single axis, hierarchical two-level).
"""
