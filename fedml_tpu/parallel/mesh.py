"""Mesh construction helpers.

The reference maps MPI ranks to GPUs via gpu_mapping.yaml
(fedml_api/distributed/utils/gpu_mapping.py:8-37) and IPs via csv. On TPU the
"cluster map" is a `jax.sharding.Mesh`: federated clients shard along a
'clients' axis; hierarchical FL uses a 2-D ('group', 'clients') mesh where the
group axis is meant to ride DCN across pod slices and the client axis ICI
(SURVEY.md §2.6.5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def client_mesh(n_devices: Optional[int] = None, axis: str = "clients") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def hierarchical_mesh(num_groups: int, clients_per_group: int) -> Mesh:
    devs = jax.devices()
    need = num_groups * clients_per_group
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    arr = np.asarray(devs[:need]).reshape(num_groups, clients_per_group)
    return Mesh(arr, ("group", "clients"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh, axis: str = "clients") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def shard_client_batch(mesh: Mesh, arrays: Sequence, axis: str = "clients"):
    """Place stacked per-client arrays with the client axis sharded over the
    mesh and everything else replicated."""
    sh = client_sharded(mesh, axis)
    return tuple(jax.device_put(a, sh) for a in arrays)
