"""Mesh construction helpers.

The reference maps MPI ranks to GPUs via gpu_mapping.yaml
(fedml_api/distributed/utils/gpu_mapping.py:8-37) and IPs via csv. On TPU the
"cluster map" is a `jax.sharding.Mesh`: federated clients shard along a
'clients' axis; hierarchical FL uses a 2-D ('group', 'clients') mesh where the
group axis is meant to ride DCN across pod slices and the client axis ICI
(SURVEY.md §2.6.5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def client_mesh(n_devices: Optional[int] = None, axis: str = "clients") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def hierarchical_mesh(num_groups: int, clients_per_group: int) -> Mesh:
    devs = jax.devices()
    need = num_groups * clients_per_group
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    arr = np.asarray(devs[:need]).reshape(num_groups, clients_per_group)
    return Mesh(arr, ("group", "clients"))


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   config=None) -> int:
    """Join a multi-host TPU pod (or GPU/CPU cluster) run.

    Counterpart of the reference's mpirun + hostfile + rank→IP csv bootstrap
    (run_fedavg_distributed_pytorch.sh:19-23, ip_config_utils): one call to
    ``jax.distributed.initialize`` (env-driven on TPU pods — all args
    optional there) after which ``jax.devices()`` spans every host and the
    same Mesh/psum code runs unchanged with DCN collectives between hosts.
    Returns this process's index. Idempotent: repeated calls are no-ops
    (tracing setup included — ``config`` is honored on every call).

    ``config`` (a FedConfig) additionally wires fedscope per-host tracing:
    tracer identity becomes (process_index, rank), so every host writes its
    own ``trace-p<p>-rank<r>.jsonl`` into the shared ``--trace_dir`` and
    ``tools/trace_report.py`` merges them on the wall-µs timebase. A flush
    hook is registered so a host that exits without reaching ``train()``'s
    finally still writes what it buffered.
    """
    if getattr(init_multihost, "_done", False) or jax.distributed.is_initialized():
        _configure_host_tracing(config)
        return jax.process_index()
    kw = {}
    if coordinator_address is not None:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)
    init_multihost._done = True
    _configure_host_tracing(config)
    return jax.process_index()


def _configure_host_tracing(config) -> bool:
    """Per-host fedscope tracer setup (see :func:`init_multihost`). Returns
    whether tracing ended up enabled. Safe to call repeatedly."""
    if config is None:
        return False
    from fedml_tpu.obs import configure_from, set_process_index

    set_process_index(jax.process_index())
    if not configure_from(config):
        return False
    if not getattr(_configure_host_tracing, "_atexit", False):
        import atexit

        from fedml_tpu.obs import flush_all

        atexit.register(flush_all)
        _configure_host_tracing._atexit = True
    return True


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh, axis: str = "clients") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def global_put(x, sh: NamedSharding):
    """``device_put`` that also works when the mesh spans multiple
    processes (a pod run bootstrapped by :func:`init_multihost`).

    ``jax.device_put`` refuses shardings with non-addressable devices; in a
    multi-process run every process instead holds the full host value — the
    reference's everyone-loads-everything pattern (main_fedavg.py:323) —
    and contributes its addressable shards via
    ``make_array_from_process_local_data``. Leaves already carrying the
    target sharding pass through untouched (round outputs fed back in)."""
    if jax.process_count() == 1:
        return jax.device_put(x, sh)

    def put_leaf(leaf):
        if isinstance(leaf, jax.Array) and leaf.sharding == sh:
            return leaf
        if isinstance(leaf, jax.Array) and jax.dtypes.issubdtype(
                leaf.dtype, jax.dtypes.prng_key):
            # typed PRNG keys have no numpy form: place the raw key data
            # (trailing key-word dims are replicated by the same spec) and
            # re-wrap on the global mesh
            data = np.asarray(jax.random.key_data(leaf))
            placed = jax.make_array_from_process_local_data(sh, data, data.shape)
            return jax.random.wrap_key_data(placed, impl=jax.random.key_impl(leaf))
        arr = np.asarray(leaf)
        return jax.make_array_from_process_local_data(sh, arr, arr.shape)

    return jax.tree.map(put_leaf, x)


def shard_client_batch(mesh: Mesh, arrays: Sequence, axis: str = "clients"):
    """Place stacked per-client arrays with the client axis sharded over the
    mesh and everything else replicated."""
    sh = client_sharded(mesh, axis)
    return tuple(global_put(a, sh) for a in arrays)
