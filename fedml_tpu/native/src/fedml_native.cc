// fedml_tpu native runtime: the IO/memory hot paths that sit AROUND the
// XLA compute path (task: serialization hot path + host-side data pipeline).
//
// The reference framework is pure Python (SURVEY.md §2: "Native-code
// components: NONE") and pays for it: state dicts cross the wire as
// pickled dicts (mpi_send_thread.py:27) or JSON nested lists
// (fedavg/utils.py:7-16), and every DataLoader batch is assembled by the
// Python interpreter. Here the equivalents are C++:
//
//   1. crc32c (Castagnoli, slice-by-8) — integrity trailer for wire frames
//      and checkpoint files.
//   2. parallel gather/scatter memcpy — pack N pytree leaves into one wire
//      buffer / unpack one buffer into N leaf arrays, threaded for large
//      payloads.
//   3. a bounded, threaded, deterministic host data pipeline — Fisher-Yates
//      shuffle per epoch (mt19937_64, seeded), worker threads gather records
//      into a ring of slots, consumer receives batches IN ORDER. This is the
//      native replacement for torch DataLoader workers: it overlaps batch
//      assembly with device compute without holding the GIL.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <functional>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// crc32c, slice-by-8
// ---------------------------------------------------------------------------

namespace {

uint32_t g_crc_tab[8][256];
std::once_flag g_crc_once;

void crc_init() {
  const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    g_crc_tab[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int t = 1; t < 8; ++t)
      g_crc_tab[t][i] =
          (g_crc_tab[t - 1][i] >> 8) ^ g_crc_tab[0][g_crc_tab[t - 1][i] & 0xFF];
}

}  // namespace

extern "C" uint32_t fed_crc32c(const uint8_t* p, uint64_t n, uint32_t seed) {
  std::call_once(g_crc_once, crc_init);
  uint32_t crc = ~seed;
  while (n && (reinterpret_cast<uintptr_t>(p) & 7)) {
    crc = g_crc_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    --n;
  }
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    w ^= crc;  // little-endian assumed (x86-64 / aarch64-le)
    crc = g_crc_tab[7][w & 0xFF] ^ g_crc_tab[6][(w >> 8) & 0xFF] ^
          g_crc_tab[5][(w >> 16) & 0xFF] ^ g_crc_tab[4][(w >> 24) & 0xFF] ^
          g_crc_tab[3][(w >> 32) & 0xFF] ^ g_crc_tab[2][(w >> 40) & 0xFF] ^
          g_crc_tab[1][(w >> 48) & 0xFF] ^ g_crc_tab[0][(w >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n--) crc = g_crc_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// ---------------------------------------------------------------------------
// parallel gather/scatter copy (wire pack/unpack hot path)
// ---------------------------------------------------------------------------

namespace {

// Split [0, n) leaf indices across threads by cumulative byte weight.
void run_sharded_copy(uint64_t n, const uint64_t* sizes, int n_threads,
                      const std::function<void(uint64_t)>& copy_one) {
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; ++i) total += sizes[i];
  if (n_threads <= 1 || total < (8u << 20) || n < 2) {
    for (uint64_t i = 0; i < n; ++i) copy_one(i);
    return;
  }
  std::atomic<uint64_t> next{0};
  auto worker = [&] {
    for (;;) {
      uint64_t i = next.fetch_add(1, std::memory_order_relaxed);
      if (i >= n) return;
      copy_one(i);
    }
  };
  std::vector<std::thread> ts;
  int nt = std::min<int>(n_threads, static_cast<int>(n));
  ts.reserve(nt - 1);
  for (int t = 1; t < nt; ++t) ts.emplace_back(worker);
  worker();
  for (auto& t : ts) t.join();
}

}  // namespace

// Pack: copy srcs[i] (sizes[i] bytes) to dst at offsets[i].
extern "C" void fed_gather_copy(uint8_t* dst, const uint8_t* const* srcs,
                                const uint64_t* sizes, const uint64_t* offsets,
                                uint64_t n, int n_threads) {
  run_sharded_copy(n, sizes, n_threads, [&](uint64_t i) {
    std::memcpy(dst + offsets[i], srcs[i], sizes[i]);
  });
}

// Unpack: copy src at offsets[i] into dsts[i].
extern "C" void fed_scatter_copy(const uint8_t* src, uint8_t* const* dsts,
                                 const uint64_t* sizes, const uint64_t* offsets,
                                 uint64_t n, int n_threads) {
  run_sharded_copy(n, sizes, n_threads, [&](uint64_t i) {
    std::memcpy(dsts[i], src + offsets[i], sizes[i]);
  });
}

// ---------------------------------------------------------------------------
// host data pipeline
// ---------------------------------------------------------------------------

namespace {

struct Slot {
  std::vector<uint8_t> x, y;
  int64_t count = 0;    // records in this batch
  int64_t seq = -1;     // which global batch sequence number it holds
  bool ready = false;
};

struct Pipeline {
  const uint8_t* x;
  const uint8_t* y;
  int64_t n_records, x_rec_bytes, y_rec_bytes, batch;
  bool drop_last;
  uint64_t seed;
  int64_t n_batches;  // per epoch

  // Explicit-order mode (fed_pipeline_create_ordered): the consumer supplies
  // the exact per-epoch record order — e.g. a federated trainer reproducing
  // its jitted scan's shuffle stream — instead of the internal Fisher-Yates.
  // Owned copy, [ext_epochs, ext_len] row-major; epochs wrap modulo.
  std::vector<int64_t> ext_orders;
  int64_t ext_epochs = 0, ext_len = 0;

  std::vector<Slot> slots;
  std::atomic<int64_t> next_fetch{0};  // next batch seq to be produced
  int64_t next_deliver = 0;            // next batch seq the consumer takes
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  bool stop = false;
  std::vector<std::thread> workers;

  // Permutations per epoch, built lazily and handed out as shared_ptr so a
  // worker mid-copy keeps its epoch's permutation alive even after the map
  // prunes it (with tiny datasets the `depth` in-flight batches can span
  // MORE epochs than the prune window — a raw reference would dangle).
  std::map<int64_t, std::shared_ptr<std::vector<int64_t>>> perms;
  std::mutex perm_mu;

  std::shared_ptr<std::vector<int64_t>> perm_for_epoch(int64_t e) {
    std::lock_guard<std::mutex> g(perm_mu);
    auto it = perms.find(e);
    if (it != perms.end()) return it->second;
    auto p = std::make_shared<std::vector<int64_t>>(n_records);
    for (int64_t i = 0; i < n_records; ++i) (*p)[i] = i;
    std::mt19937_64 rng(seed + static_cast<uint64_t>(e) * 0x9E3779B97F4A7C15ull);
    for (int64_t i = n_records - 1; i > 0; --i) {
      int64_t j = static_cast<int64_t>(rng() % static_cast<uint64_t>(i + 1));
      std::swap((*p)[i], (*p)[j]);
    }
    while (perms.size() >= 3) perms.erase(perms.begin());
    perms.emplace(e, p);
    return p;
  }

  void fill(int64_t seq_no, Slot& s) {
    int64_t epoch = seq_no / n_batches;
    int64_t b = seq_no % n_batches;
    int64_t start = b * batch;
    const int64_t* src_idx;
    int64_t limit;
    std::shared_ptr<std::vector<int64_t>> perm_keepalive;
    if (!ext_orders.empty()) {
      src_idx = ext_orders.data() + (epoch % ext_epochs) * ext_len + start;
      limit = ext_len;
    } else {
      perm_keepalive = perm_for_epoch(epoch);
      src_idx = perm_keepalive->data() + start;
      limit = n_records;
    }
    int64_t count = std::min(batch, limit - start);
    for (int64_t r = 0; r < count; ++r) {
      int64_t src = src_idx[r];
      std::memcpy(s.x.data() + r * x_rec_bytes, x + src * x_rec_bytes,
                  x_rec_bytes);
      if (y_rec_bytes)
        std::memcpy(s.y.data() + r * y_rec_bytes, y + src * y_rec_bytes,
                    y_rec_bytes);
    }
    s.count = count;
  }

  void worker_loop() {
    for (;;) {
      int64_t seq_no = next_fetch.fetch_add(1, std::memory_order_relaxed);
      Slot& s = slots[seq_no % slots.size()];
      {
        std::unique_lock<std::mutex> lk(mu);
        // Wait until the consumer has drained whatever previously lived in
        // this ring slot (in-order delivery guarantees seq-depth precedes us).
        cv_free.wait(lk, [&] { return stop || (!s.ready && next_deliver + static_cast<int64_t>(slots.size()) > seq_no); });
        if (stop) return;
      }
      fill(seq_no, s);
      {
        std::lock_guard<std::mutex> lk(mu);
        s.seq = seq_no;
        s.ready = true;
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" void* fed_pipeline_create(const uint8_t* x, const uint8_t* y,
                                     int64_t n_records, int64_t x_rec_bytes,
                                     int64_t y_rec_bytes, int64_t batch,
                                     uint64_t seed, int n_threads, int depth,
                                     int drop_last) {
  if (n_records <= 0 || batch <= 0 || x_rec_bytes <= 0) return nullptr;
  auto* p = new Pipeline;
  p->x = x;
  p->y = y;
  p->n_records = n_records;
  p->x_rec_bytes = x_rec_bytes;
  p->y_rec_bytes = y_rec_bytes;
  p->batch = batch;
  p->drop_last = drop_last != 0;
  p->seed = seed;
  p->n_batches = p->drop_last ? n_records / batch
                              : (n_records + batch - 1) / batch;
  if (p->n_batches <= 0) {
    delete p;
    return nullptr;
  }
  if (depth < 2) depth = 2;
  p->slots.resize(depth);
  for (auto& s : p->slots) {
    s.x.resize(static_cast<size_t>(batch) * x_rec_bytes);
    s.y.resize(static_cast<size_t>(batch) * (y_rec_bytes ? y_rec_bytes : 1));
  }
  if (n_threads < 1) n_threads = 1;
  n_threads = std::min<int>(n_threads, depth);
  for (int t = 0; t < n_threads; ++t)
    p->workers.emplace_back([p] { p->worker_loop(); });
  return p;
}

// Explicit-order creation: the consumer supplies the exact per-epoch record
// order ([n_epochs, order_len] row-major, epochs wrap modulo) instead of the
// internal Fisher-Yates — used by the streaming federated trainer to
// reproduce its jitted scan's shuffle stream.
extern "C" void* fed_pipeline_create_ordered(
    const uint8_t* x, const uint8_t* y, int64_t n_records,
    int64_t x_rec_bytes, int64_t y_rec_bytes, int64_t batch,
    const int64_t* orders, int64_t n_epochs, int64_t order_len,
    int n_threads, int depth) {
  if (n_records <= 0 || batch <= 0 || x_rec_bytes <= 0 || orders == nullptr ||
      n_epochs <= 0 || order_len <= 0)
    return nullptr;
  // validate indices up front: a bad order entry must fail create, not
  // corrupt a worker thread mid-copy
  for (int64_t i = 0; i < n_epochs * order_len; ++i)
    if (orders[i] < 0 || orders[i] >= n_records) return nullptr;
  auto* p = new Pipeline;
  p->x = x;
  p->y = y;
  p->n_records = n_records;
  p->x_rec_bytes = x_rec_bytes;
  p->y_rec_bytes = y_rec_bytes;
  p->batch = batch;
  p->drop_last = false;
  p->seed = 0;
  p->ext_orders.assign(orders, orders + n_epochs * order_len);
  p->ext_epochs = n_epochs;
  p->ext_len = order_len;
  p->n_batches = (order_len + batch - 1) / batch;
  if (depth < 2) depth = 2;
  p->slots.resize(depth);
  for (auto& s : p->slots) {
    s.x.resize(static_cast<size_t>(batch) * x_rec_bytes);
    s.y.resize(static_cast<size_t>(batch) * (y_rec_bytes ? y_rec_bytes : 1));
  }
  if (n_threads < 1) n_threads = 1;
  n_threads = std::min<int>(n_threads, depth);
  for (int t = 0; t < n_threads; ++t)
    p->workers.emplace_back([p] { p->worker_loop(); });
  return p;
}

// Blocks until the next in-order batch is ready, copies it to x_out/y_out,
// frees the slot. Returns the record count in the batch (full batches =
// `batch`, the final non-drop_last batch of an epoch may be smaller).
extern "C" int64_t fed_pipeline_next(void* pv, uint8_t* x_out, uint8_t* y_out) {
  auto* p = static_cast<Pipeline*>(pv);
  int64_t want = p->next_deliver;
  Slot& s = p->slots[want % p->slots.size()];
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_ready.wait(lk, [&] { return p->stop || (s.ready && s.seq == want); });
    if (p->stop) return -1;
  }
  int64_t count = s.count;
  std::memcpy(x_out, s.x.data(), static_cast<size_t>(count) * p->x_rec_bytes);
  if (p->y_rec_bytes && y_out)
    std::memcpy(y_out, s.y.data(), static_cast<size_t>(count) * p->y_rec_bytes);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    s.ready = false;
    p->next_deliver = want + 1;
  }
  p->cv_free.notify_all();
  return count;
}

extern "C" int64_t fed_pipeline_batches_per_epoch(void* pv) {
  return static_cast<Pipeline*>(pv)->n_batches;
}

extern "C" void fed_pipeline_destroy(void* pv) {
  auto* p = static_cast<Pipeline*>(pv);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv_ready.notify_all();
  p->cv_free.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}

extern "C" int fed_native_abi_version() { return 1; }
