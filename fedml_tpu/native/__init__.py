"""ctypes bindings for the native runtime (fedml_tpu/native/src/fedml_native.cc).

The reference framework is 100% Python (SURVEY.md §2: zero native
components) and its IO layer shows it — pickled state dicts and
interpreter-assembled batches. This package provides the C++ hot paths for
the runtime AROUND the XLA compute: frame integrity (crc32c), wire
pack/unpack (parallel gather/scatter memcpy), and a threaded host data
pipeline. Every entry point has a pure-Python fallback so the framework
works without a compiler; ``available()`` reports which is active.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Sequence

import numpy as np

_lib = None
_lib_lock = threading.Lock()
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lib_lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("FEDML_TPU_NO_NATIVE"):
            return None
        try:
            from fedml_tpu.native.build import build_library

            path = build_library()
            if path is None:
                return None
            lib = ctypes.CDLL(str(path))
        except OSError:
            return None
        lib.fed_crc32c.restype = ctypes.c_uint32
        lib.fed_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.fed_gather_copy.restype = None
        lib.fed_gather_copy.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64, ctypes.c_int,
        ]
        lib.fed_scatter_copy.restype = None
        lib.fed_scatter_copy.argtypes = lib.fed_gather_copy.argtypes
        lib.fed_pipeline_create.restype = ctypes.c_void_p
        lib.fed_pipeline_create.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.fed_pipeline_create_ordered.restype = ctypes.c_void_p
        lib.fed_pipeline_create_ordered.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ]
        lib.fed_pipeline_next.restype = ctypes.c_int64
        lib.fed_pipeline_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.fed_pipeline_batches_per_epoch.restype = ctypes.c_int64
        lib.fed_pipeline_batches_per_epoch.argtypes = [ctypes.c_void_p]
        lib.fed_pipeline_destroy.restype = None
        lib.fed_pipeline_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# --- crc32c -----------------------------------------------------------------

_CRC_TABLE = None


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        tab = np.zeros(256, dtype=np.uint32)
        for i in range(256):
            c = i
            for _ in range(8):
                c = (poly ^ (c >> 1)) if (c & 1) else (c >> 1)
            tab[i] = c
        _CRC_TABLE = tab
    return _CRC_TABLE


def crc32c(data: bytes | memoryview | np.ndarray, seed: int = 0) -> int:
    """crc32c (Castagnoli). Native when available, table-driven numpy-ish
    Python otherwise (slow path is fine: it only runs compiler-less)."""
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data.view(np.uint8).ravel()
    lib = _load()
    if lib is not None:
        buf = np.ascontiguousarray(buf)
        return int(lib.fed_crc32c(buf.ctypes.data, buf.size, ctypes.c_uint32(seed)))
    tab = _crc_table()
    crc = (~seed) & 0xFFFFFFFF
    for b in buf.tobytes():
        crc = (int(tab[(crc ^ b) & 0xFF]) ^ (crc >> 8)) & 0xFFFFFFFF
    return (~crc) & 0xFFFFFFFF


# --- pack/unpack ------------------------------------------------------------

def pack_buffers(arrays: Sequence[np.ndarray], out: Optional[bytearray] = None,
                 offset: int = 0, n_threads: int = 0) -> bytearray:
    """Concatenate arrays' raw bytes into ``out`` starting at ``offset``,
    with a threaded native gather when available. Returns ``out``."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = [a.nbytes for a in arrays]
    total = offset + sum(sizes)
    if out is None:
        out = bytearray(total)
    elif len(out) < total:
        raise ValueError(f"out too small: {len(out)} < {total}")
    lib = _load()
    offs, run = [], offset
    for s in sizes:
        offs.append(run)
        run += s
    if lib is not None and arrays:
        n = len(arrays)
        src_ptrs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
        c_sizes = (ctypes.c_uint64 * n)(*sizes)
        c_offs = (ctypes.c_uint64 * n)(*offs)
        dst = (ctypes.c_uint8 * len(out)).from_buffer(out)
        if n_threads <= 0:
            n_threads = min(8, os.cpu_count() or 1)
        lib.fed_gather_copy(ctypes.addressof(dst), src_ptrs, c_sizes, c_offs, n, n_threads)
    else:
        mv = memoryview(out)
        for a, o, s in zip(arrays, offs, sizes):
            mv[o:o + s] = a.tobytes() if a.nbytes else b""
    return out


def unpack_buffers(buf, specs: Sequence[tuple[tuple, str]], offset: int = 0,
                   n_threads: int = 0) -> list[np.ndarray]:
    """Slice ``buf`` (bytes-like) back into arrays per (shape, dtype) specs,
    scatter-copied natively when available. Always copies (the result owns
    its memory, detached from the wire buffer)."""
    src = np.frombuffer(buf, dtype=np.uint8)
    outs, offs, sizes = [], [], []
    run = offset
    for shape, dtype in specs:
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) if len(shape) else 1
        a = np.empty(shape, dtype=dt)
        outs.append(a)
        offs.append(run)
        sizes.append(n * dt.itemsize)
        run += n * dt.itemsize
    if run > src.size:
        raise ValueError("buffer too small for specs")
    lib = _load()
    if lib is not None and outs:
        k = len(outs)
        dst_ptrs = (ctypes.c_void_p * k)(*[a.ctypes.data for a in outs])
        c_sizes = (ctypes.c_uint64 * k)(*sizes)
        c_offs = (ctypes.c_uint64 * k)(*offs)
        if n_threads <= 0:
            n_threads = min(8, os.cpu_count() or 1)
        lib.fed_scatter_copy(src.ctypes.data, dst_ptrs, c_sizes, c_offs, k, n_threads)
    else:
        for a, o, s in zip(outs, offs, sizes):
            a.view(np.uint8).ravel()[:] = src[o:o + s] if a.nbytes else a.view(np.uint8).ravel()
    return outs


# --- host data pipeline -----------------------------------------------------

class HostPipeline:
    """Deterministic threaded shuffled batcher over (x, y) record arrays.

    Produces an infinite in-order stream of batches; each epoch is an
    independent Fisher-Yates permutation of the records derived from
    (seed, epoch). Worker threads assemble batches into a bounded ring
    concurrently with the consumer (which is typically blocked in device
    compute) — the native replacement for DataLoader worker processes.

    Falls back to a single-threaded Python implementation (same API,
    different but still deterministic permutation stream) without the
    native library.
    """

    def __init__(self, x: np.ndarray, y: Optional[np.ndarray], batch_size: int,
                 seed: int = 0, n_threads: int = 2, depth: int = 4,
                 drop_last: bool = False,
                 orders: Optional[np.ndarray] = None):
        """``orders`` switches to explicit-order mode: a [n_epochs, L] int64
        index table; epoch e streams records x[orders[e % n_epochs]] in that
        exact sequence (L need not equal len(x) — e.g. a federated trainer
        streaming only the real records of a padded client slice while
        reproducing its jitted scan's shuffle). ``seed``/``drop_last`` are
        ignored in this mode."""
        self.x = np.ascontiguousarray(x)
        self.y = None if y is None else np.ascontiguousarray(y)
        if self.y is not None and len(self.y) != len(self.x):
            raise ValueError("x/y length mismatch")
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.drop_last = bool(drop_last)
        n = len(self.x)
        if orders is not None:
            orders = np.ascontiguousarray(orders, np.int64)
            if orders.ndim != 2 or orders.size == 0:
                raise ValueError("orders must be a non-empty [n_epochs, L] table")
            if orders.min() < 0 or orders.max() >= n:
                raise ValueError("orders entries out of range")
            self.orders = orders
            self.batches_per_epoch = -(-orders.shape[1] // self.batch_size)
        else:
            self.orders = None
            self.batches_per_epoch = (n // self.batch_size if drop_last
                                      else -(-n // self.batch_size))
        if self.batches_per_epoch <= 0:
            raise ValueError("dataset smaller than one batch with drop_last")
        self._handle = None
        self._lib = _load()
        if self._lib is not None:
            xb = self.x.nbytes // n
            yb = 0 if self.y is None else self.y.nbytes // n
            if self.orders is not None:
                self._handle = self._lib.fed_pipeline_create_ordered(
                    self.x.ctypes.data,
                    0 if self.y is None else self.y.ctypes.data,
                    n, xb, yb, self.batch_size,
                    self.orders.ctypes.data, self.orders.shape[0],
                    self.orders.shape[1], int(n_threads), int(depth),
                )
            else:
                self._handle = self._lib.fed_pipeline_create(
                    self.x.ctypes.data,
                    0 if self.y is None else self.y.ctypes.data,
                    n, xb, yb, self.batch_size, self.seed,
                    int(n_threads), int(depth), int(drop_last),
                )
        if self._handle is None:
            self._rng_epoch = 0
            self._py_iter = self._python_stream()

    def _python_stream(self):
        n = len(self.x)
        epoch = 0
        while True:
            if self.orders is not None:
                perm = self.orders[epoch % self.orders.shape[0]]
            else:
                rng = np.random.default_rng(self.seed + epoch * 1_000_003)
                perm = rng.permutation(n)
            nb = self.batches_per_epoch
            for b in range(nb):
                ix = perm[b * self.batch_size:(b + 1) * self.batch_size]
                yield self.x[ix], None if self.y is None else self.y[ix]
            epoch += 1

    def next_batch(self) -> tuple[np.ndarray, Optional[np.ndarray]]:
        """Next (x, y) batch; the final batch of an epoch may be short when
        drop_last is False."""
        if self._handle is None:
            return next(self._py_iter)
        bx = np.empty((self.batch_size,) + self.x.shape[1:], dtype=self.x.dtype)
        by = (None if self.y is None
              else np.empty((self.batch_size,) + self.y.shape[1:], dtype=self.y.dtype))
        count = self._lib.fed_pipeline_next(
            self._handle, bx.ctypes.data,
            0 if by is None else by.ctypes.data)
        if count < 0:
            raise RuntimeError("pipeline stopped")
        if count < self.batch_size:
            bx = bx[:count]
            by = None if by is None else by[:count]
        return bx, by

    def epoch(self):
        """Yield exactly one epoch's batches."""
        for _ in range(self.batches_per_epoch):
            yield self.next_batch()

    def close(self):
        if self._handle is not None and self._lib is not None:
            self._lib.fed_pipeline_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
