"""Build the native runtime shared library on first use.

No pybind11 in this image, so the library is a plain C-ABI ``.so`` compiled
with g++ and consumed via ctypes (fedml_tpu/native/__init__.py). The build is
cached next to the source keyed by a hash of the source text + compiler
flags; rebuilds happen only when either changes. Everything degrades to the
pure-Python fallbacks if no compiler is present.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

_SRC = Path(__file__).parent / "src" / "fedml_native.cc"
_BUILD_DIR = Path(__file__).parent / "_build"
_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", "-Wall"]


def _key() -> str:
    h = hashlib.sha256()
    h.update(_SRC.read_bytes())
    h.update(" ".join(_FLAGS).encode())
    return h.hexdigest()[:16]


def build_library(quiet: bool = True) -> Optional[Path]:
    """Compile (or reuse the cached) libfedml_native.so; None if impossible."""
    compiler = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if compiler is None or not _SRC.exists():
        return None
    out = _BUILD_DIR / f"libfedml_native-{_key()}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(exist_ok=True)
    # Build into a temp file then atomically rename, so concurrent test
    # workers never dlopen a half-written .so.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    cmd = [compiler, *_FLAGS, str(_SRC), "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            if not quiet:
                raise RuntimeError(f"native build failed:\n{proc.stderr}")
            return None
        os.replace(tmp, out)
        return out
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
