"""Static-shape batching: the key TPU-ism the reference never needed
(SURVEY.md §7.3 hard part (a)).

Ragged per-client datasets are padded to a common ``n_pad`` (a multiple of
the batch size) and stacked [num_clients, n_pad, ...] with {0,1} masks, so
the whole federation is a handful of dense arrays XLA can tile.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(np.ceil(max(n, 1) / multiple) * multiple)


def pad_and_stack_clients(
    xs: Sequence[np.ndarray],
    ys: Sequence[np.ndarray],
    batch_size: int,
    n_pad: Optional[int] = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """[per-client ragged arrays] -> (x [C,n_pad,...], y [C,n_pad,...],
    mask [C,n_pad], counts [C]). Padding records repeat record 0 (arbitrary;
    mask 0 removes them from loss/metrics)."""
    counts = np.array([len(x) for x in xs], dtype=np.int64)
    if n_pad is None:
        n_pad = pad_to_multiple(int(counts.max()), batch_size)
    C = len(xs)
    x0, y0 = np.asarray(xs[0]), np.asarray(ys[0])
    out_x = np.zeros((C, n_pad) + x0.shape[1:], dtype=x0.dtype)
    out_y = np.zeros((C, n_pad) + y0.shape[1:], dtype=y0.dtype)
    mask = np.zeros((C, n_pad), dtype=np.float32)
    for i, (x, y) in enumerate(zip(xs, ys)):
        n = len(x)
        if n == 0:
            continue
        reps = int(np.ceil(n_pad / n))
        xi = np.concatenate([np.asarray(x)] * reps, axis=0)[:n_pad]
        yi = np.concatenate([np.asarray(y)] * reps, axis=0)[:n_pad]
        out_x[i], out_y[i] = xi, yi
        mask[i, :n] = 1.0
    return out_x, out_y, mask, counts


def pad_eval_pool(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Pad a flat eval set to a batch multiple; returns (x, y, mask)."""
    n = len(x)
    n_pad = pad_to_multiple(n, batch_size)
    if n_pad == n:
        return np.asarray(x), np.asarray(y), np.ones(n, np.float32)
    pad = n_pad - n
    xp = np.concatenate([x, np.repeat(np.asarray(x[:1]), pad, axis=0)], axis=0)
    yp = np.concatenate([y, np.repeat(np.asarray(y[:1]), pad, axis=0)], axis=0)
    m = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    return xp, yp, m


def partition_to_client_arrays(
    x: np.ndarray, y: np.ndarray, index_map: dict[int, np.ndarray]
) -> tuple[list[np.ndarray], list[np.ndarray]]:
    idxs = [index_map[i] for i in sorted(index_map)]
    return [x[ix] for ix in idxs], [y[ix] for ix in idxs]
