"""fedsched: profiler-driven cohort scheduling for cross-device rounds.

Every paradigm samples its round cohort uniformly (core/rng.sample_clients,
the reference's ``np.random.seed(round_idx)`` draw). At cross-device scale
that leaves the round gated by whichever slow client the draw happened to
include — FedML Parrot (arXiv:2303.01778, PAPERS.md) names
heterogeneity-aware cohort scheduling as the unlock, and the fedpulse
:class:`~fedml_tpu.obs.profile.ClientProfiler` was built to supply exactly
the signal it needs (``speed_rank`` / ``participation_fairness``). This
module is the consumer: a pluggable cohort-selection policy sitting where
``sample_clients`` used to be called.

Policies
--------
- ``uniform``: literally today's draw — :func:`plan_cohort` calls
  ``sample_clients`` with the same arguments, so the default is
  bit-identical to the pre-scheduler path by construction.
- ``speed``: draw an oversampled candidate pool uniformly (the same
  deterministic stream), then keep the ``cohort`` candidates with the
  LOWEST observed EMA train-ms — cohorts pack speed-homogeneous, so one
  slow client no longer gates the round. Candidates the profiler has never
  seen (cold starts, and ids dropped at the profiler's ``max_clients``
  cap) rank at the SEEN population's median EMA: they mix into the middle
  instead of being starved (or worse, raising) — the ISSUE's dropped-id
  contract.
- ``fair``: speed packing with a participation bound — a fixed fraction of
  the cohort is reserved for the LEAST-participated candidates (unseen
  clients count as participation 0, so exploration never stops), the rest
  filled fastest-first. The reservation keeps the participation gini from
  running away the way pure ``speed`` lets it.

Determinism contract
--------------------
:func:`plan_cohort` is PURE in ``(seed, round_idx, snapshot)``: the same
profiler snapshot yields the same plan, byte for byte — so the PR-3
``CohortPrefetcher`` can keep speculating (whoever computes a round's plan
first, consumer or background build, gets the identical answer) and a
static snapshot (tools/xdev_ab.py ``--policy``) makes whole runs replay
bit-identically at any pipeline depth. Live-fed snapshots are captured at
round boundaries with a fixed :data:`SCHED_LAG` (the plan for round ``r``
uses the newest snapshot taken at or before round ``r - SCHED_LAG``), and
every computed plan lands in a bounded ledger — within a run, re-requests
(the bench re-running rounds, checkpoint-restore jumps, ``round_counts``)
replay the ledger, never a fresher snapshot.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, NamedTuple, Optional

import numpy as np

from fedml_tpu.core.rng import sample_clients

log = logging.getLogger(__name__)

__all__ = ["COHORT_POLICIES", "SCHED_LAG", "CohortScheduler",
           "ProfileSnapshot", "plan_cohort", "snapshot_from_counts"]

COHORT_POLICIES = ("uniform", "speed", "fair")

#: rounds between a snapshot and the first plan allowed to use it. A plan
#: for round r reads the snapshot taken after round r - SCHED_LAG, so a
#: prefetcher speculating up to SCHED_LAG - 1 rounds ahead schedules from
#: the same snapshot the serial path would — deeper speculation falls back
#: to the newest snapshot available at build time (still pure per plan via
#: the ledger, but no longer depth-independent; xdev_ab's determinism arm
#: uses a static snapshot, which is depth-independent at ANY depth).
SCHED_LAG = 2

#: candidate pool size as a multiple of the cohort for the profiler-driven
#: policies — big enough to skip the slow tail, small enough that the pool
#: stays a uniform draw over the population
OVERSAMPLE = 4

#: ``fair``: fraction of the cohort reserved for least-participated
#: candidates (>= 1 slot)
FAIR_FRACTION = 0.25


class ProfileSnapshot(NamedTuple):
    """Immutable view of a :class:`ClientProfiler` at one schedule point:
    ``ids`` are the SEEN client ids ascending, the other arrays align."""

    ids: np.ndarray            # [n_seen] int64, sorted ascending
    ema_train_ms: np.ndarray   # [n_seen] float32
    participation: np.ndarray  # [n_seen] int32

    @property
    def n_seen(self) -> int:
        return int(self.ids.size)


def _lookup(snap: ProfileSnapshot, pool: np.ndarray):
    """Per-candidate (seen, ema, participation) against the snapshot.
    Candidates outside the snapshot — cold starts, ids beyond the
    profiler's ``max_clients`` cap — come back ``seen=False``; nothing
    here can raise on an arbitrary id."""
    idx = np.searchsorted(snap.ids, pool)
    idx_c = np.clip(idx, 0, max(snap.n_seen - 1, 0))
    seen = (idx < snap.n_seen) & (snap.ids[idx_c] == pool)
    ema = np.where(seen, snap.ema_train_ms[idx_c], np.nan)
    part = np.where(seen, snap.participation[idx_c], 0).astype(np.int64)
    return seen, ema, part


def snapshot_from_counts(counts, ms_per_record: float = 1.0,
                         participation=None) -> ProfileSnapshot:
    """Population-wide snapshot from per-client record COUNTS: expected
    train-ms = ``counts * ms_per_record``. This is the cold-start prior a
    cross-device deployment actually has — every client reports its
    dataset size at registration (the reference wires ``sample_num`` into
    every upload), while OBSERVED train-ms exists only for clients a
    cohort has already run. At a million-client population a uniformly
    drawn candidate pool almost never intersects the few thousand ids the
    live profiler has seen, so ``speed``/``fair`` would degenerate to the
    cold-start middle; extrapolating the profiler's measured per-record
    cost over the counts table (the bench fits ``ms_per_record`` =
    median(EMA/records) over the seen ids) gives the policies a total
    signal. Deterministic by construction — counts are dataset metadata."""
    counts = np.asarray(counts, np.float64)
    n = counts.shape[0]
    part = (np.zeros(n, np.int32) if participation is None
            else np.asarray(participation, np.int32))
    return ProfileSnapshot(
        ids=np.arange(n, dtype=np.int64),
        ema_train_ms=(counts * float(ms_per_record)).astype(np.float32),
        participation=part)


def plan_cohort(round_idx: int, client_num_in_total: int, cohort: int,
                seed: int, policy: str = "uniform",
                snapshot: Optional[ProfileSnapshot] = None) -> np.ndarray:
    """The pure planning function (module docstring). Returns the sampled
    cohort's client ids, sorted ascending like ``sample_clients``."""
    if policy not in COHORT_POLICIES:
        raise ValueError(
            f"cohort_policy must be one of {COHORT_POLICIES}, got {policy!r}")
    if (policy == "uniform" or snapshot is None or snapshot.n_seen == 0
            or cohort >= client_num_in_total):
        # cold start (and the full-participation degenerate case): the
        # uniform draw IS the plan — bit-identical to the unscheduled path
        return sample_clients(round_idx, client_num_in_total, cohort,
                              seed=seed)
    pool = sample_clients(round_idx, client_num_in_total,
                          min(client_num_in_total, cohort * OVERSAMPLE),
                          seed=seed)
    seen, ema, part = _lookup(snapshot, pool)
    # cold-start candidates rank at the median SEEN speed: they mix into
    # the middle of the pool instead of being pinned fastest (which would
    # thrash cohorts with unprofiled clients) or slowest (which would
    # starve them of the observations the ranking needs)
    fill = float(np.median(snapshot.ema_train_ms))
    key = np.where(seen, ema, np.float32(fill))
    if policy == "speed":
        order = np.argsort(key, kind="stable")   # ties keep pool (id) order
        pick = pool[order[:cohort]]
    else:  # fair
        reserve = max(1, int(round(FAIR_FRACTION * cohort)))
        by_part = np.argsort(part, kind="stable")
        reserved = by_part[:reserve]
        taken = np.zeros(pool.size, bool)
        taken[reserved] = True
        by_speed = np.argsort(key, kind="stable")
        rest = by_speed[~taken[by_speed]][: cohort - reserve]
        pick = pool[np.concatenate([reserved, rest])]
    return np.sort(pick).astype(np.int64)


class CohortScheduler:
    """Stateful wrapper: snapshot capture at round boundaries + the plan
    ledger. Thread-safe — the prefetcher's background builds and the
    consuming round may both ask for (and therefore compute) plans."""

    #: ledger bound: covers every realistic replay window (pipeline depth,
    #: bench re-runs, restore jumps); evicted plans recompute from the
    #: snapshot store, which only holds the recent boundary snapshots
    LEDGER_CAP = 4096

    def __init__(self, policy: str, seed: int, client_num_in_total: int,
                 cohort: int,
                 profile_source: Optional[Callable] = None,
                 lag: int = SCHED_LAG):
        if policy not in COHORT_POLICIES:
            raise ValueError(
                f"cohort_policy must be one of {COHORT_POLICIES}, got "
                f"{policy!r}")
        self.policy = policy
        self.seed = int(seed)
        self.client_num_in_total = int(client_num_in_total)
        self.cohort = int(cohort)
        self.lag = int(lag)
        #: () -> ClientProfiler | None; default: the live fedpulse profiler
        self.profile_source = profile_source or _live_profiler
        self._lock = threading.Lock()
        self._plans: dict[int, np.ndarray] = {}
        #: [(round, snapshot)] ascending, bounded — the live capture store
        self._snaps: list[tuple[int, ProfileSnapshot]] = []
        self._static: Optional[ProfileSnapshot] = None
        self._warned_no_signal = False

    # -- feeds ---------------------------------------------------------------

    @property
    def wants_notify(self) -> bool:
        """Whether the consumer should call :meth:`notify_round_done` —
        only the live-fed profiler policies need boundary snapshots.
        Locked: set_static_profile can freeze the signal from another
        thread mid-run, and the check must see a settled _static."""
        with self._lock:
            return self.policy != "uniform" and self._static is None

    def set_static_profile(self, source) -> None:
        """Freeze the scheduling signal: ``source`` is a ProfileSnapshot or
        a ClientProfiler (snapshotted once, NOW). Every plan then derives
        from this one snapshot — timing- and pipeline-depth-independent,
        the xdev_ab determinism arm's mode. ``None`` clears it."""
        if source is None:
            snap = None
        elif isinstance(source, ProfileSnapshot):
            snap = source
        else:
            snap = source.snapshot()
        with self._lock:
            self._static = snap
            self._plans.clear()

    def notify_round_done(self, round_idx: int) -> None:
        """Round boundary: capture the live profiler snapshot labeled
        ``round_idx`` (no-op for uniform / static modes)."""
        if not self.wants_notify:
            return
        profiler = self.profile_source()
        if profiler is None:
            return
        snap = profiler.snapshot()
        with self._lock:
            if self._snaps and self._snaps[-1][0] >= round_idx:
                # bench re-runs / restore jumps revisit old rounds; the
                # snapshot store stays monotone so _snapshot_for's
                # "newest at or before r - lag" is well defined
                return
            self._snaps.append((int(round_idx), snap))
            del self._snaps[:-max(self.lag + 6, 8)]

    # -- queries -------------------------------------------------------------

    def _snapshot_for(self, round_idx: int) -> Optional[ProfileSnapshot]:
        if self._static is not None:
            return self._static
        target = round_idx - self.lag
        best = None
        for r, snap in self._snaps:
            if r <= target:
                # newest at or before the lag target; a background build
                # speculating deeper than the completed rounds naturally
                # lands on the newest snapshot available at build time —
                # the ledger then makes whichever snapshot won sticky
                best = snap
            else:
                break
        return best

    def sample(self, round_idx: int) -> np.ndarray:
        """The round's cohort plan (ledger-memoized; see module contract)."""
        r = int(round_idx)
        with self._lock:
            plan = self._plans.get(r)
            if plan is None:
                snap = self._snapshot_for(r)
                if (snap is None and self.policy != "uniform"
                        and not self._warned_no_signal
                        and self.profile_source() is None
                        and self._static is None):
                    log.warning(
                        "cohort_policy=%r has no profiler signal (pulse "
                        "plane off and no static profile); scheduling "
                        "uniform cold-starts until one appears", self.policy)
                    self._warned_no_signal = True
                plan = plan_cohort(r, self.client_num_in_total, self.cohort,
                                   self.seed, self.policy, snap)
                if len(self._plans) >= self.LEDGER_CAP:
                    self._plans.pop(next(iter(self._plans)))
                self._plans[r] = plan
            else:
                self._plans[r] = self._plans.pop(r)   # LRU refresh
        return plan


def _live_profiler():
    """Default profile source: the fedpulse plane's ClientProfiler (None
    while the plane is off — the scheduler then runs uniform cold-start)."""
    from fedml_tpu.obs.live import pulse_if_enabled

    plane = pulse_if_enabled()
    return plane.profiler if plane is not None else None
