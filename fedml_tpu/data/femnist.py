"""FederatedEMNIST + fed_cifar100 loaders — TFF h5 format, natural partition
(reference fedml_api/data_preprocessing/FederatedEMNIST/data_loader.py:26-151,
fed_cifar100/data_loader.py).

h5 layout: ``examples/<client_id>/pixels|image`` and ``label``. Synthetic
fallback keeps the natural-partition shape (3400 / 500 clients).
"""

from __future__ import annotations

import os

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool
from fedml_tpu.data.synthetic import make_synthetic_classification


def _h5_clients(path: str, x_key: str, y_key: str, limit: int):
    import h5py

    xs, ys = [], []
    with h5py.File(path, "r") as f:
        ex = f["examples"]
        for cid in list(ex.keys())[:limit]:
            xs.append(np.asarray(ex[cid][x_key]))
            ys.append(np.asarray(ex[cid][y_key], np.int32))
    return xs, ys


@register_dataset("femnist")
def load_femnist(
    data_dir: str = "./data/FederatedEMNIST/datasets",
    client_num_in_total: int = 3400,
    batch_size: int = 20,
    seed: int = 0,
    **_,
) -> FedDataset:
    train_h5 = os.path.join(data_dir, "fed_emnist_train.h5")
    test_h5 = os.path.join(data_dir, "fed_emnist_test.h5")
    if not (os.path.exists(train_h5) and os.path.exists(test_h5)):
        return make_synthetic_classification(
            "femnist(synthetic)", (28, 28, 1), 62, min(client_num_in_total, 400),
            records_per_client=30, batch_size=batch_size, seed=seed,
        )
    xs, ys = _h5_clients(train_h5, "pixels", "label", client_num_in_total)
    xs = [x.reshape(len(x), 28, 28, 1).astype(np.float32) for x in xs]
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    txs, tys = _h5_clients(test_h5, "pixels", "label", client_num_in_total)
    ex = np.concatenate([x.reshape(len(x), 28, 28, 1).astype(np.float32) for x in txs])
    ey = np.concatenate(tys)
    ex, ey, em = pad_eval_pool(ex, ey, 256)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=62, name="femnist",
    )


_FC100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
_FC100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)


@register_dataset("fed_cifar100")
def load_fed_cifar100(
    data_dir: str = "./data/fed_cifar100/datasets",
    client_num_in_total: int = 500,
    batch_size: int = 20,
    crop: int = 24,
    seed: int = 0,
    **_,
) -> FedDataset:
    train_h5 = os.path.join(data_dir, "fed_cifar100_train.h5")
    test_h5 = os.path.join(data_dir, "fed_cifar100_test.h5")
    if not (os.path.exists(train_h5) and os.path.exists(test_h5)):
        return make_synthetic_classification(
            "fed_cifar100(synthetic)", (crop, crop, 3), 100, min(client_num_in_total, 200),
            records_per_client=100, batch_size=batch_size, seed=seed,
        )
    xs, ys = _h5_clients(train_h5, "image", "label", client_num_in_total)
    off = (32 - crop) // 2

    def prep(x):
        x = ((x.astype(np.float32) / 255.0) - _FC100_MEAN) / _FC100_STD
        return x[:, off : off + crop, off : off + crop, :]

    xs = [prep(x) for x in xs]
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    txs, tys = _h5_clients(test_h5, "image", "label", client_num_in_total)
    ex, ey, em = pad_eval_pool(np.concatenate([prep(x) for x in txs]), np.concatenate(tys), 256)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=100, name="fed_cifar100",
    )
