"""StackOverflow datasets — logistic-regression tag prediction and next-word
prediction (reference fedml_api/data_preprocessing/stackoverflow_lr/
data_loader.py:25-130 and stackoverflow_nwp/, TFF h5, 342,477 clients).

The full corpus is ~342k clients; loaders take ``client_num_in_total`` as the
cap (the reference samples 50/round out of the full set). Synthetic fallback
mirrors shapes: LR = 10k-dim bag-of-words -> 500 multilabel tags; NWP =
token sequences of length 20 over a 10004-word vocab.
"""

from __future__ import annotations

import os

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool

WORD_DIM = 10000
TAG_DIM = 500
NWP_SEQ = 20
NWP_VOCAB = 10004


def _synthetic_so_lr(num_clients: int, batch_size: int, seed: int) -> FedDataset:
    rng = np.random.default_rng(seed)
    # low-rank word->tag structure so the linear model learns
    proj = rng.normal(0, 1, (WORD_DIM, TAG_DIM)).astype(np.float32)
    xs, ys = [], []
    for c in range(num_clients):
        n = int(rng.integers(8, 40))
        x = (rng.random((n, WORD_DIM)) < 0.002).astype(np.float32)
        scores = x @ proj
        y = (scores > np.quantile(scores, 0.99, axis=1, keepdims=True)).astype(np.float32)
        xs.append(x); ys.append(y)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(np.concatenate(xs)[:512], np.concatenate(ys)[:512], 128)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=TAG_DIM,
        task="tag_prediction", name="stackoverflow_lr(synthetic)",
    )


@register_dataset("stackoverflow_lr")
def load_stackoverflow_lr(
    data_dir: str = "./data/stackoverflow",
    client_num_in_total: int = 100,
    batch_size: int = 10,
    seed: int = 0,
    **_,
) -> FedDataset:
    h5 = os.path.join(data_dir, "stackoverflow_train.h5")
    if not os.path.exists(h5):
        return _synthetic_so_lr(min(client_num_in_total, 100), batch_size, seed)
    raise NotImplementedError(
        "real stackoverflow_lr requires the TFF h5 + vocab/tag tables; "
        "mount them under data_dir (see reference stackoverflow_lr/data_loader.py)"
    )


def _synthetic_so_nwp(num_clients: int, batch_size: int, seed: int) -> FedDataset:
    from fedml_tpu.data.shakespeare import _synthetic_nwp

    ds = _synthetic_nwp("stackoverflow_nwp(synthetic)", num_clients, NWP_VOCAB, NWP_SEQ, batch_size, seed)
    return ds


@register_dataset("stackoverflow_nwp")
def load_stackoverflow_nwp(
    data_dir: str = "./data/stackoverflow",
    client_num_in_total: int = 100,
    batch_size: int = 16,
    seed: int = 0,
    **_,
) -> FedDataset:
    h5 = os.path.join(data_dir, "stackoverflow_train.h5")
    if not os.path.exists(h5):
        return _synthetic_so_nwp(min(client_num_in_total, 100), batch_size, seed)
    raise NotImplementedError(
        "real stackoverflow_nwp requires the TFF h5 + vocab tables; "
        "mount them under data_dir (see reference stackoverflow_nwp/data_loader.py)"
    )
