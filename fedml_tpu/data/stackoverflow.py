"""StackOverflow datasets — logistic-regression tag prediction and next-word
prediction (reference fedml_api/data_preprocessing/stackoverflow_lr/
data_loader.py:25-130 and stackoverflow_nwp/, TFF h5, 342,477 clients).

The full corpus is ~342k clients; loaders take ``client_num_in_total`` as the
cap (the reference samples 50/round out of the full set). Synthetic fallback
mirrors shapes: LR = 10k-dim bag-of-words -> 500 multilabel tags; NWP =
token sequences of length 20 over a 10004-word vocab.
"""

from __future__ import annotations

import json
import os

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool

WORD_DIM = 10000
TAG_DIM = 500
NWP_SEQ = 20
NWP_VOCAB = 10004  # pad + 10k words + bos + eos + 1 oov bucket

WORD_COUNT_FILE = "stackoverflow.word_count"
TAG_COUNT_FILE = "stackoverflow.tag_count"


def _word_vocab(data_dir: str, vocab_size: int) -> dict[str, int]:
    """Top-``vocab_size`` words, one per ``word count`` line (reference
    stackoverflow_lr/utils.py:32-52)."""
    vocab: dict[str, int] = {}
    with open(os.path.join(data_dir, WORD_COUNT_FILE)) as f:
        for line in f:
            if len(vocab) >= vocab_size:
                break
            w = line.split()[0]
            if w not in vocab:
                vocab[w] = len(vocab)
    return vocab


def _tag_vocab(data_dir: str, tag_size: int) -> dict[str, int]:
    """Top-``tag_size`` tags from the json count table (reference
    stackoverflow_lr/utils.py:39-62)."""
    with open(os.path.join(data_dir, TAG_COUNT_FILE)) as f:
        counts = json.load(f)
    return {t: i for i, t in enumerate(list(counts)[:tag_size])}


def _h5_client_examples(h5_path: str, limit: int):
    """Yield (tokens, title, tags) string-arrays for the first ``limit``
    clients of a TFF stackoverflow h5 (layout ``examples/<client_id>/
    tokens|title|tags``, reference stackoverflow_lr/dataset.py:21-60)."""
    import h5py

    with h5py.File(h5_path, "r") as f:
        ex = f["examples"]
        for cid in list(ex.keys())[:limit]:
            g = ex[cid]
            toks = [b.decode("utf8") for b in g["tokens"][()]]
            titles = [b.decode("utf8") for b in g["title"][()]] if "title" in g else [""] * len(toks)
            tags = [b.decode("utf8") for b in g["tags"][()]]
            yield toks, titles, tags


def _bag_of_words(sentence: str, vocab: dict[str, int]) -> np.ndarray:
    """Mean multi-hot over the vocab; OOV tokens fall off the end (reference
    stackoverflow_lr/utils.py:65-84 keeps only the first vocab_size dims)."""
    out = np.zeros(len(vocab), np.float32)
    toks = sentence.split(" ")
    for t in toks:
        i = vocab.get(t)
        if i is not None:
            out[i] += 1.0
    if toks:
        out /= len(toks)
    return out


def _multi_hot_tags(tag: str, tags: dict[str, int]) -> np.ndarray:
    out = np.zeros(len(tags), np.float32)
    for t in tag.split("|"):
        i = tags.get(t)
        if i is not None:
            out[i] = 1.0
    return out


def _load_so_lr_h5(data_dir: str, client_num: int, batch_size: int) -> FedDataset:
    vocab = _word_vocab(data_dir, WORD_DIM)
    tags = _tag_vocab(data_dir, TAG_DIM)
    xs, ys = [], []
    for toks, titles, tg in _h5_client_examples(
        os.path.join(data_dir, "stackoverflow_train.h5"), client_num
    ):
        x = np.stack([_bag_of_words(" ".join(p for p in (a, b) if p), vocab)
                      for a, b in zip(toks, titles)])
        y = np.stack([_multi_hot_tags(t, tags) for t in tg])
        xs.append(x); ys.append(y)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    test_h5 = os.path.join(data_dir, "stackoverflow_test.h5")
    if os.path.exists(test_h5):
        ex_list, ey_list = [], []
        for toks, titles, tg in _h5_client_examples(test_h5, client_num):
            ex_list.append(np.stack(
                [_bag_of_words(" ".join(p for p in (a, b) if p), vocab)
                 for a, b in zip(toks, titles)]))
            ey_list.append(np.stack([_multi_hot_tags(t, tags) for t in tg]))
        pool_x, pool_y = np.concatenate(ex_list), np.concatenate(ey_list)
    else:
        pool_x, pool_y = np.concatenate(xs), np.concatenate(ys)
    ex, ey, em = pad_eval_pool(pool_x, pool_y, max(batch_size, 32))
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=len(tags),
        task="tag_prediction", name="stackoverflow_lr",
    )


def _nwp_ids(sentence: str, vocab: dict[str, int]) -> np.ndarray:
    """bos + truncated token ids (+eos if short) padded to NWP_SEQ+1 ids
    (reference stackoverflow_nwp/utils.py:56-84: pad=0, words=1..V,
    bos=V+1, eos=V+2, one OOV bucket=V+3)."""
    V = len(vocab)
    pad, bos, eos, oov = 0, V + 1, V + 2, V + 3
    toks = sentence.split(" ")[:NWP_SEQ]
    ids = [vocab[t] + 1 if t in vocab else oov for t in toks]
    if len(ids) < NWP_SEQ:
        ids.append(eos)
    ids = [bos] + ids
    ids += [pad] * (NWP_SEQ + 1 - len(ids))
    return np.asarray(ids[: NWP_SEQ + 1], np.int32)


def _load_so_nwp_h5(data_dir: str, client_num: int, batch_size: int) -> FedDataset:
    vocab = _word_vocab(data_dir, WORD_DIM)

    def read(path, limit):
        xs, ys = [], []
        for toks, _titles, _tg in _h5_client_examples(path, limit):
            seq = np.stack([_nwp_ids(s, vocab) for s in toks])
            xs.append(seq[:, :-1]); ys.append(seq[:, 1:])
        return xs, ys

    xs, ys = read(os.path.join(data_dir, "stackoverflow_train.h5"), client_num)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    test_h5 = os.path.join(data_dir, "stackoverflow_test.h5")
    if os.path.exists(test_h5):
        exs, eys = read(test_h5, client_num)
        pool_x, pool_y = np.concatenate(exs), np.concatenate(eys)
    else:
        pool_x, pool_y = np.concatenate(xs), np.concatenate(ys)
    ex, ey, em = pad_eval_pool(pool_x, pool_y, max(batch_size, 32))
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=len(vocab) + 4,
        task="nwp", name="stackoverflow_nwp",
    )


def _synthetic_so_lr(num_clients: int, batch_size: int, seed: int) -> FedDataset:
    rng = np.random.default_rng(seed)
    # low-rank word->tag structure so the linear model learns
    proj = rng.normal(0, 1, (WORD_DIM, TAG_DIM)).astype(np.float32)
    xs, ys = [], []
    for c in range(num_clients):
        n = int(rng.integers(8, 40))
        x = (rng.random((n, WORD_DIM)) < 0.002).astype(np.float32)
        scores = x @ proj
        y = (scores > np.quantile(scores, 0.99, axis=1, keepdims=True)).astype(np.float32)
        xs.append(x); ys.append(y)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(np.concatenate(xs)[:512], np.concatenate(ys)[:512], 128)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=TAG_DIM,
        task="tag_prediction", name="stackoverflow_lr(synthetic)",
    )


@register_dataset("stackoverflow_lr")
def load_stackoverflow_lr(
    data_dir: str = "./data/stackoverflow",
    client_num_in_total: int = 100,
    batch_size: int = 10,
    seed: int = 0,
    **_,
) -> FedDataset:
    h5 = os.path.join(data_dir, "stackoverflow_train.h5")
    if not os.path.exists(h5):
        if client_num_in_total > 4096:
            # the reference's real operating point (342,477 clients): the
            # stacked fallback cannot hold that, so serve the cross-device
            # sampled-materialization dataset at the full client count
            from fedml_tpu.data.crossdevice import load_stackoverflow_lr_full

            return load_stackoverflow_lr_full(
                client_num_in_total=client_num_in_total,
                batch_size=batch_size, seed=seed)
        return _synthetic_so_lr(min(client_num_in_total, 100), batch_size, seed)
    missing = [f for f in (WORD_COUNT_FILE, TAG_COUNT_FILE)
               if not os.path.exists(os.path.join(data_dir, f))]
    if missing:
        raise FileNotFoundError(
            f"stackoverflow_train.h5 is mounted but the vocab tables {missing} "
            f"are missing from {data_dir}; refusing to fall back to synthetic "
            "data silently"
        )
    return _load_so_lr_h5(data_dir, client_num_in_total, batch_size)


def _synthetic_so_nwp(num_clients: int, batch_size: int, seed: int) -> FedDataset:
    from fedml_tpu.data.shakespeare import _synthetic_nwp

    ds = _synthetic_nwp("stackoverflow_nwp(synthetic)", num_clients, NWP_VOCAB, NWP_SEQ, batch_size, seed)
    return ds


@register_dataset("stackoverflow_nwp")
def load_stackoverflow_nwp(
    data_dir: str = "./data/stackoverflow",
    client_num_in_total: int = 100,
    batch_size: int = 16,
    seed: int = 0,
    **_,
) -> FedDataset:
    h5 = os.path.join(data_dir, "stackoverflow_train.h5")
    if not os.path.exists(h5):
        return _synthetic_so_nwp(min(client_num_in_total, 100), batch_size, seed)
    if not os.path.exists(os.path.join(data_dir, WORD_COUNT_FILE)):
        raise FileNotFoundError(
            f"stackoverflow_train.h5 is mounted but {WORD_COUNT_FILE} is "
            f"missing from {data_dir}; refusing to fall back to synthetic "
            "data silently"
        )
    return _load_so_nwp_h5(data_dir, client_num_in_total, batch_size)
