"""Vertical (feature-partitioned) datasets for classical VFL.

Counterpart of the reference's vertical-FL loaders, which split ONE table's
feature columns across parties:

- lending_club: party A = qualification+loan features, party B =
  debt+repayment(+multi_acc+mal_behavior) — lending_club_dataset.py:141-190,
- NUS_WIDE: party A = low-level image features, party B = tag features —
  NUS_WIDE/nus_wide_dataset.py:23-230,
- UCI credit default — UCI/.

All reference loaders reduce to the same contract: ``(Xa, y)`` for the
label-holding guest and ``Xb[, Xc]`` for the hosts, already row-aligned.
:class:`VerticalDataset` captures that contract; the real-file loaders are
gated on the files existing on disk (zero-egress environment) and otherwise
fall back to a synthetic table with the same party feature-widths, so every
algorithm and test path exercises identical code.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass
class VerticalDataset:
    """Row-aligned feature-partitioned dataset; party 0 is the guest
    (holds the binary labels), parties 1.. are hosts."""

    train_parts: list[np.ndarray]     # per-party [n_train, d_p] float32
    train_y: np.ndarray               # [n_train] {0,1} float32
    test_parts: list[np.ndarray]
    test_y: np.ndarray
    name: str = ""

    @property
    def num_parties(self) -> int:
        return len(self.train_parts)

    @property
    def party_dims(self) -> list[int]:
        return [int(p.shape[1]) for p in self.train_parts]


def make_synthetic_vertical(
    party_dims: Sequence[int] = (12, 10),
    n_train: int = 512,
    n_test: int = 128,
    seed: int = 0,
    name: str = "synthetic_vertical",
) -> VerticalDataset:
    """Learnable two/three-party binary task: the label depends on ALL
    parties' features, so a guest-only model underperforms the federation —
    the property VFL exists to demonstrate."""
    rng = np.random.default_rng(seed)
    n = n_train + n_test
    parts = [rng.normal(0, 1, (n, d)).astype(np.float32) for d in party_dims]
    ws = [rng.normal(0, 1, (d,)) for d in party_dims]
    score = sum(p @ w for p, w in zip(parts, ws)) + 0.3 * rng.normal(0, 1, n)
    y = (score > np.median(score)).astype(np.float32)
    return VerticalDataset(
        train_parts=[p[:n_train] for p in parts],
        train_y=y[:n_train],
        test_parts=[p[n_train:] for p in parts],
        test_y=y[n_train:],
        name=name,
    )


def _standardize(x: np.ndarray) -> np.ndarray:
    mu, sd = x.mean(0, keepdims=True), x.std(0, keepdims=True)
    return ((x - mu) / np.maximum(sd, 1e-6)).astype(np.float32)


def load_lending_club(
    data_dir: str, party_num: int = 2, test_frac: float = 0.2, seed: int = 0
) -> VerticalDataset:
    """Lending-club loan VFL split (lending_club_dataset.py:141-190). Expects
    a preprocessed ``loan_processed.npz`` with arrays X (features ordered as
    qualification|loan|debt|repayment|multi_acc|mal_behavior), y, and
    ``party_cuts`` giving the column index where each party's slice starts.
    Falls back to a synthetic table with the reference's party widths."""
    path = os.path.join(data_dir, "lending_club", "loan_processed.npz")
    if not os.path.exists(path):
        dims = (17, 25) if party_num == 2 else (17, 15, 10)
        return make_synthetic_vertical(dims, seed=seed, name="lending_club_synth")
    blob = np.load(path)
    X, y = blob["X"], blob["y"].astype(np.float32)
    cuts = list(blob["party_cuts"])[: party_num - 1]
    cols = np.split(np.arange(X.shape[1]), cuts)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(X))
    n_test = int(len(X) * test_frac)
    tr, te = order[n_test:], order[:n_test]
    parts = [_standardize(X[:, c]) for c in cols]
    return VerticalDataset(
        train_parts=[p[tr] for p in parts], train_y=y[tr],
        test_parts=[p[te] for p in parts], test_y=y[te],
        name="lending_club",
    )


def load_uci_credit(
    data_dir: str, test_frac: float = 0.2, seed: int = 0
) -> VerticalDataset:
    """UCI default-of-credit-card-clients two-party split (reference
    UCI/ loader): party A = demographic columns, party B = bill/payment
    history. Expects ``uci_credit.npz`` with X [n, 23], y; synthetic
    fallback keeps those widths (A=5 demographics, B=18 history)."""
    path = os.path.join(data_dir, "UCI", "uci_credit.npz")
    if not os.path.exists(path):
        return make_synthetic_vertical((5, 18), seed=seed, name="uci_credit_synth")
    blob = np.load(path)
    X, y = _standardize(blob["X"]), blob["y"].astype(np.float32)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(y))
    n_test = int(len(y) * test_frac)
    tr, te = order[n_test:], order[:n_test]
    parts = [X[:, :5], X[:, 5:]]
    return VerticalDataset(
        train_parts=[p[tr] for p in parts], train_y=y[tr],
        test_parts=[p[te] for p in parts], test_y=y[te],
        name="uci_credit",
    )


def load_nus_wide(
    data_dir: str, selected_label: str = "sky", test_frac: float = 0.2, seed: int = 0
) -> VerticalDataset:
    """NUS-WIDE two-party split: guest = 634-d low-level image features,
    host = 1000-d tag features (nus_wide_dataset.py:23-230). Expects
    ``nus_wide_processed.npz`` with XA, XB, y; synthetic fallback keeps the
    reference widths (downscaled 4x to stay CI-sized)."""
    path = os.path.join(data_dir, "NUS_WIDE", "nus_wide_processed.npz")
    if not os.path.exists(path):
        return make_synthetic_vertical((158, 250), seed=seed, name="nus_wide_synth")
    blob = np.load(path)
    # standardize once over the full matrix (train stats leak into test
    # scaling either way; matching lending_club keeps both splits on the
    # same affine transform)
    XA, XB = _standardize(blob["XA"]), _standardize(blob["XB"])
    y = blob["y"].astype(np.float32)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(y))
    n_test = int(len(y) * test_frac)
    tr, te = order[n_test:], order[:n_test]
    return VerticalDataset(
        train_parts=[XA[tr], XB[tr]], train_y=y[tr],
        test_parts=[XA[te], XB[te]], test_y=y[te],
        name="nus_wide",
    )
