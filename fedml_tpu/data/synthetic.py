"""Synthetic federated datasets.

1. ``synthetic_1_1`` etc. — the LEAF synthetic(alpha, beta) logistic-regression
   task with power-law client sizes (reference
   fedml_api/data_preprocessing/synthetic_1_1/, ~75 LoC): per-client model
   W_k ~ N(u_k, 1), u_k ~ N(0, alpha); features x ~ N(v_k, Sigma) with
   v_k ~ N(B_k, 1), B_k ~ N(0, beta); labels argmax(Wx + b).
2. ``make_synthetic_classification`` — generic learnable image/vector task
   used by every real-data loader as its zero-egress fallback: class means
   separated in input space so accuracy is meaningfully learnable.
"""

from __future__ import annotations

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool
from fedml_tpu.core.partition import partition as partition_fn


def _power_law_sizes(num_clients: int, rng: np.random.Generator, min_size: int = 10, mean: float = 40.0):
    sizes = (rng.lognormal(np.log(mean), 1.0, num_clients)).astype(int)
    return np.clip(sizes, min_size, None)


def make_synthetic_lr(
    alpha: float = 1.0,
    beta: float = 1.0,
    num_clients: int = 30,
    dim: int = 60,
    classes: int = 10,
    batch_size: int = 10,
    seed: int = 0,
) -> FedDataset:
    rng = np.random.default_rng(seed)
    sizes = _power_law_sizes(num_clients, rng)
    # diagonal covariance x_j ~ j^-1.2 (LEAF recipe)
    diag = np.array([(j + 1) ** -1.2 for j in range(dim)])
    xs, ys, test_xs, test_ys = [], [], [], []
    for k in range(num_clients):
        u_k = rng.normal(0, alpha)
        W = rng.normal(u_k, 1, (dim, classes))
        b = rng.normal(u_k, 1, classes)
        B_k = rng.normal(0, beta)
        v_k = rng.normal(B_k, 1, dim)
        n = int(sizes[k]) + 8  # extra records become the test split
        # x ~ N(v_k, Sigma): the diagonal covariance scales the NOISE only
        # (scaling the mean too would shrink the inter-client signal in
        # later feature dims and make the task much harder than LEAF's)
        x = v_k + rng.normal(0, 1, (n, dim)) * np.sqrt(diag)
        y = np.argmax(x @ W + b, axis=1)
        xs.append(x[:-8].astype(np.float32)); ys.append(y[:-8].astype(np.int32))
        test_xs.append(x[-8:].astype(np.float32)); test_ys.append(y[-8:].astype(np.int32))
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(np.concatenate(test_xs), np.concatenate(test_ys), 256)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=classes,
        name=f"synthetic_{alpha}_{beta}",
    )


@register_dataset("synthetic_1_1")
def _syn11(num_clients: int = 30, batch_size: int = 10, seed: int = 0, **_):
    return make_synthetic_lr(1.0, 1.0, num_clients, batch_size=batch_size, seed=seed)


@register_dataset("synthetic_0_0")
def _syn00(num_clients: int = 30, batch_size: int = 10, seed: int = 0, **_):
    return make_synthetic_lr(0.0, 0.0, num_clients, batch_size=batch_size, seed=seed)


@register_dataset("synthetic_0.5_0.5")
def _syn55(num_clients: int = 30, batch_size: int = 10, seed: int = 0, **_):
    return make_synthetic_lr(0.5, 0.5, num_clients, batch_size=batch_size, seed=seed)


def make_synthetic_classification(
    name: str,
    input_shape: tuple,
    classes: int,
    num_clients: int,
    records_per_client: int = 64,
    test_records: int = 512,
    partition_method: str = "hetero",
    partition_alpha: float = 0.5,
    batch_size: int = 32,
    seed: int = 0,
    dtype=np.float32,
    integer_inputs: bool = False,
    vocab: int = 0,
    data_dir: str = "./data",
    separation: float = 1.0,
    label_noise: float = 0.0,
) -> FedDataset:
    """Learnable stand-in with the same shapes/partition semantics as the real
    dataset (used when the files aren't on disk — this image has no egress).

    Class-conditional gaussian blobs (images/vectors) or class-biased token
    streams (integer inputs) so models actually learn; partitioned with the
    real Dirichlet machinery so non-IID behavior is exercised.
    """
    rng = np.random.default_rng(seed)
    n_total = num_clients * records_per_client + test_records
    y = rng.integers(0, classes, n_total).astype(np.int32)
    y_clean = y
    if integer_inputs:
        # biased token stream: class c prefers tokens around c * vocab/classes
        base = (y[:, None] * (vocab // max(classes, 1))) % max(vocab, 1)
        x = (base + rng.integers(0, max(vocab // 4, 1), (n_total,) + input_shape)) % vocab
        x = x.astype(np.int32)
    else:
        dim = int(np.prod(input_shape))
        # separation scales the class-mean spread relative to unit noise: in
        # high dim the default blobs are many sigma apart (trivially
        # separable), so convergence-pin tests shrink it to land mid-range
        # accuracy where dtype/precision drift is actually visible
        means = rng.normal(0, 1.0, (classes, dim)) * separation
        x = (means[y_clean] + rng.normal(0, 1.0, (n_total, dim))).astype(dtype)
        x = x.reshape((n_total,) + tuple(input_shape))
    if label_noise > 0.0:
        # symmetric label noise: features stay class-conditional on the
        # CLEAN label, a ``label_noise`` fraction of OBSERVED labels is
        # resampled uniformly — an irreducible accuracy ceiling of
        # (1 - rho) + rho/classes for train AND test, the difficulty knob
        # the non-saturating accuracy benchmark calibrates
        # (tools/accuracy_run.py, VERDICT r4 #5)
        flip = rng.random(n_total) < label_noise
        y = np.where(flip, rng.integers(0, classes, n_total), y).astype(np.int32)
    train_x, train_y = x[:-test_records], y[:-test_records]
    test_x, test_y = x[-test_records:], y[-test_records:]
    import os

    idx_map = partition_fn(
        partition_method, train_y, num_clients, classes, partition_alpha,
        seed=seed,
        # synthetic labels depend on the seed, so the fixed map is keyed on
        # alpha AND seed (a real dataset's labels are seed-independent)
        map_path=os.path.join(
            data_dir,
            f"{name}_partition_{num_clients}_a{partition_alpha}_s{seed}.npz"),
    )
    xs = [train_x[idx_map[i]] for i in range(num_clients)]
    ys = [train_y[idx_map[i]] for i in range(num_clients)]
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(test_x, test_y, 256)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=classes, name=name,
    )
