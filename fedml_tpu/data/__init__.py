"""Federated data layer.

The reference's loader contract is an 8-tuple of torch DataLoaders
(train_data_num, test_data_num, train_data_global, test_data_global,
train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
class_num) — cifar10/data_loader.py:235-269. A DataLoader-per-client is
hostile to XLA: ragged shapes, Python iteration, per-batch host->device hops.

The TPU-native contract is :class:`FedDataset`: every client's records are
padded to one static shape and stacked along a leading client axis, with a
mask marking real records. One ``vmap``/``shard_map`` then trains all
clients without a single dynamic shape. Loaders register under the
reference's --dataset names.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

_LOADERS: dict[str, Callable[..., "FedDataset"]] = {}


def register_dataset(*names: str):
    def deco(fn):
        for n in names:
            _LOADERS[n] = fn
        return fn
    return deco


@dataclass
class FedDataset:
    """Stacked, padded, mask-aware federated dataset (host numpy; algorithms
    move slices to device per round)."""

    # per-client train data: leaves [num_clients, n_pad, ...]
    train_x: np.ndarray
    train_y: np.ndarray
    train_mask: np.ndarray          # [num_clients, n_pad] {0,1}
    train_counts: np.ndarray        # [num_clients] true record counts
    # global test pool (already padded to a batch multiple by loaders)
    test_x: np.ndarray
    test_y: np.ndarray
    test_mask: np.ndarray           # [n_test_pad]
    class_num: int
    task: str = "classification"
    # optional per-client test split (cross-device eval), same stacked scheme
    test_x_local: Optional[np.ndarray] = None
    test_y_local: Optional[np.ndarray] = None
    test_mask_local: Optional[np.ndarray] = None
    name: str = ""

    #: True for cross-device datasets whose client stack is never
    #: materialized (data/crossdevice.py) — algorithms must use
    #: client_slice/client_arrays and keep memory O(cohort)
    virtual = False

    @property
    def num_clients(self) -> int:
        return int(self.train_x.shape[0])

    @property
    def train_data_num(self) -> int:
        return int(self.train_counts.sum())

    @property
    def test_data_num(self) -> int:
        return int(self.test_mask.sum())

    def client_slice(self, idx: np.ndarray):
        """Gather sampled clients' arrays (host-side; the result ships to
        device once per round — the only host->device transfer in a round)."""
        return (
            self.train_x[idx],
            self.train_y[idx],
            self.train_mask[idx],
            self.train_counts[idx],
        )

    def client_arrays(self, k: int):
        """One client's (x, y, mask) — the streaming paradigm's accessor
        (virtual datasets materialize it on demand)."""
        return self.train_x[k], self.train_y[k], self.train_mask[k]

    def client_slice_cached(self, k: int, cap: int = 64):
        """Single-client :meth:`client_slice` behind a tiny per-dataset LRU.

        The edge/streaming call sites re-request the SAME client's slice
        every epoch/round (the reference's DataLoader-per-client contract,
        FedAVGTrainer.py:4-52); for virtual cross-device datasets each
        request re-materializes the client's records from its RNG stream.
        The LRU makes repeats O(1) and keeps a CrossDeviceDataset's
        ``materialized_rows`` proportional to UNIQUE clients requested, not
        epochs x rounds. Returned arrays are shared across callers and
        must be treated as read-only. Thread-safe and SINGLE-FLIGHT:
        concurrent misses for the same client materialize once and share
        the result (the host round pipeline prefetches adjacent rounds
        concurrently, and adjacent cohorts can share clients)."""
        from concurrent.futures import Future

        k = int(k)
        lock = self.__dict__.setdefault("_client_lru_lock", threading.Lock())
        cache = self.__dict__.setdefault("_client_lru", {})
        pending = self.__dict__.setdefault("_client_lru_pending", {})
        with lock:
            hit = cache.get(k)
            if hit is not None:
                cache[k] = cache.pop(k)    # dict order is recency
                return hit
            fut = pending.get(k)
            if fut is None:
                fut = pending[k] = Future()
                owner = True
            else:
                owner = False
        if not owner:
            return fut.result()
        try:
            out = self.client_slice(np.asarray([k]))
            for a in out:
                if isinstance(a, np.ndarray):
                    # enforce the read-only contract: an in-place write
                    # through a cached slice would silently corrupt every
                    # later hit — make it an immediate ValueError instead
                    a.flags.writeable = False
        except BaseException as e:
            with lock:
                pending.pop(k, None)       # next request retries fresh
            fut.set_exception(e)
            raise
        with lock:
            cache[k] = out
            while len(cache) > cap:
                cache.pop(next(iter(cache)))
            pending.pop(k, None)
        fut.set_result(out)
        return out


def load_dataset(name: str, **kw) -> FedDataset:
    """Dispatch on the reference's --dataset flag values (mnist, femnist,
    shakespeare, fed_shakespeare, fed_cifar100, stackoverflow_lr,
    stackoverflow_nwp, cifar10, cifar100, cinic10, synthetic_1_1, ...)."""
    from fedml_tpu.data import (  # noqa: F401
        cifar, crossdevice, femnist, imagenet, mnist, segmentation, shakespeare, stackoverflow, synthetic,
    )
    if name not in _LOADERS:
        raise KeyError(f"unknown dataset {name!r}; known: {sorted(_LOADERS)}")
    return _LOADERS[name](**kw)


def known_datasets() -> list[str]:
    from fedml_tpu.data import (  # noqa: F401
        cifar, crossdevice, femnist, imagenet, mnist, segmentation, shakespeare, stackoverflow, synthetic,
    )
    return sorted(_LOADERS)
