"""Cross-device scale: datasets with 10^5-10^6 logical clients.

The stacked :class:`~fedml_tpu.data.FedDataset` contract materializes every
client's padded records up front — right for cross-silo (tens of silos,
device-resident rounds), impossible at the reference's cross-device scale
(stackoverflow: 342,477 clients, 50/round —
reference fedml_api/data_preprocessing/stackoverflow_lr/data_loader.py:25-130,
benchmark/README.md:57). The reference streams each sampled client from h5
at round time; the TPU-native counterpart here keeps the same sampled-
materialization idea with the stacked-cohort contract:

- :class:`CrossDeviceDataset` holds ONLY O(num_clients) metadata (the
  per-client record counts) plus the test pool. ``train_x/y/mask`` are
  :class:`VirtualArray` stubs that carry shape/dtype for the planners and
  RAISE on any data access — nothing can silently densify 342k clients.
- ``client_slice(sampled)`` materializes just the round's cohort
  ([cohort, n_pad, ...]) through a ``materialize`` callback: memory is
  O(cohort), independent of the client total. The FedAvg host path ships
  exactly this slice per round; ``client_arrays(k)`` feeds the streaming
  paradigm one client at a time.
- Each synthetic client's records derive deterministically from
  (seed, client_id) — any cohort is reproducible without generating the
  other 342k clients.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_eval_pool


class VirtualArray:
    """Shape/dtype facade for a never-materialized stacked client array.

    Planners read ``.shape``/``.dtype``/``.nbytes`` (the device-residency
    eligibility check sees the VIRTUAL byte count and correctly declines);
    any attempt to read data raises instead of silently densifying."""

    def __init__(self, shape: tuple, dtype):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def __len__(self) -> int:
        return self.shape[0]

    def _refuse(self, *_a, **_k):
        raise RuntimeError(
            "this dataset is cross-device scale (virtual client stack of "
            f"shape {self.shape}); materialize cohorts via client_slice() "
            "instead of touching train_x/train_y/train_mask directly")

    __getitem__ = _refuse
    __array__ = _refuse
    astype = _refuse


class CrossDeviceDataset(FedDataset):
    """FedDataset whose client stack is materialized per-cohort on demand.

    ``materialize(ids) -> (x, y, mask)`` returns the stacked padded arrays
    for exactly the given client ids ([len(ids), n_pad, ...]).
    ``materialized_rows`` counts every padded record row ever produced —
    tests assert it stays O(rounds * cohort * n_pad), the memory-bound
    evidence the r4 verdict asked for."""

    virtual = True

    def __init__(self, *, materialize: Callable, counts: np.ndarray,
                 n_pad: int, sample_shape: tuple, x_dtype, y_shape: tuple,
                 y_dtype, test_x, test_y, test_mask, class_num: int,
                 task: str = "classification", name: str = ""):
        counts = np.asarray(counts)
        n_clients = int(counts.shape[0])
        super().__init__(
            train_x=VirtualArray((n_clients, n_pad) + tuple(sample_shape),
                                 x_dtype),
            train_y=VirtualArray((n_clients, n_pad) + tuple(y_shape), y_dtype),
            train_mask=VirtualArray((n_clients, n_pad), np.float32),
            train_counts=counts,
            test_x=test_x, test_y=test_y, test_mask=test_mask,
            class_num=class_num, task=task, name=name,
        )
        self._materialize = materialize
        self.materialized_rows = 0
        # the host round pipeline materializes cohort chunks from several
        # threads at once (data/pipeline.materialize_cohort); the counter
        # must not lose increments to racing read-modify-writes
        self._rows_lock = threading.Lock()

    def _count_rows(self, x: np.ndarray) -> None:
        with self._rows_lock:
            self.materialized_rows += int(np.prod(x.shape[:2]))

    def client_slice(self, idx: np.ndarray):
        idx = np.asarray(idx)
        x, y, m = self._materialize(idx)
        self._count_rows(x)
        return x, y, m, self.train_counts[idx]

    def client_arrays(self, k: int):
        x, y, m, _c = self.client_slice_cached(k)
        return x[0], y[0], m[0]


def _client_rng(seed: int, client_id: int) -> np.random.Generator:
    """Deterministic per-client stream independent of every other client."""
    return np.random.default_rng(
        np.random.SeedSequence(entropy=seed, spawn_key=(int(client_id),)))


def make_synthetic_crossdevice(
    name: str,
    input_dim: int,
    classes: int,
    num_clients: int,
    *,
    batch_size: int = 10,
    mean_records: float = 20.0,
    max_records: int = 64,
    test_records: int = 512,
    label_alpha: float = 0.3,
    separation: float = 1.0,
    multilabel: bool = False,
    seed: int = 0,
) -> CrossDeviceDataset:
    """Cross-device classification/tag task at any client count.

    Per-client record counts are lognormal (clipped to ``max_records`` so
    n_pad is bounded); each client draws a Dirichlet(``label_alpha``) label
    preference from its own (seed, id) stream — the standard cross-device
    non-IID structure — and features are class-mean gaussians, so models
    actually learn. Counts for ALL clients are one vectorized draw
    (O(num_clients) ints); records exist only for materialized cohorts."""
    gl = np.random.default_rng(seed)
    counts = np.clip(
        gl.lognormal(np.log(mean_records), 0.8, num_clients), 1, max_records
    ).astype(np.int64)
    n_pad = int(-(-max_records // batch_size) * batch_size)
    # class structure shared by all clients (O(classes * dim) memory)
    means = (gl.standard_normal((classes, input_dim)).astype(np.float32)
             * separation)

    def _gen(rng: np.random.Generator, n: int):
        if multilabel:
            # Each record activates a few of the client's preferred tags.
            # Tag sets are drawn VECTORIZED via Gumbel top-k — an exact
            # weighted sample without replacement (Plackett-Luce), replacing
            # the per-record rng.choice loop that dominated cohort
            # materialization at the stackoverflow row's 500-tag scale.
            # Documented draw order (pinned by tests/test_crossdevice.py):
            # dirichlet(pref) -> poisson(k_tags) -> gumbel[n, classes] ->
            # standard_normal feature noise.
            pref = rng.dirichlet(np.full(classes, label_alpha))
            k_tags = 1 + rng.poisson(1.0, n).clip(max=4)
            with np.errstate(divide="ignore"):   # pref underflow -> never picked
                scores = np.log(pref)[None, :] + rng.gumbel(size=(n, classes))
            order = np.argsort(-scores, axis=1, kind="stable")[:, :int(k_tags.max())]
            sel = np.arange(order.shape[1])[None, :] < k_tags[:, None]
            y = np.zeros((n, classes), np.float32)
            y[np.arange(n)[:, None], order] = sel.astype(np.float32)
            # mean of the selected tags' class means: k_max (<= 5) gathered
            # fused-weight terms, x_r = sum_j means[order_rj] * sel_rj/k_r —
            # never a dense (n, classes) matmul, which would burn
            # classes/k_tags x the flops at the 500-tag 10k-dim shape, and
            # no (n, k_max, dim) intermediate either
            w = (sel / k_tags[:, None]).astype(np.float32)
            x = means[order[:, 0]] * w[:, 0:1]
            for j in range(1, order.shape[1]):
                x += means[order[:, j]] * w[:, j:j + 1]
            x += rng.standard_normal((n, input_dim)).astype(np.float32)
            return x, y
        pref = rng.dirichlet(np.full(classes, label_alpha))
        y = rng.choice(classes, size=n, p=pref).astype(np.int32)
        x = means[y] + rng.standard_normal((n, input_dim)).astype(np.float32)
        return x.astype(np.float32), y

    y_shape = (classes,) if multilabel else ()
    y_dtype = np.float32 if multilabel else np.int32

    def materialize(ids: np.ndarray):
        m = len(ids)
        x = np.zeros((m, n_pad, input_dim), np.float32)
        y = np.zeros((m, n_pad) + y_shape, y_dtype)
        mask = np.zeros((m, n_pad), np.float32)
        for j, cid in enumerate(ids):
            n = int(counts[cid])
            cx, cy = _gen(_client_rng(seed, int(cid)), n)
            x[j, :n] = cx
            y[j, :n] = cy
            mask[j, :n] = 1.0
        return x, y, mask

    # test pool from held-out pseudo-clients (ids beyond num_clients)
    tx_parts, ty_parts = [], []
    rows = 0
    cid = num_clients
    while rows < test_records:
        cx, cy = _gen(_client_rng(seed, cid), int(
            min(max_records, test_records - rows)))
        tx_parts.append(cx); ty_parts.append(cy)
        rows += cx.shape[0]
        cid += 1
    ex, ey, em = pad_eval_pool(np.concatenate(tx_parts),
                               np.concatenate(ty_parts), 256)
    return CrossDeviceDataset(
        materialize=materialize, counts=counts, n_pad=n_pad,
        sample_shape=(input_dim,), x_dtype=np.float32,
        y_shape=y_shape, y_dtype=y_dtype,
        test_x=ex, test_y=ey, test_mask=em, class_num=classes,
        task="tag_prediction" if multilabel else "classification",
        name=name,
    )


@register_dataset("stackoverflow_lr_full")
def load_stackoverflow_lr_full(
    client_num_in_total: int = 342_477,
    batch_size: int = 10,
    seed: int = 0,
    **_,
) -> CrossDeviceDataset:
    """The reference's cross-device operating point — 342,477 logical
    clients (benchmark/README.md:57) — at its REAL scale, zero-egress:
    10k-dim bag-of-words-shaped features, 500 multilabel tags, lognormal
    client sizes, per-client Dirichlet tag preference. Memory is
    O(client_num) counts + O(cohort) per round."""
    from fedml_tpu.data.stackoverflow import TAG_DIM, WORD_DIM

    return make_synthetic_crossdevice(
        "stackoverflow_lr_full", WORD_DIM, TAG_DIM, client_num_in_total,
        batch_size=batch_size, mean_records=20.0, max_records=64,
        multilabel=True, seed=seed)
