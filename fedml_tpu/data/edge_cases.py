"""Edge-case backdoor datasets (poisoned federations).

Counterpart of reference fedml_api/data_preprocessing/edge_case_examples/
data_loader.py:283 ``load_poisoned_dataset``: attacker clients' training
data is augmented with "edge-case" examples — real-looking inputs from a
rare tail distribution relabeled to the attacker's target class (southwest
airliners -> 'truck' in CIFAR-10, ARDIS digits -> '7' in EMNIST) — plus a
backdoor test set to measure targeted success.

Real poison archives are file-gated (zero egress); the fallback synthesizes
an off-manifold edge cluster: inputs drawn far from every class mean,
labeled with the target class. This preserves the measurement the
reference's datasets exist for — clean accuracy vs targeted backdoor
accuracy — with no downloaded data.
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass

import numpy as np

from fedml_tpu.data import FedDataset


@dataclass
class PoisonedFederation:
    dataset: FedDataset            # train data with attacker clients poisoned
    attacker_clients: list         # indices of poisoned clients
    target_class: int
    edge_test_x: np.ndarray        # backdoor eval inputs
    edge_test_y: np.ndarray        # all == target_class
    edge_test_true_y: np.ndarray   # what they SHOULD be classified as


def _synthesize_edge_cases(
    base: FedDataset, n: int, target_class: int, rng: np.random.Generator
) -> tuple[np.ndarray, np.ndarray]:
    """Off-manifold cluster: base-distribution shape, shifted far from the
    data mean with a fixed pattern so the backdoor is learnable."""
    shape = (n,) + tuple(base.train_x.shape[2:])
    x = rng.normal(0, 0.3, shape).astype(base.train_x.dtype)
    # fixed structured offset = the 'edge-case signature'
    sig = np.linspace(-1.5, 1.5, int(np.prod(shape[1:]))).reshape(shape[1:])
    x = x + sig.astype(x.dtype)
    # true labels deliberately exclude the attack target so targeted-accuracy
    # eval (robust.py backdoor metrics) measures real label flips
    y_true = rng.integers(0, max(base.class_num - 1, 1), n)
    y_true = np.where(y_true >= target_class, y_true + 1, y_true) \
        if base.class_num > 1 else y_true
    return x, y_true.astype(base.train_y.dtype)


# CIFAR-10 channel statistics the reference's transform pipeline applies to
# the raw uint8 southwest images (data_loader.py:330-339)
_CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
_CIFAR_STD = np.array([0.2023, 0.1994, 0.2010], np.float32)

# archive filenames per attack case (reference data_loader.py:344-361)
_SOUTHWEST_FILES = {
    "edge-case": ("southwest_images_new_train.pkl",
                  "southwest_images_new_test.pkl"),
    "normal-case": ("southwest_images_adv_p_percent_edge_case.pkl",
                    "southwest_images_p_percent_edge_case_test.pkl"),
    "almost-edge-case": ("southwest_images_adv_p_percent_edge_case.pkl",
                         "southwest_images_p_percent_edge_case_test.pkl"),
}


def _load_southwest_archives(data_dir: str, attack_case: str, base: FedDataset):
    """Parse the reference's REAL southwest archives — raw [N, 32, 32, 3]
    uint8 ndarray pickles under edge_case_examples/southwest_cifar10/
    (data_loader.py:344-376; labels are implicit — every image is relabeled
    to the attack target, true class 'airplane'). Returns
    (train_x, test_x) in the base dataset's dtype/normalization, or None
    when the files are absent (zero-egress fallback)."""
    names = _SOUTHWEST_FILES.get(attack_case)
    if names is None:
        return None
    sw_dir = os.path.join(data_dir, "edge_case_examples", "southwest_cifar10")
    paths = [os.path.join(sw_dir, n) for n in names]
    if not all(os.path.exists(p) for p in paths):
        return None
    out = []
    for p in paths:
        with open(p, "rb") as f:
            arr = np.asarray(pickle.load(f))
        if arr.ndim != 4 or arr.shape[1:] != tuple(base.train_x.shape[2:]):
            raise ValueError(
                f"southwest archive {p}: expected raw images "
                f"[N, {base.train_x.shape[2:]}], got {arr.shape}")
        if arr.dtype == np.uint8:  # reference transform: ToTensor + Normalize
            arr = (arr.astype(np.float32) / 255.0 - _CIFAR_MEAN) / _CIFAR_STD
        out.append(arr.astype(base.train_x.dtype))
    return out[0], out[1]


def load_poisoned_dataset(
    base: FedDataset,
    attack_case: str = "edge-case",
    target_class: int = 1,
    attacker_clients: list | None = None,
    poison_frac: float = 0.5,
    data_dir: str = "./data",
    seed: int = 0,
) -> PoisonedFederation:
    """Inject edge-case poison into `attacker_clients` (default: client 1,
    like the reference's rank-1 attacker, FedAvgRobustTrainer.py:14-25).

    With real archives the genuine edge images are used — the reference's
    southwest layout ({data_dir}/edge_case_examples/southwest_cifar10/
    southwest_images_new_{train,test}.pkl, raw uint8 image stacks) or the
    generic {attack_case}.pkl dict {"x", "y_true"} — otherwise the synthetic
    edge cluster. ``poison_frac`` of each attacker's real records are
    replaced.
    """
    rng = np.random.default_rng(seed)
    attacker_clients = attacker_clients if attacker_clients is not None else [1]
    path = os.path.join(data_dir, "edge_case_examples", f"{attack_case.replace('-', '_')}.pkl")
    n_pad = base.train_x.shape[1]
    # pool-sizing estimate for the synthetic fallback (upper bound); the
    # ACTUAL per-attacker poison count is poison_frac of that attacker's
    # REAL record count, computed in the injection loop below.
    # poison_frac=0 must mean a genuinely clean control federation
    n_poison_per = max(int(n_pad * poison_frac), 1) if poison_frac > 0 else 0

    southwest = _load_southwest_archives(data_dir, attack_case, base)
    edge_test_from_archive = None
    if southwest is not None:
        edge_x, edge_test_from_archive = southwest
        # southwest true class is 'airplane' (reference relabels 0 -> 9)
        edge_true = np.zeros(len(edge_x), base.train_y.dtype)
    elif os.path.exists(path):
        with open(path, "rb") as f:
            blob = pickle.load(f)
        edge_x = np.asarray(blob["x"], base.train_x.dtype)
        edge_true = np.asarray(blob.get("y_true", np.zeros(len(edge_x))), base.train_y.dtype)
    else:
        edge_x, edge_true = _synthesize_edge_cases(
            base, n_poison_per * (len(attacker_clients) + 4), target_class, rng
        )

    train_x = base.train_x.copy()
    train_y = base.train_y.copy()
    used = 0
    for c in attacker_clients:
        # poison REPLACES real records (slots within the client's true
        # count), preserving the mask/count invariant the local trainer
        # relies on — padded slots never train, so flipping their mask
        # would silently shrink the effective poison
        n_real = int(base.train_counts[c])
        n_poison = max(int(n_real * poison_frac), 1) if poison_frac > 0 else 0
        take = min(n_poison, len(edge_x) - used, n_real)
        slots = rng.choice(n_real, take, replace=False)
        train_x[c, slots] = edge_x[used : used + take]
        train_y[c, slots] = target_class
        used += take

    # backdoor test set: the archive's dedicated test images (reference
    # keeps southwest_*_test.pkl as the targeted task test set) or the
    # leftover edge cases
    if edge_test_from_archive is not None:
        edge_test_x = edge_test_from_archive
        # same true class as the train archive (airplane=0, set where the
        # southwest branch builds edge_true)
        edge_test_true = np.full(len(edge_test_x), edge_true[0] if len(edge_true)
                                 else 0, base.train_y.dtype)
    else:
        edge_test_x = edge_x[used:]
        edge_test_true = edge_true[used:]
    import dataclasses

    poisoned = dataclasses.replace(
        base, train_x=train_x, train_y=train_y,
        name=f"{base.name}+{attack_case}",
    )
    return PoisonedFederation(
        dataset=poisoned,
        attacker_clients=list(attacker_clients),
        target_class=target_class,
        edge_test_x=edge_test_x,
        edge_test_y=np.full(len(edge_test_x), target_class, base.train_y.dtype),
        edge_test_true_y=edge_test_true,
    )


def backdoor_success_rate(logits: np.ndarray, target_class: int) -> float:
    """Fraction of edge-case inputs classified as the attacker's target."""
    if len(logits) == 0:
        return 0.0
    return float((np.argmax(logits, axis=-1) == target_class).mean())
