"""Edge-case backdoor datasets (poisoned federations).

Counterpart of reference fedml_api/data_preprocessing/edge_case_examples/
data_loader.py:283 ``load_poisoned_dataset``: attacker clients' training
data is augmented with "edge-case" examples — real-looking inputs from a
rare tail distribution relabeled to the attacker's target class (southwest
airliners -> 'truck' in CIFAR-10, ARDIS digits -> '7' in EMNIST) — plus a
backdoor test set to measure targeted success.

Real poison archives are file-gated (zero egress); the fallback synthesizes
an off-manifold edge cluster: inputs drawn far from every class mean,
labeled with the target class. This preserves the measurement the
reference's datasets exist for — clean accuracy vs targeted backdoor
accuracy — with no downloaded data.
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass

import numpy as np

from fedml_tpu.data import FedDataset


@dataclass
class PoisonedFederation:
    dataset: FedDataset            # train data with attacker clients poisoned
    attacker_clients: list         # indices of poisoned clients
    target_class: int
    edge_test_x: np.ndarray        # backdoor eval inputs
    edge_test_y: np.ndarray        # all == target_class
    edge_test_true_y: np.ndarray   # what they SHOULD be classified as


def _synthesize_edge_cases(
    base: FedDataset, n: int, target_class: int, rng: np.random.Generator
) -> tuple[np.ndarray, np.ndarray]:
    """Off-manifold cluster: base-distribution shape, shifted far from the
    data mean with a fixed pattern so the backdoor is learnable."""
    shape = (n,) + tuple(base.train_x.shape[2:])
    x = rng.normal(0, 0.3, shape).astype(base.train_x.dtype)
    # fixed structured offset = the 'edge-case signature'
    sig = np.linspace(-1.5, 1.5, int(np.prod(shape[1:]))).reshape(shape[1:])
    x = x + sig.astype(x.dtype)
    # true labels deliberately exclude the attack target so targeted-accuracy
    # eval (robust.py backdoor metrics) measures real label flips
    y_true = rng.integers(0, max(base.class_num - 1, 1), n)
    y_true = np.where(y_true >= target_class, y_true + 1, y_true) \
        if base.class_num > 1 else y_true
    return x, y_true.astype(base.train_y.dtype)


def load_poisoned_dataset(
    base: FedDataset,
    attack_case: str = "edge-case",
    target_class: int = 1,
    attacker_clients: list | None = None,
    poison_frac: float = 0.5,
    data_dir: str = "./data",
    seed: int = 0,
) -> PoisonedFederation:
    """Inject edge-case poison into `attacker_clients` (default: client 1,
    like the reference's rank-1 attacker, FedAvgRobustTrainer.py:14-25).

    With real archives ({data_dir}/edge_case_examples/southwest.pkl, etc.)
    the genuine edge images are used; otherwise the synthetic edge cluster.
    ``poison_frac`` of each attacker's padded slots are replaced.
    """
    rng = np.random.default_rng(seed)
    attacker_clients = attacker_clients if attacker_clients is not None else [1]
    path = os.path.join(data_dir, "edge_case_examples", f"{attack_case.replace('-', '_')}.pkl")
    n_pad = base.train_x.shape[1]
    # poison_frac=0 must mean a genuinely clean control federation
    n_poison_per = max(int(n_pad * poison_frac), 1) if poison_frac > 0 else 0

    if os.path.exists(path):
        with open(path, "rb") as f:
            blob = pickle.load(f)
        edge_x = np.asarray(blob["x"], base.train_x.dtype)
        edge_true = np.asarray(blob.get("y_true", np.zeros(len(edge_x))), base.train_y.dtype)
    else:
        edge_x, edge_true = _synthesize_edge_cases(
            base, n_poison_per * (len(attacker_clients) + 4), target_class, rng
        )

    train_x = base.train_x.copy()
    train_y = base.train_y.copy()
    used = 0
    for c in attacker_clients:
        # poison REPLACES real records (slots within the client's true
        # count), preserving the mask/count invariant the local trainer
        # relies on — padded slots never train, so flipping their mask
        # would silently shrink the effective poison
        n_real = int(base.train_counts[c])
        take = min(n_poison_per, len(edge_x) - used, n_real)
        slots = rng.choice(n_real, take, replace=False)
        train_x[c, slots] = edge_x[used : used + take]
        train_y[c, slots] = target_class
        used += take

    # remaining edge cases form the backdoor test set
    edge_test_x = edge_x[used:]
    edge_test_true = edge_true[used:]
    import dataclasses

    poisoned = dataclasses.replace(
        base, train_x=train_x, train_y=train_y,
        name=f"{base.name}+{attack_case}",
    )
    return PoisonedFederation(
        dataset=poisoned,
        attacker_clients=list(attacker_clients),
        target_class=target_class,
        edge_test_x=edge_test_x,
        edge_test_y=np.full(len(edge_test_x), target_class, base.train_y.dtype),
        edge_test_true_y=edge_test_true,
    )


def backdoor_success_rate(logits: np.ndarray, target_class: int) -> float:
    """Fraction of edge-case inputs classified as the attacker's target."""
    if len(logits) == 0:
        return 0.0
    return float((np.argmax(logits, axis=-1) == target_class).mean())
