"""CIFAR-10/100 + CINIC-10 loaders with homo/hetero partition
(reference fedml_api/data_preprocessing/{cifar10,cifar100,cinic10}/
data_loader.py:101-269).

Real data path: torchvision-style pickled batches (cifar-10-batches-py /
cifar-100-python) under data_dir. Zero-egress fallback: class-blob synthetic
with the same 32x32x3 shapes and partition semantics. Images normalized with
the reference's per-channel mean/std; hetero partition uses the shared
Dirichlet machinery (fedml_tpu.core.partition).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool
from fedml_tpu.data.synthetic import make_synthetic_classification
from fedml_tpu.core.partition import partition as partition_fn

_CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
_CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
# reference cinic10/data_loader.py:118-119
_CINIC_MEAN = np.array([0.47889522, 0.47227842, 0.43047404], np.float32)
_CINIC_STD = np.array([0.24205776, 0.23828046, 0.25874835], np.float32)


def _load_cifar10_files(root: str):
    d = os.path.join(root, "cifar-10-batches-py")
    if not os.path.isdir(d):
        return None
    xs, ys = [], []
    for name in [f"data_batch_{i}" for i in range(1, 6)]:
        with open(os.path.join(d, name), "rb") as f:
            b = pickle.load(f, encoding="bytes")
        xs.append(b[b"data"]); ys.extend(b[b"labels"])
    with open(os.path.join(d, "test_batch"), "rb") as f:
        b = pickle.load(f, encoding="bytes")
    test_x, test_y = b[b"data"], np.asarray(b[b"labels"])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    tx = np.asarray(test_x).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return x, np.asarray(ys), tx, test_y


def _load_cifar100_files(root: str):
    d = os.path.join(root, "cifar-100-python")
    if not os.path.isdir(d):
        return None
    with open(os.path.join(d, "train"), "rb") as f:
        b = pickle.load(f, encoding="bytes")
    x = b[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y = np.asarray(b[b"fine_labels"])
    with open(os.path.join(d, "test"), "rb") as f:
        b = pickle.load(f, encoding="bytes")
    tx = b[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    ty = np.asarray(b[b"fine_labels"])
    return x, y, tx, ty


def _load_cinic10_files(root: str):
    """CINIC-10 ships as an ImageFolder tree (train/<class>/*.png,
    test/<class>/*.png — reference cinic10/data_loader.py:114-147). Class
    index = alphabetical class-dir order, matching torchvision ImageFolder."""
    train_dir, test_dir = os.path.join(root, "train"), os.path.join(root, "test")
    if not (os.path.isdir(train_dir) and os.path.isdir(test_dir)):
        return None
    from PIL import Image

    def class_dirs(d):
        return sorted(e for e in os.listdir(d)
                      if os.path.isdir(os.path.join(d, e)))

    def image_files(cdir):
        return [fn for fn in sorted(os.listdir(cdir))
                if fn.lower().endswith((".png", ".jpg", ".jpeg"))]

    # class index comes from the per-split alphabetical dir order; a split
    # missing a class dir — or a stray extracted artifact like __MACOSX
    # sorting in front and shifting every real class — must be an error,
    # not garbage labels
    classes = class_dirs(train_dir)
    if classes != class_dirs(test_dir):
        raise ValueError(
            f"CINIC-10 train/test class dirs differ under {root}: "
            f"{classes} vs {class_dirs(test_dir)}")
    if len(classes) != 10:
        raise ValueError(
            f"CINIC-10 tree under {root} has {len(classes)} class dirs "
            f"({classes}); expected exactly 10")

    # decoded-array cache: the real tree is ~180k PNGs; one sequential PIL
    # pass costs minutes, so persist the decoded arrays next to the tree.
    # Fingerprint = per-class image counts of both splits, so completing or
    # fixing a partial download invalidates the cache instead of being
    # silently ignored.
    fingerprint = np.asarray(
        [len(image_files(os.path.join(d, c)))
         for d in (train_dir, test_dir) for c in classes], np.int64)
    cache = os.path.join(root, "cinic10_decoded.npz")
    if os.path.isfile(cache):
        try:
            z = np.load(cache)
            if np.array_equal(z["fingerprint"], fingerprint):
                return z["x"], z["y"], z["tx"], z["ty"]
        except Exception:  # truncated/stale cache: fall through and rebuild
            pass

    def load_split(d):
        xs, ys = [], []
        for ci, cls in enumerate(classes):
            cdir = os.path.join(d, cls)
            for fn in image_files(cdir):
                with Image.open(os.path.join(cdir, fn)) as im:
                    xs.append(np.asarray(im.convert("RGB"), np.uint8))
                ys.append(ci)
        if not xs:
            raise ValueError(f"CINIC-10 split {d} contains no images")
        return np.stack(xs), np.asarray(ys)

    x, y = load_split(train_dir)
    tx, ty = load_split(test_dir)
    try:
        # atomic publish: a kill mid-write must not leave a truncated npz
        # that bricks every later load
        np.savez_compressed(cache + ".tmp.npz", x=x, y=y, tx=tx, ty=ty,
                            fingerprint=fingerprint)
        os.replace(cache + ".tmp.npz", cache)
    except OSError:  # read-only data dir / disk full: just skip the cache
        for p in (cache + ".tmp.npz",):
            if os.path.exists(p):
                try:
                    os.unlink(p)
                except OSError:
                    pass
    return x, y, tx, ty


def _normalize(u8: np.ndarray, mean=_CIFAR_MEAN, std=_CIFAR_STD) -> np.ndarray:
    return ((u8.astype(np.float32) / 255.0) - mean) / std


def _build(
    name: str, loaded, classes: int, client_num_in_total: int,
    partition_method: str, partition_alpha: float, batch_size: int, seed: int,
    data_dir: str = "./data", mean=_CIFAR_MEAN, std=_CIFAR_STD,
) -> FedDataset:
    if loaded is None:
        return make_synthetic_classification(
            f"{name}(synthetic)", (32, 32, 3), classes, client_num_in_total,
            records_per_client=160, partition_method=partition_method,
            partition_alpha=partition_alpha, batch_size=batch_size, seed=seed,
            data_dir=data_dir,
        )
    x, y, test_x, test_y = loaded
    x, test_x = _normalize(x, mean, std), _normalize(test_x, mean, std)
    idx_map = partition_fn(
        partition_method, y, client_num_in_total, classes, partition_alpha,
        seed=seed,
        # hetero-fix: the precomputed-map file lives next to the data
        # (reference ships distribution/net_dataidx_map files,
        # cifar10/data_loader.py:150-158)
        # alpha is a semantic parameter of the split — a map for one alpha
        # must never be silently reused for another
        map_path=os.path.join(
            data_dir,
            f"{name}_partition_{client_num_in_total}_a{partition_alpha}.npz"),
    )
    xs = [x[idx_map[i]] for i in range(client_num_in_total)]
    ys = [y[idx_map[i]].astype(np.int32) for i in range(client_num_in_total)]
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(test_x, test_y.astype(np.int32), 256)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=classes, name=name,
    )


@register_dataset("cifar10")
def load_cifar10(
    data_dir: str = "./data/cifar10", client_num_in_total: int = 10,
    partition_method: str = "hetero", partition_alpha: float = 0.5,
    batch_size: int = 64, seed: int = 0, **_,
) -> FedDataset:
    return _build("cifar10", _load_cifar10_files(data_dir), 10, client_num_in_total,
                  partition_method, partition_alpha, batch_size, seed, data_dir)


@register_dataset("cifar100")
def load_cifar100(
    data_dir: str = "./data/cifar100", client_num_in_total: int = 10,
    partition_method: str = "hetero", partition_alpha: float = 0.5,
    batch_size: int = 64, seed: int = 0, **_,
) -> FedDataset:
    return _build("cifar100", _load_cifar100_files(data_dir), 100, client_num_in_total,
                  partition_method, partition_alpha, batch_size, seed, data_dir)


@register_dataset("cinic10")
def load_cinic10(
    data_dir: str = "./data/cinic10", client_num_in_total: int = 10,
    partition_method: str = "hetero", partition_alpha: float = 0.5,
    batch_size: int = 64, seed: int = 0, **_,
) -> FedDataset:
    # CINIC-10 ships as an ImageFolder tree; without it we use the synthetic
    # stand-in (same 10 classes / 32x32x3). Real files use CINIC's own
    # per-channel statistics (reference data_loader.py:118-119).
    return _build("cinic10", _load_cinic10_files(data_dir), 10,
                  client_num_in_total, partition_method, partition_alpha,
                  batch_size, seed, data_dir,
                  mean=_CINIC_MEAN, std=_CINIC_STD)
