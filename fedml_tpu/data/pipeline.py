"""Host-side streaming input pipeline.

The in-memory path (data/batching.py) stacks the whole federation into
device arrays — right when the dataset fits in HBM. For datasets that do
not (ImageNet/Landmarks scale), this module streams: the native threaded
batcher (fedml_tpu/native.HostPipeline, C++ workers assembling shuffled
batches off-GIL) feeds a double-buffered host→device prefetcher, so batch
assembly and PCIe/ICI transfer overlap device compute — the TPU-native
counterpart of the reference's DataLoader worker processes
(cifar10/data_loader.py DataLoader(..., shuffle=True)).
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import numpy as np

from fedml_tpu.native import HostPipeline

__all__ = ["HostPipeline", "device_stream"]


def device_stream(
    pipeline: HostPipeline,
    n_batches: Optional[int] = None,
    prefetch: int = 2,
    device=None,
) -> Iterator[tuple]:
    """Yield (x, y) already resident on ``device``, keeping ``prefetch``
    transfers in flight ahead of the consumer. ``n_batches=None`` streams
    one epoch."""
    if n_batches is None:
        n_batches = pipeline.batches_per_epoch
    if device is None:
        device = jax.devices()[0]
    buf = []
    for _ in range(n_batches):
        bx, by = pipeline.next_batch()
        item = (jax.device_put(bx, device),
                None if by is None else jax.device_put(by, device))
        buf.append(item)
        if len(buf) > prefetch:
            yield buf.pop(0)
    while buf:
        yield buf.pop(0)
