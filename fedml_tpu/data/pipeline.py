"""Host-side streaming input pipeline.

The in-memory path (data/batching.py) stacks the whole federation into
device arrays — right when the dataset fits in HBM. For datasets that do
not (ImageNet/Landmarks scale), this module streams: the native threaded
batcher (fedml_tpu/native.HostPipeline, C++ workers assembling shuffled
batches off-GIL) feeds a double-buffered host→device prefetcher, so batch
assembly and PCIe/ICI transfer overlap device compute — the TPU-native
counterpart of the reference's DataLoader worker processes
(cifar10/data_loader.py DataLoader(..., shuffle=True)).

This module also owns the ROUND-granular pipeline: cross-device rounds
materialize their sampled cohort host-side every round (the stacked client
array is virtual at 342k clients, data/crossdevice.py), and the per-round
plan is a pure function of (seed, round_idx) — so future rounds' cohorts
are known before the current round finishes. :class:`CohortPrefetcher`
keeps a bounded depth of rounds in flight on background threads:
materialize (fanned out over the cohort's clients), host bf16 cast, and
host→device transfer all overlap the in-flight round's device compute,
while the consumer pops bit-identical inputs in round order.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Iterator, Optional

import jax
import numpy as np

from fedml_tpu.native import HostPipeline

__all__ = ["HostPipeline", "device_stream", "CohortPrefetcher",
           "materialize_cohort"]


def materialize_cohort(dataset, sampled: np.ndarray,
                       pool: Optional[ThreadPoolExecutor] = None,
                       n_chunks: int = 0):
    """``dataset.client_slice(sampled)``, optionally fanned out over client
    chunks on ``pool``. Bit-identical to the serial call by the dataset
    contract: each client's records derive from its own (seed, client_id)
    stream, independent of every other client (data/crossdevice.py
    ``_client_rng``), so chunk boundaries cannot change any record. Returns
    (x, y, mask, counts) exactly like ``client_slice``."""
    sampled = np.asarray(sampled)
    if pool is None or n_chunks <= 1 or len(sampled) < 2:
        return dataset.client_slice(sampled)
    chunks = np.array_split(sampled, min(n_chunks, len(sampled)))
    parts = list(pool.map(dataset.client_slice, chunks))
    return tuple(np.concatenate([p[i] for p in parts]) for i in range(4))


class CohortPrefetcher:
    """Bounded-depth background pipeline over per-round cohort payloads.

    ``build(round_idx, pool) -> (payload, stages)`` runs on a background
    thread and produces everything the round step needs (materialized —
    usually also cast and device-resident — cohort arrays) plus a stage-
    timing dict ({"materialize_ms", "h2d_ms"}, utils/metrics.round_stats).
    ``pool`` is a shared worker pool for fanning materialization out over
    the cohort's clients (see :func:`materialize_cohort`).

    ``pop(round_idx)`` returns ``(payload, stages, wait_ms)`` for exactly
    that round, scheduling builds for the next ``depth`` rounds before it
    blocks — so the steady state keeps ``depth`` rounds in flight while the
    device computes. Rounds may be popped in any order (checkpoint restore
    jumps backward, the bench re-runs the same rounds): a round that was
    never scheduled is built on demand, and speculative rounds outside the
    new (round, round + depth] window are discarded. A build exception is
    held in its round's future and re-raised by the ``pop`` that consumes
    it — the consumer's next ``run_round`` fails loudly instead of hanging.

    ``close()`` drains cleanly: in-flight builds finish (their payloads are
    dropped), worker threads exit. The prefetcher holds NO round state —
    everything it produces is a pure function of round_idx — so teardown or
    checkpoint at any point cannot change what a later pop returns."""

    def __init__(self, build: Callable, depth: int, workers: int = 0,
                 max_round: Optional[int] = None,
                 name: str = "cohort-prefetch"):
        import os

        self.depth = max(int(depth), 1)
        # auto: leave one core for the consumer (dispatch + host maths);
        # never exceed cores-1 — on a 2-core host that means ONE worker,
        # over-threading there only adds churn against device dispatch
        self.workers = int(workers) if workers > 0 else min(
            8, max(1, (os.cpu_count() or 2) - 1))
        #: SPECULATION bound (exclusive): rounds >= max_round are never
        #: built ahead — the federation's schedule ends, so building past
        #: it is pure waste. A driver that explicitly pops beyond the bound
        #: (the bench re-runs rounds [1, comm_round]) RAISES it: observed
        #: demand beats the static schedule.
        self.max_round = max_round
        self._build = build
        # depth+1 workers: discarded speculative builds cannot be cancelled
        # once running, so after a window jump (checkpoint restore) the
        # on-demand build needs a free worker to start immediately instead
        # of queueing behind up-to-depth rounds of dead work
        self._rounds = ThreadPoolExecutor(
            max_workers=self.depth + 1, thread_name_prefix=f"{name}-round")
        self._mat = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix=f"{name}-mat")
        self._inflight: dict[int, Future] = {}
        self._past_schedule = False   # was the PREVIOUS pop at/past the bound?
        self._closed = False

    def _ensure(self, round_idx: int) -> Future:
        fut = self._inflight.get(round_idx)
        if fut is None:
            fut = self._inflight[round_idx] = self._rounds.submit(
                self._build, round_idx, self._mat)
        return fut

    def prime(self, round_idx: int, wait: bool = False) -> None:
        """Schedule builds for rounds [round_idx, round_idx + depth) without
        popping — brings a measured window straight to the steady state a
        long run reaches naturally (every round prefetched during its
        predecessor), instead of paying a cold first build on the clock.
        ``wait=True`` blocks until the primed builds finish (build errors
        stay in their futures and re-raise at the consuming pop)."""
        if self._closed:
            raise RuntimeError("CohortPrefetcher is closed")
        for i in range(round_idx, round_idx + self.depth):
            if self.max_round is None or i < self.max_round:
                self._ensure(i)
        if wait:
            for fut in list(self._inflight.values()):
                fut.exception()     # block for completion, raise nothing

    def pop(self, round_idx: int):
        if self._closed:
            raise RuntimeError("CohortPrefetcher is closed")
        if self.max_round is not None and round_idx >= self.max_round:
            # ONE pop at the bound is a window artifact (the bench pops
            # [1, comm_round] against train()'s [0, comm_round)) — admit
            # just that round. A SECOND consecutive past-schedule pop
            # means the driver ignores the static schedule entirely: drop
            # the bound so pipelining continues (cost: up to depth wasted
            # builds at the true end) instead of silently going serial.
            self.max_round = None if self._past_schedule else round_idx + 1
            self._past_schedule = True
        else:
            self._past_schedule = False
        fut = self._inflight.pop(round_idx, None) or self._rounds.submit(
            self._build, round_idx, self._mat)
        # top up the window BEFORE blocking, so the background stages of
        # rounds r+1..r+depth overlap this round's device compute
        for i in range(round_idx + 1, round_idx + 1 + self.depth):
            if self.max_round is None or i < self.max_round:
                self._ensure(i)
        # discard speculative rounds outside the window (a pop order jump:
        # restore-from-checkpoint, or the bench re-running rounds 1..N)
        for r in [r for r in self._inflight
                  if not round_idx < r <= round_idx + self.depth]:
            self._inflight.pop(r).cancel()
        t0 = time.perf_counter()
        payload, stages = fut.result()
        wait_ms = (time.perf_counter() - t0) * 1e3
        return payload, stages, wait_ms

    def close(self) -> None:
        """Drain and shut down; idempotent."""
        if self._closed:
            return
        self._closed = True
        for fut in self._inflight.values():
            fut.cancel()
        self._inflight.clear()
        self._rounds.shutdown(wait=True)
        self._mat.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        self.close()


def device_stream(
    pipeline: HostPipeline,
    n_batches: Optional[int] = None,
    prefetch: int = 2,
    device=None,
) -> Iterator[tuple]:
    """Yield (x, y) already resident on ``device``, keeping ``prefetch``
    transfers in flight ahead of the consumer. ``n_batches=None`` streams
    one epoch."""
    if n_batches is None:
        n_batches = pipeline.batches_per_epoch
    if device is None:
        device = jax.devices()[0]
    buf = []
    for _ in range(n_batches):
        bx, by = pipeline.next_batch()
        item = (jax.device_put(bx, device),
                None if by is None else jax.device_put(by, device))
        buf.append(item)
        if len(buf) > prefetch:
            yield buf.pop(0)
    while buf:
        yield buf.pop(0)
