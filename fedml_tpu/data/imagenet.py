"""ImageNet (ILSVRC2012) + Google Landmarks federated loaders.

Counterparts of reference fedml_api/data_preprocessing/ImageNet/data_loader.py
(folder-per-class layout, equal client split) and Landmarks/data_loader.py
(csv mapping rows (user_id, image_id, class) onto an image folder — natural
233/1,262-client federation for gld23k/gld160k).

Real images are absent in this zero-egress environment; the loaders are
file-gated and otherwise fall back to a learnable synthetic stand-in of the
same shape contract ([H, W, 3] float32, int labels), so every code path
downstream of the loader is identical either way.
"""

from __future__ import annotations

import csv
import os

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool
from fedml_tpu.data.synthetic import make_synthetic_classification


def _read_image(path: str, size: int) -> np.ndarray:
    from PIL import Image

    im = Image.open(path).convert("RGB").resize((size, size))
    return np.asarray(im, np.float32) / 255.0


@register_dataset("ILSVRC2012", "imagenet")
def load_imagenet(
    data_dir: str = "./data", num_clients: int = 10, batch_size: int = 32,
    image_size: int = 64, max_per_class: int = 50, seed: int = 0, **_,
) -> FedDataset:
    """Folder layout {data_dir}/ILSVRC2012/train/<wnid>/*.JPEG; clients get
    an equal random split (reference ImageNet/data_loader.py uses an equal
    partition over the sample index space)."""
    root = os.path.join(data_dir, "ILSVRC2012", "train")
    if not os.path.isdir(root):
        return make_synthetic_classification(
            "imagenet", (image_size, image_size, 3), 100, num_clients,
            records_per_client=32, partition_method="homo",
            batch_size=batch_size, seed=seed,
        )
    classes = sorted(os.listdir(root))
    xs_all, ys_all = [], []
    for ci, wnid in enumerate(classes):
        files = sorted(os.listdir(os.path.join(root, wnid)))[:max_per_class]
        for f in files:
            xs_all.append(_read_image(os.path.join(root, wnid, f), image_size))
            ys_all.append(ci)
    x = np.stack(xs_all)
    y = np.asarray(ys_all, np.int32)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(x))
    n_test = max(len(x) // 10, 1)
    te, tr = order[:n_test], order[n_test:]
    splits = np.array_split(tr, num_clients)
    tx, ty, tm, tc = pad_and_stack_clients(
        [x[s] for s in splits], [y[s] for s in splits], batch_size
    )
    ex, ey, em = pad_eval_pool(x[te], y[te], 64)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em,
        class_num=len(classes), name="ILSVRC2012",
    )


def load_landmarks(
    data_dir: str = "./data", num_clients: int = 16, batch_size: int = 16,
    image_size: int = 64, seed: int = 0, variant: str = "gld23k", **_,
) -> FedDataset:
    """CSV schema user_id,image_id,class (reference Landmarks/data_loader.py):
    the user_id column IS the federation — clients are given, not
    partitioned."""
    csv_path = os.path.join(data_dir, "landmarks", f"{variant}_train.csv")
    img_root = os.path.join(data_dir, "landmarks", "images")
    if not (os.path.exists(csv_path) and os.path.isdir(img_root)):
        return make_synthetic_classification(
            variant, (image_size, image_size, 3), 40, num_clients,
            records_per_client=24, partition_method="hetero",
            batch_size=batch_size, seed=seed,
        )
    by_user: dict[str, list] = {}
    classes: set = set()
    with open(csv_path) as f:
        for row in csv.DictReader(f):
            by_user.setdefault(row["user_id"], []).append(
                (row["image_id"], int(row["class"]))
            )
            classes.add(int(row["class"]))
    users = sorted(by_user)[:num_clients]
    xs, ys, test_x, test_y = [], [], [], []
    for u in users:
        recs = by_user[u]
        imgs = np.stack([
            _read_image(os.path.join(img_root, f"{iid}.jpg"), image_size)
            for iid, _ in recs
        ])
        labels = np.asarray([c for _, c in recs], np.int32)
        n_hold = max(len(recs) // 10, 1)
        xs.append(imgs[n_hold:]); ys.append(labels[n_hold:])
        test_x.append(imgs[:n_hold]); test_y.append(labels[:n_hold])
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(np.concatenate(test_x), np.concatenate(test_y), 64)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em,
        class_num=max(classes) + 1, name=variant,
    )


# registry dispatch doesn't forward the requested name, so each variant
# gets its own registered wrapper pinning `variant`
@register_dataset("gld23k")
def _gld23k(**kw) -> FedDataset:
    kw.pop("variant", None)
    return load_landmarks(variant="gld23k", **kw)


@register_dataset("gld160k")
def _gld160k(**kw) -> FedDataset:
    kw.pop("variant", None)
    return load_landmarks(variant="gld160k", **kw)
