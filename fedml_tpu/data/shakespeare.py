"""Shakespeare next-char datasets.

- ``shakespeare``: LEAF json, role-per-client, char sequences of length 80
  (reference fedml_api/data_preprocessing/shakespeare/data_loader.py:11-118 +
  language_utils.py: 80-symbol printable vocab, word->indices).
- ``fed_shakespeare``: TFF h5 ``snippets`` per client
  (reference fed_shakespeare/data_loader.py:27-150, vocab = 86 chars + pad/
  bos/eos/oov, seq len 80).

Records are (x[T], y[T]) with y the one-step-shifted sequence; pairs with the
``nwp`` task. Synthetic fallback generates structured token streams.
"""

from __future__ import annotations

import json
import os
from glob import glob

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool
from fedml_tpu.data.synthetic import make_synthetic_classification

SEQ_LEN = 80
# LEAF printable character vocabulary (80 symbols + pad), language_utils.py.
ALL_LETTERS = "\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ[]abcdefghijklmnopqrstuvwxyz}"
VOCAB_SIZE = len(ALL_LETTERS) + 1  # +1 pad/oov -> 81; reference rnn uses 90
_CHAR2IDX = {c: i + 1 for i, c in enumerate(ALL_LETTERS)}


def text_to_ids(s: str) -> np.ndarray:
    return np.asarray([_CHAR2IDX.get(c, 0) for c in s], np.int32)


def _sequences_from_text(ids: np.ndarray, seq_len: int) -> tuple[np.ndarray, np.ndarray]:
    """Chop a char-id stream into (x, y) next-char pairs of fixed length."""
    n = (len(ids) - 1) // seq_len
    if n <= 0:
        return np.zeros((0, seq_len), np.int32), np.zeros((0, seq_len), np.int32)
    x = ids[: n * seq_len].reshape(n, seq_len)
    y = ids[1 : n * seq_len + 1].reshape(n, seq_len)
    return x, y


def _synthetic_nwp(name: str, num_clients: int, vocab: int, seq_len: int, batch_size: int, seed: int) -> FedDataset:
    """Markov-ish token streams so an LSTM can genuinely reduce perplexity."""
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for c in range(num_clients):
        stride = rng.integers(1, 7)
        start = rng.integers(0, vocab)
        n_seq = int(rng.integers(6, 14))
        stream = (start + stride * np.arange(n_seq * seq_len + 1) + rng.integers(0, 2, n_seq * seq_len + 1)) % vocab
        x, y = _sequences_from_text(stream.astype(np.int32), seq_len)
        xs.append(x); ys.append(y)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(np.concatenate(xs), np.concatenate(ys), 64)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=vocab, task="nwp", name=name,
    )


@register_dataset("shakespeare")
def load_shakespeare(
    data_dir: str = "./data/shakespeare",
    client_num_in_total: int = 715,
    batch_size: int = 4,
    seed: int = 0,
    **_,
) -> FedDataset:
    train_dir = os.path.join(data_dir, "train")
    if not glob(os.path.join(train_dir, "*.json")):
        return _synthetic_nwp("shakespeare(synthetic)", min(client_num_in_total, 100),
                              VOCAB_SIZE, SEQ_LEN, batch_size, seed)
    xs, ys, exs, eys = [], [], [], []
    for split, accx, accy in ((os.path.join(data_dir, "train"), xs, ys),
                              (os.path.join(data_dir, "test"), exs, eys)):
        for path in sorted(glob(os.path.join(split, "*.json"))):
            with open(path) as f:
                blob = json.load(f)
            for u in blob["users"][: client_num_in_total]:
                ud = blob["user_data"][u]
                sx = np.stack([text_to_ids(s.ljust(SEQ_LEN)[:SEQ_LEN]) for s in ud["x"]])
                sy_last = [text_to_ids(t)[0] for t in ud["y"]]
                # LEAF stores y as the single next char; reconstruct full-shift
                sy = np.concatenate([sx[:, 1:], np.asarray(sy_last, np.int32)[:, None]], axis=1)
                accx.append(sx); accy.append(sy)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(np.concatenate(exs), np.concatenate(eys), 64)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=VOCAB_SIZE, task="nwp",
        name="shakespeare",
    )


@register_dataset("fed_shakespeare")
def load_fed_shakespeare(
    data_dir: str = "./data/fed_shakespeare/datasets",
    client_num_in_total: int = 715,
    batch_size: int = 4,
    seed: int = 0,
    **_,
) -> FedDataset:
    train_h5 = os.path.join(data_dir, "shakespeare_train.h5")
    test_h5 = os.path.join(data_dir, "shakespeare_test.h5")
    vocab = 90  # 86 chars + pad + bos + eos + oov (TFF convention)
    if not (os.path.exists(train_h5) and os.path.exists(test_h5)):
        return _synthetic_nwp("fed_shakespeare(synthetic)", min(client_num_in_total, 100),
                              vocab, SEQ_LEN, batch_size, seed)
    import h5py

    def read(path, limit):
        xs, ys = [], []
        with h5py.File(path, "r") as f:
            ex = f["examples"]
            for cid in list(ex.keys())[:limit]:
                snippets = [s.decode("utf-8") for s in np.asarray(ex[cid]["snippets"])]
                ids = text_to_ids("".join(snippets))
                x, y = _sequences_from_text(ids, SEQ_LEN)
                if len(x):
                    xs.append(x); ys.append(y)
        return xs, ys

    xs, ys = read(train_h5, client_num_in_total)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    exs, eys = read(test_h5, client_num_in_total)
    ex, ey, em = pad_eval_pool(np.concatenate(exs), np.concatenate(eys), 64)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=vocab, task="nwp",
        name="fed_shakespeare",
    )
