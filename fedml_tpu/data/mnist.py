"""MNIST loader — LEAF json format, natural per-user partition
(reference fedml_api/data_preprocessing/MNIST/data_loader.py:8-120).

LEAF layout: ``{data_dir}/train/*.json`` and ``{data_dir}/test/*.json``, each
json holding {"users": [...], "user_data": {user: {"x": [[784]...], "y": [...]}}}.
Falls back to a synthetic stand-in with identical shapes when absent.
"""

from __future__ import annotations

import json
import os
from glob import glob

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool
from fedml_tpu.data.synthetic import make_synthetic_classification


def _read_leaf_dir(d: str) -> dict[str, dict]:
    users: dict[str, dict] = {}
    for path in sorted(glob(os.path.join(d, "*.json"))):
        with open(path) as f:
            blob = json.load(f)
        for u in blob["users"]:
            users[u] = blob["user_data"][u]
    return users


@register_dataset("mnist")
def load_mnist(
    data_dir: str = "./data/MNIST",
    client_num_in_total: int = 1000,
    batch_size: int = 10,
    seed: int = 0,
    **_,
) -> FedDataset:
    train_dir, test_dir = os.path.join(data_dir, "train"), os.path.join(data_dir, "test")
    if not (glob(os.path.join(train_dir, "*.json")) and glob(os.path.join(test_dir, "*.json"))):
        return make_synthetic_classification(
            "mnist(synthetic)", (784,), 10, client_num_in_total,
            records_per_client=30, batch_size=batch_size, seed=seed,
        )
    train_users = _read_leaf_dir(train_dir)
    test_users = _read_leaf_dir(test_dir)
    names = sorted(train_users)[:client_num_in_total]
    xs = [np.asarray(train_users[u]["x"], np.float32) for u in names]
    ys = [np.asarray(train_users[u]["y"], np.int32) for u in names]
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex = np.concatenate([np.asarray(test_users[u]["x"], np.float32) for u in names if u in test_users])
    ey = np.concatenate([np.asarray(test_users[u]["y"], np.int32) for u in names if u in test_users])
    ex, ey, em = pad_eval_pool(ex, ey, 256)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em, class_num=10, name="mnist",
    )
