"""Federated segmentation datasets (FedSeg).

The reference's FedSeg path consumes Pascal-VOC-augmented / COCO loaders
(fedml_api/data_preprocessing/{pascal_voc_augmented,coco}/ in upstream; this
fork ships the FedSeg trainers in fedml_api/distributed/fedseg/). Real files
are absent in this zero-egress environment, so the registered loaders fall
back to a synthetic blob-segmentation task with the same contract: images
[*, H, W, 3], integer masks [*, H, W] with 255 = ignore.
"""

from __future__ import annotations

import os

import numpy as np

from fedml_tpu.data import FedDataset, register_dataset
from fedml_tpu.data.batching import pad_and_stack_clients, pad_eval_pool


def make_synthetic_segmentation(
    num_clients: int = 4,
    records_per_client: int = 8,
    image_size: int = 32,
    num_classes: int = 4,
    batch_size: int = 4,
    seed: int = 0,
    ignore_frac: float = 0.02,
) -> FedDataset:
    """Blob task: class-0 background + colored rectangles whose fill color
    correlates with their class, so a conv net can actually learn it."""
    rng = np.random.default_rng(seed)
    H = image_size

    def sample(n):
        xs = np.zeros((n, H, H, 3), np.float32)
        ys = np.zeros((n, H, H), np.int32)
        for i in range(n):
            xs[i] = rng.normal(0, 0.05, (H, H, 3))
            for _ in range(rng.integers(1, 4)):
                c = int(rng.integers(1, num_classes))
                h0, w0 = rng.integers(0, H // 2, 2)
                h1 = h0 + int(rng.integers(4, H // 2))
                w1 = w0 + int(rng.integers(4, H // 2))
                color = np.array([c / num_classes, 1 - c / num_classes, 0.5])
                xs[i, h0:h1, w0:w1] = color + rng.normal(0, 0.05, 3)
                ys[i, h0:h1, w0:w1] = c
            # sprinkle ignore pixels (reference VOC border class 255)
            ign = rng.random((H, H)) < ignore_frac
            ys[i][ign] = 255
        return xs, ys

    xs, ys = [], []
    for _ in range(num_clients):
        x, y = sample(records_per_client)
        xs.append(x)
        ys.append(y)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex_raw, ey_raw = sample(max(2 * records_per_client, 16))
    ex, ey, em = pad_eval_pool(ex_raw, ey_raw, 16)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em,
        class_num=num_classes, task="segmentation", name="synthetic_seg",
    )


@register_dataset("pascal_voc", "coco_seg")
def _load_seg(
    data_dir: str = "./data", num_clients: int = 4, batch_size: int = 4,
    image_size: int = 32, seed: int = 0, **_,
) -> FedDataset:
    """Gated real loader: expects preprocessed npz shards
    ``{data_dir}/pascal_voc/client_*.npz`` with arrays x [n,H,W,3] float and
    y [n,H,W] uint8 (255=ignore); synthetic fallback otherwise."""
    root = os.path.join(data_dir, "pascal_voc")
    shards = sorted(
        os.path.join(root, f) for f in (os.listdir(root) if os.path.isdir(root) else [])
        if f.startswith("client_") and f.endswith(".npz")
    )
    if not shards:
        return make_synthetic_segmentation(
            num_clients=num_clients, batch_size=batch_size,
            image_size=image_size, seed=seed,
        )
    xs, ys = [], []
    # class count spans ALL shards + the test set, not just the loaded
    # subset — a class missing from the first num_clients shards must still
    # exist in the label space or metrics/loss silently drop it
    classes = 0
    for s in shards:
        y = np.load(s)["y"].astype(np.int32)
        if np.any(y != 255):
            classes = max(classes, int(y[y != 255].max()) + 1)
    for s in shards[:num_clients]:
        blob = np.load(s)
        xs.append(blob["x"].astype(np.float32))
        ys.append(blob["y"].astype(np.int32))
    test = np.load(os.path.join(root, "test.npz"))
    test_y = test["y"].astype(np.int32)
    if np.any(test_y != 255):
        classes = max(classes, int(test_y[test_y != 255].max()) + 1)
    tx, ty, tm, tc = pad_and_stack_clients(xs, ys, batch_size)
    ex, ey, em = pad_eval_pool(test["x"].astype(np.float32), test_y, 16)
    return FedDataset(
        train_x=tx, train_y=ty, train_mask=tm, train_counts=tc,
        test_x=ex, test_y=ey, test_mask=em,
        class_num=classes, task="segmentation", name="pascal_voc",
    )
