"""Pytree <-> wire serialization for the edge transport.

The reference ships whole ``state_dict``s as pickled dicts over MPI
(mpi_send_thread.py:27) or as nested Python lists inside JSON for mobile
clients (fedavg/utils.py:7-16 ``transform_tensor_to_list``). Both are slow
and type-lossy. Here a pytree is serialized as:

    header(JSON: treedef repr, shapes, dtypes) + concatenated raw buffers

which round-trips exactly, costs one memcpy per leaf, and is the payload
format for the gRPC edge backend (fedml_tpu/comm/grpc_backend.py). A JSON
nested-list codec is kept for is_mobile parity.
"""

from __future__ import annotations

import json
import struct
from typing import Any

import jax
import numpy as np

Pytree = Any

_MAGIC = b"FTPU1"


def frame_pack(magic: bytes, header: Any, *payloads: bytes) -> bytes:
    """The one binary framing used everywhere a JSON header fronts raw
    buffers (pytree wire format here, comm/message.py envelopes,
    utils/checkpoint.py files): MAGIC | u64 header_len | JSON | payloads."""
    hbytes = json.dumps(header).encode("utf-8")
    return b"".join([magic, struct.pack("<Q", len(hbytes)), hbytes, *payloads])


def frame_unpack(magic: bytes, buf: bytes) -> tuple[Any, int]:
    """Returns (header, payload_offset); raises on a foreign or torn buffer."""
    if buf[: len(magic)] != magic:
        raise ValueError(f"bad magic: expected {magic!r}")
    off = len(magic)
    if len(buf) < off + 8:
        raise ValueError("truncated frame: missing header length")
    (hlen,) = struct.unpack("<Q", buf[off : off + 8])
    off += 8
    if len(buf) < off + hlen:
        raise ValueError("truncated frame: incomplete header")
    header = json.loads(buf[off : off + hlen].decode("utf-8"))
    return header, off + hlen


def tree_to_bytes(tree: Pytree) -> bytes:
    """Serialize an arbitrary pytree of arrays to a self-describing buffer.

    Payload assembly and the crc32c integrity trailer run on the native
    runtime (fedml_tpu/native: threaded gather memcpy + slice-by-8 crc32c)
    when it is available; the format is identical either way. The crc covers
    the concatenated payload bytes and is carried in the JSON header, so
    pre-crc readers still parse new frames and vice versa.
    """
    from fedml_tpu import native

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = ["/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path) for path, _ in leaves_with_path]
    leaves = [np.ascontiguousarray(np.asarray(leaf)) for _, leaf in leaves_with_path]
    payload = bytes(native.pack_buffers(leaves))
    header = {
        "treedef": _treedef_to_json(treedef),
        "paths": paths,
        "shapes": [list(x.shape) for x in leaves],
        "dtypes": [x.dtype.str for x in leaves],
        "crc32c": native.crc32c(payload),
    }
    return frame_pack(_MAGIC, header, payload)


def tree_from_bytes(buf: bytes) -> Pytree:
    from fedml_tpu import native

    header, off = frame_unpack(_MAGIC, buf)
    if "crc32c" in header:
        got = native.crc32c(np.frombuffer(buf, np.uint8, offset=off))
        if got != header["crc32c"]:
            raise ValueError(
                f"wire frame payload corrupt: crc32c {got:#010x} != "
                f"{header['crc32c']:#010x}"
            )
    specs = [(tuple(s), d) for s, d in zip(header["shapes"], header["dtypes"])]
    leaves = native.unpack_buffers(buf, specs, offset=off)
    treedef = _treedef_from_json(header["treedef"])
    return jax.tree.unflatten(treedef, leaves)


def _treedef_to_json(treedef) -> Any:
    """Represent a treedef as the structure with leaf placeholders.

    Only dict/list/tuple/None containers survive (which covers flax param
    dicts and optax states built from them); exotic custom nodes should be
    converted to plain containers before shipping over the wire.
    """
    example = jax.tree.unflatten(treedef, list(range(treedef.num_leaves)))
    return _pyify(example)


def _pyify(x):
    if isinstance(x, dict):
        for k in x:
            if not isinstance(k, str):
                # JSON would stringify the key and jax's key-sorted flatten
                # order would then silently reassign leaves — refuse instead.
                raise TypeError(
                    f"wire pytrees require string dict keys, got {type(k).__name__} {k!r}"
                )
        return {"__d__": {k: _pyify(v) for k, v in x.items()}}
    if isinstance(x, tuple) and hasattr(x, "_fields"):
        # NamedTuples (optax optimizer states) keep their class identity so
        # checkpoint resume restores the exact treedef tx.update expects.
        cls = type(x)
        return {
            "__nt__": f"{cls.__module__}:{cls.__qualname__}",
            "v": [_pyify(v) for v in x],
        }
    if isinstance(x, tuple):
        return {"__t__": [_pyify(v) for v in x]}
    if isinstance(x, list):
        return {"__l__": [_pyify(v) for v in x]}
    if x is None:
        return {"__n__": 0}
    if isinstance(x, int):
        return x  # leaf placeholder
    raise TypeError(f"unsupported container in wire pytree: {type(x)}")


def _unpyify(x):
    if isinstance(x, dict):
        if "__d__" in x:
            return {k: _unpyify(v) for k, v in x["__d__"].items()}
        if "__nt__" in x:
            vals = [_unpyify(v) for v in x["v"]]
            mod, _, qual = x["__nt__"].partition(":")
            try:
                import importlib

                cls = importlib.import_module(mod)
                for part in qual.split("."):
                    cls = getattr(cls, part)
                return cls(*vals)
            except (ImportError, AttributeError):
                return tuple(vals)  # class gone: degrade to plain tuple
        if "__t__" in x:
            return tuple(_unpyify(v) for v in x["__t__"])
        if "__l__" in x:
            return [_unpyify(v) for v in x["__l__"]]
        if "__n__" in x:
            return None
    return x


def _treedef_from_json(j) -> Any:
    example = _unpyify(j)
    return jax.tree.structure(example, is_leaf=lambda v: isinstance(v, int) and not isinstance(v, bool))


# --- is_mobile JSON path (reference fedavg/utils.py:7-16) -------------------

def tree_to_jsonable(tree: Pytree) -> Any:
    """Tensors -> nested Python lists, mirroring transform_tensor_to_list."""
    return jax.tree.map(lambda x: np.asarray(x).tolist(), tree)


def tree_from_jsonable(jtree: Pytree, like: Pytree) -> Pytree:
    """Nested lists -> arrays with dtypes taken from ``like``
    (mirrors transform_list_to_tensor, fedavg/utils.py:7-11). The nested
    lists in ``jtree`` are leaves, so flatten up to ``like``'s structure."""
    ref_leaves, treedef = jax.tree.flatten(like)
    jleaves = treedef.flatten_up_to(jtree)
    return treedef.unflatten(
        [np.asarray(l, dtype=np.asarray(ref).dtype) for l, ref in zip(jleaves, ref_leaves)]
    )
