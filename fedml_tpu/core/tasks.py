"""Task families: loss + metric functions, mask-aware.

The reference couples task logic to trainers — one MyModelTrainer subclass
per family (classification / next-word-prediction / tag-prediction,
fedml_api/standalone/fedavg/my_model_trainer_*.py) plus the segmentation
Evaluator (fedseg/utils.py:62-70). Here a task is a pair of pure functions
``loss(logits, targets, mask)`` and ``metrics(logits, targets, mask)``, so
one jitted trainer serves every family.

Masks make ragged client datasets static-shaped for XLA: padded records
carry mask 0 and contribute nothing to loss or metrics.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class Task(NamedTuple):
    """loss returns a scalar; metrics returns a dict of SUMS plus 'count' so
    results aggregate correctly across batches and clients."""

    loss: Callable[[jax.Array, jax.Array, jax.Array], jax.Array]
    metrics: Callable[[jax.Array, jax.Array, jax.Array], dict]


def _masked_mean(values: jax.Array, mask: jax.Array) -> jax.Array:
    m = mask.astype(values.dtype)
    return jnp.sum(values * m) / jnp.maximum(jnp.sum(m), 1.0)


def int_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example softmax CE with integer labels. (Hand-rolled: optax's
    version chex-asserts on tracer dtypes, which trips under vmap+grad with
    numpy 2.)"""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(logz, labels[..., None].astype(jnp.int32), axis=-1)
    return -gold[..., 0]


def binary_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Numerically stable elementwise sigmoid BCE."""
    l = logits.astype(jnp.float32)
    t = targets.astype(jnp.float32)
    return jnp.maximum(l, 0.0) - l * t + jnp.log1p(jnp.exp(-jnp.abs(l)))


# --- classification (MyModelTrainerCLS counterpart) -------------------------

def classification_loss(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    per = int_cross_entropy(logits, targets)
    return _masked_mean(per, mask)


def classification_metrics(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> dict:
    m = mask.astype(jnp.float32)
    pred = jnp.argmax(logits, axis=-1)
    correct = jnp.sum((pred == targets).astype(jnp.float32) * m)
    per = int_cross_entropy(logits, targets)
    return {
        "correct": correct,
        "loss_sum": jnp.sum(per * m),
        "count": jnp.sum(m),
    }


classification = Task(classification_loss, classification_metrics)


# --- next-word / next-char prediction (MyModelTrainerNWP counterpart) -------
# logits [B, T, V], targets [B, T]; mask may be [B] (whole sequence) or [B, T].

def _seq_mask(mask: jax.Array, targets: jax.Array) -> jax.Array:
    if mask.ndim < targets.ndim:
        mask = jnp.broadcast_to(mask[..., None], targets.shape)
    return mask


def nwp_loss(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    m = _seq_mask(mask, targets)
    per = int_cross_entropy(logits, targets)
    return _masked_mean(per, m)


def nwp_metrics(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> dict:
    m = _seq_mask(mask, targets).astype(jnp.float32)
    pred = jnp.argmax(logits, axis=-1)
    per = int_cross_entropy(logits, targets)
    return {
        "correct": jnp.sum((pred == targets).astype(jnp.float32) * m),
        "loss_sum": jnp.sum(per * m),
        "count": jnp.sum(m),
    }


nwp = Task(nwp_loss, nwp_metrics)


# --- multilabel tag prediction (MyModelTrainerTAG counterpart; the reference
# tracks precision/recall for stackoverflow_lr, my_model_trainer.py:61-105) --

def tag_loss(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    per = jnp.sum(binary_cross_entropy(logits, targets), axis=-1)
    return _masked_mean(per, mask)


def tag_metrics(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> dict:
    m = mask.astype(jnp.float32)[:, None]
    pred = (jax.nn.sigmoid(logits) > 0.5).astype(jnp.float32)
    tgt = targets.astype(jnp.float32)
    tp = jnp.sum(pred * tgt * m)
    fp = jnp.sum(pred * (1 - tgt) * m)
    fn = jnp.sum((1 - pred) * tgt * m)
    per = jnp.sum(binary_cross_entropy(logits, targets), axis=-1)
    return {
        "true_pos": tp,
        "false_pos": fp,
        "false_neg": fn,
        "loss_sum": jnp.sum(per * mask.astype(jnp.float32)),
        "count": jnp.sum(mask.astype(jnp.float32)),
    }


tag_prediction = Task(tag_loss, tag_metrics)


# --- semantic segmentation (FedSeg Evaluator counterpart:
# pixel acc / mIoU / FWIoU from a confusion matrix, fedseg/utils.py) ---------

def make_segmentation_task(num_classes: int, ignore_index: int = 255) -> Task:
    def seg_loss(logits, targets, mask):
        # logits [B, H, W, C], targets [B, H, W]
        valid = (targets != ignore_index) & (mask.reshape(mask.shape + (1,) * (targets.ndim - mask.ndim)) > 0)
        tgt = jnp.where(valid, targets, 0)
        per = int_cross_entropy(logits, tgt)
        return _masked_mean(per, valid)

    def seg_metrics(logits, targets, mask):
        valid = (targets != ignore_index) & (mask.reshape(mask.shape + (1,) * (targets.ndim - mask.ndim)) > 0)
        pred = jnp.argmax(logits, axis=-1)
        tgt = jnp.where(valid, targets, 0)
        idx = tgt * num_classes + pred
        # int32 accumulation: float32 stalls at 2^24, which a single large
        # eval pool's background cell can exceed; int32 is exact to 2.1e9
        conf = jnp.bincount(
            idx.reshape(-1), weights=valid.reshape(-1).astype(jnp.int32),
            length=num_classes * num_classes,
        ).reshape(num_classes, num_classes)
        return {"confusion": conf, "count": jnp.sum(valid.astype(jnp.int32))}

    return Task(seg_loss, seg_metrics)


def segmentation_scores(confusion) -> dict:
    """Derive Acc / Acc_class / mIoU / FWIoU from an accumulated confusion
    matrix (reference Evaluator in fedseg/utils.py). Host-side finalizer:
    numpy float64, since jnp silently truncates to f32 without x64 mode."""
    import numpy as np

    conf = np.asarray(confusion, np.float64)
    total = max(conf.sum(), 1.0)
    diag = np.diag(conf)
    rows = conf.sum(axis=1)
    cols = conf.sum(axis=0)
    acc = diag.sum() / total
    with np.errstate(invalid="ignore"):
        acc_class = np.nanmean(np.where(rows > 0, diag / np.maximum(rows, 1.0), np.nan))
        union = rows + cols - diag
        iou = np.where(union > 0, diag / np.maximum(union, 1.0), np.nan)
        miou = np.nanmean(iou)
    freq = rows / total
    fwiou = np.nansum(np.where(union > 0, freq * diag / np.maximum(union, 1.0), 0.0))
    return {"Acc": acc, "Acc_class": acc_class, "mIoU": miou, "FWIoU": fwiou}


TASKS: dict[str, Task] = {
    "classification": classification,
    "nwp": nwp,
    "tag_prediction": tag_prediction,
}


def get_task(name: str, class_num: Optional[int] = None) -> Task:
    """'segmentation' is parameterized by class count (its metrics carry a
    [C, C] confusion matrix), so it is built on demand rather than looked up."""
    if name == "segmentation":
        if not class_num:
            raise ValueError("segmentation task requires class_num")
        return make_segmentation_task(class_num)
    if name not in TASKS:
        raise KeyError(f"unknown task {name!r}; known: {sorted(TASKS) + ['segmentation']}")
    return TASKS[name]
