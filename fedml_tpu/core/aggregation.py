"""Server-side aggregation primitives.

Covers the reference's aggregation variants as pure functions over pytrees:

- plain weighted averaging (fedavg_api.py:100-115),
- robust aggregation: norm-difference clipping and weak-DP gaussian noise
  (fedml_core/robustness/robust_aggregation.py:38-55),
- adaptive gradient clipping aggregation, NFNet-style unit-wise norms
  (fork's silo_fedagc.py:12-29, SiloFedAGC._aggregate :50-69),
- in-mesh collective aggregation: the weighted ``psum`` along a mesh axis
  that replaces the whole MPI round-trip of state dicts for in-pod runs
  (SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from fedml_tpu.core.pytree import (
    Pytree,
    path_str,
    tree_global_norm,
    tree_map_with_path_filter,
    tree_weighted_mean,
    tree_zero_by_path,
)

# Leaves whose key path contains one of these fragments are treated as
# non-weight statistics (BatchNorm running mean/var) and are averaged but
# never clipped/noised — mirrors is_weight_param (robust_aggregation.py:28-29).
# Precise fragments only: a weight legitimately named e.g. 'mean_head' must
# NOT be excluded. Flax puts BN stats under 'batch_stats/'.
NON_WEIGHT_KEY_FRAGMENTS = ("batch_stats", "running_mean", "running_var", "num_batches_tracked")


def is_weight_path(path: str) -> bool:
    return not any(frag in path for frag in NON_WEIGHT_KEY_FRAGMENTS)


def fedavg_aggregate(stacked_params: Pytree, num_samples: jax.Array) -> Pytree:
    """Sample-weighted FedAvg aggregation over the leading client axis.

    Reference: FedAvgAPI._aggregate (fedavg_api.py:100-115) /
    FedAVGAggregator.aggregate (FedAVGAggregator.py:58-87).
    """
    return tree_weighted_mean(stacked_params, num_samples)


def clip_update_by_norm(global_params: Pytree, local_params: Pytree, clip: float) -> Pytree:
    """Scale the client *update* (local - global) to L2 norm <= clip, then
    re-add. Reference: RobustAggregator.norm_diff_clipping
    (robust_aggregation.py:38-49), applied only to weight leaves."""
    diff = jax.tree.map(jnp.subtract, local_params, global_params)
    norm = tree_global_norm(tree_zero_by_path(diff, is_weight_path))
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    clipped = tree_map_with_path_filter(lambda x: x * scale, diff, is_weight_path)
    return jax.tree.map(jnp.add, global_params, clipped)


def add_dp_noise(params: Pytree, stddev: float, rng: jax.Array) -> Pytree:
    """Add i.i.d. gaussian noise to float weight leaves (weak DP defense,
    robust_aggregation.py:51-55). Stats and integer leaves (e.g. step
    counters) pass through untouched. Single traversal."""
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for i, (path, leaf) in enumerate(leaves_with_path):
        if is_weight_path(path_str(path)) and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            key = jax.random.fold_in(rng, i)
            out.append(leaf + stddev * jax.random.normal(key, leaf.shape, leaf.dtype))
        else:
            out.append(leaf)
    return jax.tree.unflatten(treedef, out)


def unitwise_norm(x: jax.Array) -> jax.Array:
    """NFNet unit-wise norm, matching the fork's shape dispatch
    (silo_fedagc.py:12-29): scalars/vectors -> global L2; linear weights
    [out,in] -> per-output-row; conv kernels -> per-output-filter.

    Flax conv kernels are [kh, kw, cin, cout] (torch is [cout, cin, kh, kw]),
    so the "unit" axis here is the LAST axis for ndim>=2.
    """
    if x.ndim <= 1:
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    axes = tuple(range(x.ndim - 1))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True))


def agc_clip_update(global_params: Pytree, local_params: Pytree, clipping: float = 1e-2, eps: float = 1e-3) -> Pytree:
    """Adaptive gradient clipping of the client update relative to the unit-wise
    norm of the global params (SiloFedAGC._aggregate, silo_fedagc.py:50-69)."""

    def clip_leaf(g, l):
        upd = l - g
        p_norm = jnp.maximum(unitwise_norm(g), eps)
        u_norm = jnp.maximum(unitwise_norm(upd), 1e-6)
        max_norm = p_norm * clipping
        clipped = jnp.where(u_norm > max_norm, upd * (max_norm / u_norm), upd)
        return g + clipped

    return jax.tree.map(clip_leaf, global_params, local_params)


def robust_aggregate(
    global_params: Pytree,
    stacked_local_params: Pytree,
    num_samples: jax.Array,
    norm_bound: Optional[float] = None,
    dp_stddev: Optional[float] = None,
    rng: Optional[jax.Array] = None,
) -> Pytree:
    """Norm-clip each client update, weighted-average, optionally add DP noise.

    Composition of the defenses used by fedavg_robust
    (FedAvgRobustAggregator.py:14-60 + robust_aggregation.py:38-55).
    """
    if norm_bound is not None:
        stacked_local_params = jax.vmap(
            lambda local: clip_update_by_norm(global_params, local, norm_bound)
        )(stacked_local_params)
    agg = tree_weighted_mean(stacked_local_params, num_samples)
    if dp_stddev is not None:
        if rng is None:
            raise ValueError("dp noise requires an rng key")
        agg = add_dp_noise(agg, dp_stddev, rng)
    return agg


def psum_weighted_average(local_params: Pytree, num_samples: jax.Array, axis_name: str) -> Pytree:
    """In-mesh FedAvg: every device holds one client's params; the weighted
    average is two psums over the mesh axis. This single collective replaces
    the reference's serialize -> MPI send -> queue -> poll -> deserialize ->
    Python dict-loop pipeline (SURVEY.md §3.2 boundary) and rides ICI.

    Call inside ``shard_map``/``pjit`` with ``axis_name`` bound.
    """
    w = num_samples.astype(jnp.float32)
    total = jax.lax.psum(w, axis_name)

    def avg(x):
        return (jax.lax.psum(x.astype(jnp.float32) * w, axis_name) / total).astype(x.dtype)

    return jax.tree.map(avg, local_params)


def mixing_average(stacked_params: Pytree, mixing_row: jax.Array) -> Pytree:
    """Decentralized gossip step for one node: weighted combination of
    neighbor params by a topology mixing-matrix row
    (reference symmetric_topology_manager.py:54-62 +
    decentralized_worker_manager.py:29-46)."""
    return tree_weighted_mean(stacked_params, mixing_row)


def hierarchical_aggregate(
    stacked_params: Pytree,
    num_samples: jax.Array,
    group_ids: jax.Array,
    num_groups: int,
) -> tuple[Pytree, Pytree]:
    """Two-tier client->group->global aggregation
    (reference hierarchical_fl/group.py:24-46 + trainer.py:43-69).

    Returns (group_params stacked [num_groups, ...], global_params).
    Implemented with segment_sum so it stays one fused XLA program.
    """
    w = num_samples.astype(jnp.float32)
    group_tot = jax.ops.segment_sum(w, group_ids, num_groups)

    def group_avg(x):
        xw = x.astype(jnp.float32) * w.reshape((-1,) + (1,) * (x.ndim - 1))
        s = jax.ops.segment_sum(xw, group_ids, num_groups)
        return (s / jnp.maximum(group_tot, 1e-12).reshape((-1,) + (1,) * (x.ndim - 1))).astype(x.dtype)

    group_params = jax.tree.map(group_avg, stacked_params)
    global_params = tree_weighted_mean(group_params, group_tot)
    return group_params, global_params
