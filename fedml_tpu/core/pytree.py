"""Pytree math utilities.

The reference manipulates ``OrderedDict`` state_dicts with per-key Python
loops (e.g. weighted averaging repeated verbatim in >=6 files,
fedavg_api.py:100-115; weight vectorization robustness/robust_aggregation.py:4-9).
Here every model/optimizer state is a JAX pytree and these helpers are the
single shared vocabulary: they are jit-safe, differentiable where meaningful,
and shape/dtype preserving.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    """a - b, leafwise. The FedOpt pseudo-gradient is tree_sub(global, avg)
    (reference fedopt_api.py:139-152)."""
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_axpy(a, x: Pytree, y: Pytree) -> Pytree:
    """a * x + y, leafwise."""
    return jax.tree.map(lambda xi, yi: a * xi + yi, x, y)


def tree_dot(a: Pytree, b: Pytree) -> jax.Array:
    """Global inner product over all leaves."""
    leaves = jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b)
    return jax.tree.reduce(jnp.add, leaves, jnp.zeros(()))


def tree_vectorize(tree: Pytree) -> jax.Array:
    """Flatten all leaves to one 1-D vector (reference
    robust_aggregation.py:4-9 ``vectorize_weight``)."""
    leaves = jax.tree.leaves(tree)
    return jnp.concatenate([jnp.ravel(x) for x in leaves]) if leaves else jnp.zeros((0,))


def tree_global_norm(tree: Pytree) -> jax.Array:
    """L2 norm over every element of every leaf."""
    sq = jax.tree.map(lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))), tree)
    return jnp.sqrt(jax.tree.reduce(jnp.add, sq, jnp.zeros(())))


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_count_params(tree: Pytree) -> int:
    return int(sum(np.prod(x.shape) for x in jax.tree.leaves(tree)))


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack a list of identically-structured pytrees along a new leading
    axis — how a list of per-client states becomes one vmap-able batch."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def tree_unstack(tree: Pytree, n: int) -> list[Pytree]:
    """Inverse of :func:`tree_stack`."""
    return [jax.tree.map(lambda x: x[i], tree) for i in range(n)]


def tree_index(tree: Pytree, i) -> Pytree:
    """Select index ``i`` along the leading axis of every leaf."""
    return jax.tree.map(lambda x: x[i], tree)


def tree_weighted_mean(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Weighted average along the leading (client) axis of every leaf.

    This is THE aggregation primitive: the reference re-implements it as a
    per-key dict loop in fedavg_api.py:100-115, FedAVGAggregator.py:58-87,
    fedopt_api.py, fednova_trainer.py, silo_fedavg.py... Here it is one
    einsum-shaped reduction that XLA maps onto the MXU/VPU.

    Args:
      stacked: pytree whose leaves have leading axis ``num_clients``.
      weights: ``[num_clients]`` nonnegative; normalized internally.
    """
    w = weights.astype(jnp.float32)
    w = w / jnp.maximum(jnp.sum(w), 1e-12)

    def avg(x):
        wb = w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(jnp.float32)
        return jnp.sum(x.astype(jnp.float32) * wb, axis=0).astype(x.dtype)

    return jax.tree.map(avg, stacked)


def tree_weighted_sum_list(trees: Sequence[Pytree], weights: Sequence[float]) -> Pytree:
    """Host-side weighted sum of a Python list of pytrees (normalized).

    Convenience for algorithm code that holds results as a list (mirrors the
    reference ``_aggregate`` signature, fedavg_api.py:100-115) without the
    reference's in-place mutation bug of ``w_locals[0]``.
    """
    total = float(sum(weights))
    out = tree_scale(trees[0], weights[0] / total)
    for t, w in zip(trees[1:], weights[1:]):
        out = tree_axpy(w / total, t, out)
    return out


def path_str(path) -> str:
    """Join a jax key-path to 'a/b/c' (single definition shared by the
    aggregation and serialization modules)."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def tree_map_with_path_filter(
    fn: Callable[[jax.Array], jax.Array],
    tree: Pytree,
    path_pred: Callable[[str], bool],
) -> Pytree:
    """Apply ``fn`` only to leaves whose joined key-path satisfies ``path_pred``;
    other leaves pass through unchanged.

    Used to skip non-weight leaves (e.g. BatchNorm running stats) the way the
    reference's ``is_weight_param`` does (robust_aggregation.py:28-29).
    """

    def _fn(path, leaf):
        return fn(leaf) if path_pred(path_str(path)) else leaf

    return jax.tree_util.tree_map_with_path(_fn, tree)


def tree_zero_by_path(tree: Pytree, path_pred: Callable[[str], bool]) -> Pytree:
    """Zero out leaves whose path does NOT satisfy ``path_pred`` (so norms /
    reductions see only the selected leaves)."""

    def _fn(path, leaf):
        return leaf if path_pred(path_str(path)) else jnp.zeros_like(leaf)

    return jax.tree_util.tree_map_with_path(_fn, tree)
