"""Deterministic RNG plumbing.

The reference seeds random/np/torch at each main (main_fedavg.py:313-316) and
notoriously reseeds np.random with the round index inside client sampling
(fedavg_api.py:83-91 ``np.random.seed(round_idx)``) so sampling is
reproducible across runs. Here everything flows from one ``jax.random.key``;
client sampling keys are derived by folding in the round index, which keeps
the reference's "same round -> same sample" property without touching global
state.
"""

from __future__ import annotations

import random

import jax
import numpy as np


def seed_everything(seed: int) -> jax.Array:
    """Seed python/numpy global RNGs (for host-side shuffles in data loaders)
    and return the root JAX key."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.key(seed)


def round_key(root: jax.Array, round_idx: int) -> jax.Array:
    """Per-round key; deterministic in (seed, round) like the reference's
    per-round reseed (fedavg_api.py:87)."""
    return jax.random.fold_in(root, round_idx)


def server_key(round_k: jax.Array) -> jax.Array:
    """Key for server-side randomness in a round (DP noise in robust
    aggregation). Derived by fold_in rather than reusing the round key the
    client keys were already split from (JAX RNG hygiene: never consume a
    parent key after splitting it). The simulation and cross-silo paths both
    use this same derivation so they stay bit-identical."""
    return jax.random.fold_in(round_k, 0x5E87)


def client_keys(round_k: jax.Array, num_clients: int) -> jax.Array:
    """[num_clients] keys for per-client dropout/shuffle inside one round."""
    return jax.random.split(round_k, num_clients)


def sample_clients(round_idx: int, client_num_in_total: int, client_num_per_round: int, seed: int = 0) -> np.ndarray:
    """Round-deterministic client sampling without replacement
    (reference _client_sampling, fedavg_api.py:83-91)."""
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total, dtype=np.int64)
    rng = np.random.default_rng(seed * 1_000_003 + round_idx)
    return np.sort(rng.choice(client_num_in_total, client_num_per_round, replace=False)).astype(np.int64)
