"""Federated data partitioners.

Re-implements the reference's non-IID machinery as seedable numpy functions:

- Dirichlet latent-Dirichlet-allocation label partition for classification
  and segmentation (fedml_core/non_iid_partition/noniid_partition.py:6-91),
- homogeneous random equal split (cifar10/data_loader.py:119-123),
- hetero Dirichlet over record indices (cifar10/data_loader.py:125-148),
- power-law / natural splits used by synthetic data,
- partition stats recording (noniid_partition.py:94-103).

All functions return ``dict[client_idx -> np.ndarray of record indices]``.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np


def record_data_stats(y: np.ndarray, net_dataidx_map: dict[int, np.ndarray], task: str = "classification") -> dict:
    """Per-client label histogram (reference noniid_partition.py:94-103)."""
    net_cls_counts = {}
    for net_i, dataidx in net_dataidx_map.items():
        if task == "segmentation":
            unq, unq_cnt = np.unique(np.concatenate(y[dataidx]), return_counts=True)
        else:
            unq, unq_cnt = np.unique(y[dataidx], return_counts=True)
        net_cls_counts[net_i] = {int(u): int(c) for u, c in zip(unq, unq_cnt)}
    logging.debug("Data statistics: %s", net_cls_counts)
    return net_cls_counts


def partition_class_samples_with_dirichlet_distribution(
    N: int,
    alpha: float,
    client_num: int,
    idx_batch: list[list[int]],
    idx_k: np.ndarray,
    rng: np.random.Generator,
) -> tuple[list[list[int]], int]:
    """Distribute one class's sample indices over clients by a Dirichlet draw,
    balancing so no client exceeds N/client_num samples
    (reference noniid_partition.py:76-91)."""
    rng.shuffle(idx_k)
    proportions = rng.dirichlet(np.repeat(alpha, client_num))
    # Zero out clients already at capacity, renormalize (reference :84-86).
    proportions = np.array(
        [p * (len(idx_j) < N / client_num) for p, idx_j in zip(proportions, idx_batch)]
    )
    total = proportions.sum()
    if total <= 0:
        # every client at capacity: spread this class uniformly instead of
        # dividing by zero (NaN cascade in the reference's version)
        proportions = np.full(client_num, 1.0 / client_num)
    else:
        proportions = proportions / total
    proportions = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
    idx_batch = [idx_j + idx.tolist() for idx_j, idx in zip(idx_batch, np.split(idx_k, proportions))]
    min_size = min(len(idx_j) for idx_j in idx_batch)
    return idx_batch, min_size


def non_iid_partition_with_dirichlet_distribution(
    label_list: np.ndarray,
    client_num: int,
    classes: int,
    alpha: float,
    task: str = "classification",
    seed: int = 0,
    min_size_floor: int = 10,
) -> dict[int, np.ndarray]:
    """Dirichlet LDA partition with the reference's min-10-samples retry loop
    (noniid_partition.py:6-73). ``task='segmentation'`` treats each record's
    label as a set of present classes."""
    net_dataidx_map: dict[int, np.ndarray] = {}
    rng = np.random.default_rng(seed)
    min_size = 0
    N = len(label_list)
    # Feasibility: with the capacity balancing, no client can exceed
    # N/client_num samples, and Dirichlet draws rarely give every client the
    # exact cap — clamp the floor to a reliably attainable level rather than
    # spin forever (the reference's retry loop hangs when N/client_num < 10).
    min_size_floor = max(1, min(min_size_floor, N // (client_num * 10)))
    attempts = 0
    while min_size < min_size_floor:
        attempts += 1
        if attempts > 1000:
            raise RuntimeError(
                f"Dirichlet partition failed to reach min size {min_size_floor} "
                f"after 1000 attempts (N={N}, clients={client_num}, alpha={alpha})"
            )
        idx_batch: list[list[int]] = [[] for _ in range(client_num)]
        for k in range(classes):
            if task == "segmentation":
                idx_k = np.asarray(
                    [i for i, lab in enumerate(label_list) if k in np.asarray(lab)]
                )
            else:
                idx_k = np.where(label_list == k)[0]
            if len(idx_k) == 0:
                continue
            idx_batch, min_size = partition_class_samples_with_dirichlet_distribution(
                N, alpha, client_num, idx_batch, idx_k, rng
            )
    for i in range(client_num):
        rng.shuffle(idx_batch[i])
        net_dataidx_map[i] = np.asarray(idx_batch[i], dtype=np.int64)
    return net_dataidx_map


def homo_partition(n_records: int, client_num: int, seed: int = 0) -> dict[int, np.ndarray]:
    """Random equal split (reference cifar10/data_loader.py:119-123)."""
    rng = np.random.default_rng(seed)
    idxs = rng.permutation(n_records)
    return {i: np.sort(part).astype(np.int64) for i, part in enumerate(np.array_split(idxs, client_num))}


def hetero_partition(
    labels: np.ndarray,
    client_num: int,
    classes: int,
    alpha: float,
    seed: int = 0,
) -> dict[int, np.ndarray]:
    """'hetero' partition method: Dirichlet over labels
    (reference cifar10/data_loader.py:125-148)."""
    return non_iid_partition_with_dirichlet_distribution(
        labels, client_num, classes, alpha, seed=seed
    )


def hetero_fix_partition(
    labels: np.ndarray,
    client_num: int,
    classes: int,
    alpha: float,
    map_path: str,
    seed: int = 0,
) -> dict[int, np.ndarray]:
    """'hetero-fix': a PRECOMPUTED partition map file so every run (and every
    rank) sees the identical non-IID split (reference
    cifar10/data_loader.py:150-158 reads distribution/net_dataidx_map text
    files shipped with the repo). Here the map is a .npz of per-client index
    arrays; when the file doesn't exist yet it is generated once with the
    Dirichlet machinery and saved, so the first run fixes the split for all
    later runs."""
    import os

    if os.path.exists(map_path):
        with np.load(map_path) as z:
            m = {int(k.split("_", 1)[1]): z[k] for k in z.files}
        if len(m) != client_num:
            raise ValueError(
                f"partition map {map_path!r} has {len(m)} clients, expected "
                f"{client_num}; delete it to regenerate"
            )
        # a stale map from a different dataset snapshot must not silently
        # mis-partition: it must cover exactly the current records
        allidx = np.concatenate([m[i] for i in range(client_num)])
        if len(allidx) != len(labels) or (
            len(allidx) and int(allidx.max()) >= len(labels)
        ):
            raise ValueError(
                f"partition map {map_path!r} covers {len(allidx)} records "
                f"(max index {int(allidx.max()) if len(allidx) else -1}) but "
                f"the dataset has {len(labels)}; delete it to regenerate"
            )
        return {i: m[i].astype(np.int64) for i in range(client_num)}
    m = hetero_partition(labels, client_num, classes, alpha, seed=seed)
    os.makedirs(os.path.dirname(map_path) or ".", exist_ok=True)
    tmp = map_path + ".tmp.npz"
    np.savez(tmp, **{f"client_{i}": v for i, v in m.items()})
    os.replace(tmp, map_path)
    return m


def partition(
    method: str,
    labels: np.ndarray,
    client_num: int,
    classes: int,
    alpha: Optional[float] = None,
    seed: int = 0,
    map_path: Optional[str] = None,
) -> dict[int, np.ndarray]:
    """Dispatch on the reference's --partition_method flag values
    (homo | hetero | hetero-fix)."""
    if method == "homo":
        return homo_partition(len(labels), client_num, seed=seed)
    if method == "hetero":
        if alpha is None:
            raise ValueError("hetero partition requires alpha (--partition_alpha)")
        return hetero_partition(labels, client_num, classes, alpha, seed=seed)
    if method == "hetero-fix":
        if alpha is None:
            raise ValueError("hetero-fix partition requires alpha for first-run generation")
        if map_path is None:
            raise ValueError("hetero-fix partition requires a map_path")
        return hetero_fix_partition(labels, client_num, classes, alpha, map_path, seed=seed)
    raise ValueError(f"unknown partition method: {method!r}")
