"""Core substrate: pytree math, aggregation primitives, partitioners, config.

Replaces the reference's ``fedml_core`` package (SURVEY.md §2.1). Everything
here is backend-agnostic pure math — no communication, no models.
"""

from fedml_tpu.core import aggregation, config, partition, pytree, rng, serialization  # noqa: F401
