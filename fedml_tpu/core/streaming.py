"""Streaming server-side aggregation: fold contributions as they arrive.

The batch aggregation paths buffer the whole cohort before averaging — the
edge server's ``model_dict`` holds every worker's model tree, the sim
paradigm stacks the cohort's results inside one program. Both are O(cohort)
in memory, which is exactly the bound thousand-client cohorts must escape.
This module is the O(1) replacement: a running weighted accumulator (ONE
model-shaped sum + a scalar weight) each contribution folds into.

Two fold orders, selected by ``--stream_aggregate``:

- ``deterministic``: contributions fold in their CANONICAL index order
  (worker index on the edge, chunk order on the sim path). Out-of-order
  arrivals are held until their predecessors fold — the held set is empty
  whenever arrivals are in order, and bounded by the worker count in the
  worst case (``peak_held`` measures it). The aggregate is a pure function
  of the contribution SET — independent of arrival timing, retransmits,
  chaos reordering, or pipeline depth.
- ``arrival``: fold strictly on arrival — O(1) held state always. The
  aggregate depends on arrival order only through float summation order
  (pinned at the fedseg tolerance by tests/test_fedsched.py).

The accumulator sums in float64 and divides once at :meth:`finalize`, so
a long fold cannot drift the way repeated float32 re-normalization would;
the result is cast back to each leaf's dtype. Zero-weight contributions
(rejoin catch-ups, failed clients) fold as no-ops — identical to their
zero-weight term in the batch weighted mean.

The sim paradigm's chunked round path does its folding ON DEVICE inside
jitted chunk programs (algorithms/fedavg.py), and the sequential-client
``StreamingFedAvgAPI`` builds its own jitted device fold — this host-side
class serves the EDGE aggregator (StreamingFedAVGAggregator) and carries
the measured ``nbytes`` the O(1)-memory test pins.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import numpy as np

__all__ = ["StreamAccumulator"]

Pytree = Any


def _tree_map(fn, *trees):
    import jax

    return jax.tree.map(fn, *trees)


class StreamAccumulator:
    """Running weighted accumulator over pytree contributions (module
    docstring). Thread-safe: the edge server's handler thread feeds it."""

    def __init__(self, mode: str = "deterministic"):
        if mode not in ("deterministic", "arrival"):
            raise ValueError(
                f"stream mode must be deterministic|arrival, got {mode!r}")
        self.mode = mode
        self._lock = threading.Lock()
        self._acc: Optional[Pytree] = None      # float64 leaf sums
        self._acc_w = 0.0
        self._next = 0                          # deterministic: fold frontier
        self._held: dict[int, tuple] = {}       # deterministic out-of-order
        self.folded = 0
        #: high-water mark of simultaneously held contributions — the
        #: measured evidence the O(1) pin reads (0 for in-order feeds)
        self.peak_held = 0

    def _fold(self, tree: Pytree, weight: float) -> None:
        if weight:
            scaled = _tree_map(
                lambda x: np.asarray(x, np.float64) * weight, tree)
            if self._acc is None:
                self._acc = scaled
            else:
                self._acc = _tree_map(np.add, self._acc, scaled)
            self._acc_w += weight
        elif self._acc is None:
            # remember the tree SHAPE so an all-zero-weight round can still
            # finalize to the elastic no-op without a template guess
            self._acc = _tree_map(
                lambda x: np.zeros(np.shape(x), np.float64), tree)
        self.folded += 1

    def add(self, index: int, tree: Pytree, weight: float) -> None:
        """Fold contribution ``index`` (its canonical position: worker
        index, chunk index) with aggregation ``weight``."""
        weight = float(weight)
        with self._lock:
            if self.mode == "arrival":
                self._fold(tree, weight)
                return
            self._held[int(index)] = (tree, weight)
            self.peak_held = max(self.peak_held, len(self._held))
            while self._next in self._held:
                t, w = self._held.pop(self._next)
                self._fold(t, w)
                self._next += 1

    def finalize(self, template: Pytree) -> Optional[Pytree]:
        """Close the round: drain any still-held contributions in index
        order (workers the deadline dropped leave gaps — the survivors
        fold in THEIR index order, still arrival-independent), then return
        the weighted mean cast to ``template``'s leaf dtypes — or ``None``
        for a zero-weight round (the caller's elastic no-op)."""
        with self._lock:
            for i in sorted(self._held):
                t, w = self._held.pop(i)
                self._fold(t, w)
            if self._acc is None or self._acc_w <= 0.0:
                return None
            inv = 1.0 / self._acc_w
            return _tree_map(
                lambda a, t: (a * inv).astype(np.asarray(t).dtype),
                self._acc, template)

    @property
    def nbytes(self) -> int:
        """Measured accumulator footprint: the float64 running sum plus
        whatever is currently held — ONE model copy plus the (normally
        empty) out-of-order buffer, independent of how many contributions
        have folded."""
        import jax

        # locked: add() on the arrival path mutates _held mid-iteration
        # otherwise (dict-changed-size) and swaps _acc leaves mid-sum
        with self._lock:
            total = 0
            if self._acc is not None:
                total += sum(np.asarray(leaf).nbytes
                             for leaf in jax.tree.leaves(self._acc))
            for t, _w in self._held.values():
                total += sum(np.asarray(leaf).nbytes
                             for leaf in jax.tree.leaves(t))
            return int(total)
