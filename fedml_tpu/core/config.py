"""Typed configuration.

The reference drives everything through ~20 raw argparse flags repeated in
every ``main_*.py`` (fedml_experiments/distributed/fedavg/main_fedavg.py:48-120)
plus bash positional launchers and ad-hoc YAML/CSV sidecars. Here the flag
surface is one dataclass with validation, an argparse bridge that reproduces
the reference flag names, and YAML load/save.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional

try:
    import yaml
except ImportError:  # pragma: no cover
    yaml = None


@dataclass
class FedConfig:
    """Union of the reference's experiment flags (main_fedavg.py:48-120,
    main_fedopt.py:54-60, main_fedgkt.py:37-88) with validated defaults."""

    # model / data
    model: str = "lr"
    dataset: str = "mnist"
    data_dir: str = "./data"
    partition_method: str = "hetero"
    partition_alpha: float = 0.5
    class_num: Optional[int] = None

    # federation topology
    client_num_in_total: int = 10
    client_num_per_round: int = 10
    comm_round: int = 10
    group_num: int = 1               # hierarchical FL (group_comm_round below)
    group_comm_round: int = 1

    # local training
    batch_size: int = 32
    client_optimizer: str = "sgd"    # sgd | adam
    lr: float = 0.03
    wd: float = 0.0
    momentum: float = 0.0
    epochs: int = 1
    grad_clip: Optional[float] = None  # reference clips local grads at 1.0 for some trainers

    # server optimizer (FedOpt; reference main_fedopt.py:54-60)
    server_optimizer: str = "sgd"
    server_lr: float = 1.0
    server_momentum: float = 0.0

    # FedProx (reference omitted the prox term — we implement it; mu flag)
    fedprox_mu: float = 0.1

    # robustness (fedavg_robust main flags)
    norm_bound: Optional[float] = None
    stddev: Optional[float] = None
    attack_type: Optional[str] = None
    poison_frac: float = 0.0

    # FedNAS (main_fednas.py --unrolled: second-order DARTS architect)
    unrolled: int = 0

    # FedGKT (main_fedgkt.py:37-88)
    temperature: float = 3.0
    alpha_distill: float = 1.0
    model_client: str = "resnet8"
    model_server: str = "resnet56_server"
    epochs_server: int = 1           # reference --epochs_server / epoch strategy

    # runtime / backend
    backend: str = "mesh"            # mesh | inproc | grpc | mqtt (reference: MPI|GRPC|MQTT)
    # Multi-process deployment (reference: mpirun -np N, run_fedavg_
    # distributed_pytorch.sh:21-23 — one OS process per participant). When
    # rank is set, the entry point starts ONLY this rank's manager over a
    # real transport (gRPC, rank→IP resolved from grpc_ipconfig_path like
    # the reference's grpc_ipconfig.csv, grpc_comm_manager.py:59-60) and
    # blocks until the federation finishes. rank=None (default) keeps the
    # single-process in-memory launch used by simulations and tests.
    rank: Optional[int] = None
    world_size: Optional[int] = None
    grpc_ipconfig_path: Optional[str] = None  # csv "receiver_id,ip"; None = all loopback
    grpc_base_port: int = 50000      # reference: port 50000 + rank
    # Edge-transport payload compression (core/compression.py):
    # "raw" (exact) | "q8" (uint8 affine quantization, ~4x smaller) |
    # "topk:<ratio>" (magnitude sparsification — for update deltas).
    # The reference's --is_mobile JSON-list path is the counterpart
    # (fedavg/utils.py:7-16) — it converts format without saving bytes.
    wire_codec: str = "raw"
    # Edge FedAvg uploads (local - global) deltas with an error-feedback
    # residual instead of full weights (DGC-style). Lossless under
    # wire_codec="raw"; pairs with "topk:<r>"/"q8", whose un-sent mass
    # re-enters the next round's upload.
    wire_delta: bool = False
    # Reliable wire delivery (comm/reliable.py): per-pair sequence numbers,
    # ACK/retransmit with exponential backoff, receiver-side dedup — every
    # protocol handler sees exact-once semantics over a lossy wire. With
    # zero faults the layer is bit-identical to the bare transports
    # (tests/test_chaos.py), so the only cost of enabling it is the ack
    # traffic. Required whenever chaos drop/dup/reorder rates are set.
    wire_reliable: bool = False
    # Reliable-layer retry schedule: exponential backoff from
    # wire_retry_base_s (cap at 20x the base) for up to wire_retry_max
    # retransmits before a message is abandoned (gave_up — the dead-peer
    # oracle fedbuff ejects by). The defaults reproduce the layer's
    # historical schedule (~6.6 s to exhaustion); a LAN/CI federation can
    # shrink detection latency by an order of magnitude, a lossy WAN can
    # deepen the budget. The teardown drain window derives from the
    # schedule automatically.
    wire_retry_base_s: float = 0.05
    wire_retry_max: int = 10
    # Bounded inboxes (comm/local.py, grpc_backend.py, mqtt_backend.py) and
    # the gateway's per-tenant lane queues (comm/flow.py): 0 keeps the
    # historical unbounded queues; > 0 caps delivery-queue depth. On bare
    # transports a full inbox BLOCKS the producer (queue put / gRPC flow
    # control / broker TCP); at the gateway a full lane answers WIRE_BUSY,
    # so the cap requires wire_reliable=True there (the sender's reliable
    # layer consumes the push-back).
    wire_inbox_cap: int = 0
    # Federation gateway quotas (distributed/gateway.py): over-admission is
    # rejected with a typed terminal NACK, never silently. max_tenants caps
    # concurrent federations; tenant_workers (0 = unlimited) caps any one
    # tenant's worker count.
    gateway_max_tenants: int = 8
    gateway_tenant_workers: int = 0
    # Chaos injection (comm/chaos.py): seeded, deterministic wire faults for
    # robustness testing. Rates are per-transmission probabilities; delay is
    # the max per-message latency in ms (uniform draw). chaos_crash_rank /
    # chaos_crash_after crash-stop one rank after that many sends (the
    # killed-process model the straggler deadline handles).
    chaos_seed: int = 0
    chaos_drop: float = 0.0
    chaos_dup: float = 0.0
    chaos_delay_ms: float = 0.0
    chaos_reorder: float = 0.0
    chaos_crash_rank: Optional[int] = None
    chaos_crash_after: Optional[int] = None
    # crash_restart fate: the crash-stopped rank REVIVES after this many
    # seconds of total silence (both directions) and its protocol layer
    # re-announces itself (JOIN) — the recovery path, not just death.
    # None (default) keeps crash-stop permanent.
    chaos_crash_restart_s: Optional[float] = None
    frequency_of_the_test: int = 5
    is_mobile: int = 0
    seed: int = 0
    ci: int = 0                      # --ci fast path (reference CI-script-fedavg.sh)

    # TPU-specific
    mesh_shape: tuple = ()           # e.g. (8,) client axis; () = auto
    dtype: str = "float32"           # compute dtype: float32 | bfloat16
    donate: bool = True
    # Defer the per-round host sync: run_round returns the loss as a device
    # scalar instead of float()ing it, so consecutive rounds pipeline through
    # the dispatch queue (the remote-compile tunnel costs ~100 ms per forced
    # sync; eval/logging rounds still sync when they read the value).
    async_rounds: bool = False
    # Keep the full stacked client dataset resident in HBM and gather the
    # sampled cohort ON DEVICE each round ("auto"|"on"|"off"). The reference
    # re-ships the cohort host->device every round (its DataLoader contract);
    # on TPU that transfer dominates the round (tunnel/PCIe bandwidth), so
    # auto places train data on device whenever it fits the budget below.
    device_data: str = "auto"
    device_data_max_bytes: int = 6_000_000_000
    # Cohort bucketing: pad each round's scan length to the max REAL record
    # count of the sampled cohort, quantized to this many batches (0 = always
    # pad to the global max). Under hetero (LDA) partitions the global n_pad
    # is set by the single biggest client, so every round otherwise burns
    # dead masked SGD steps on pure padding (~40% of compute at alpha=0.5).
    # Each distinct bucket compiles its own XLA program (bounded by
    # n_pad/quantum programs; quantization keeps that small). Note: the
    # per-epoch shuffle draws a permutation of the (truncated) record axis,
    # so a bucketed run composes real records into different minibatches
    # than an unbucketed run — same distribution, different trajectory.
    # Runs are still deterministic per (seed, config).
    bucket_quantum_batches: int = 8
    # Split the sampled cohort into up to this many count-sorted groups,
    # each with its own (quantum-rounded) scan length, inside ONE round
    # program — small clients stop paying the largest client's padding
    # steps. 1 = single shared scan length (the bucket above). Same
    # weighted aggregate either way (group order is irrelevant to it);
    # like bucketing itself, the truncated shuffle stream changes the
    # trajectory, not the distribution. Device-resident (gather) path only.
    bucket_groups: int = 1
    # Client-packing schedule (parallel/packed.py): pack the sampled cohort
    # into this many fixed-length scan lanes, clients back-to-back with
    # optimizer reset at boundaries — padding shrinks from group-max
    # granularity to one batch per client plus the lane tail. 0 = off.
    # Each client's trajectory replays the canonical unbucketed program
    # exactly; the aggregate matches up to float summation order. Overrides
    # bucket_groups on the device-resident simulation path; serves every
    # algorithm with a plain weighted mean OR a crosssilo_hooks contract
    # (FedOpt/FedNova/FedAGC/robust — server state threads through the
    # packed round); only rewired build_local_train / hookless custom
    # aggregate() fall back, with a warning.
    pack_lanes: int = 0
    # fedpack conv lowering for the packed schedule's lane axis
    # (ops/packed_conv.py): how the K co-scheduled lanes' same-shape convs
    # reach the MXU. "off" (default) keeps the per-lane vmap (XLA lowers it
    # to a grouped conv, docs/mfu_experiments.md H4); "blockdiag" runs ONE
    # im2col block-diagonal GEMM per conv across all lanes (output lanes
    # K*Cout, reduction lanes K*kh*kw*Cin — full MXU dims at K*C >= 128, at
    # the price of K x streamed FLOPs, reported honestly by fedcost's
    # packing_factor column); "grouped" runs one feature_group_count=K
    # convolution (useful FLOPs only; XLA picks the MXU mapping); "auto"
    # asks the fedplan cost model (obs/plan.py) to pick PER CONV STAGE from
    # the static fedcost table at program-build time — the chosen plan
    # rides cost_hints, a program_plan trace instant and the "plan" pulse
    # lane, and a post-first-call self-check warns when the realized
    # static ceiling diverges from the prediction. Applies
    # wherever pack_lanes schedules lanes (sim + cross-silo mesh). The
    # joint form is the DEFAULT abstraction (packed-everywhere, DESIGN.md
    # §15): every client optimizer (stacked per-lane optax state),
    # explicit-key dropout models and the Silo variants ride it; only the
    # documented exception table (no packed twin / flax-rng dropout) falls
    # back, warned once + counted in the "packed" registry lane. Numerics
    # match the vmap lowering up to GEMM summation order
    # (tests/test_packed_conv.py, tests/test_packed_everywhere.py).
    packed_conv: str = "off"
    # Cross-silo super-step: fold H consecutive rounds into ONE jitted
    # program (lax.scan over round keys) on the packed resident-sharded
    # mesh path — amortizes the fixed per-round cost (dispatch + program
    # prologue/epilogue, the weak-scaling intercept of docs/perf.md) over
    # H rounds. Requires full participation without failure injection;
    # per-round losses still come back individually. 1 = off.
    rounds_per_step: int = 1
    # lax.scan unroll factor for the local-SGD minibatch loop: XLA fuses
    # across adjacent steps (amortizing per-step loop/weight-traffic
    # overheads) without changing the math — same updates in the same
    # order. Measured on v5e: see docs/mfu_experiments.md.
    scan_unroll: int = 1
    # Host round pipeline (data/pipeline.CohortPrefetcher): keep this many
    # FUTURE rounds' cohorts in flight on background threads — cohort
    # materialization, host bf16 cast, and host->device transfer all overlap
    # the in-flight round's device compute. Applies to the non-device-
    # resident (host) round paths only: the sampled cross-device
    # materialization path and the streaming paradigm. The per-round plan is
    # a pure function of (seed, round_idx), so prefetched rounds are
    # bit-identical to the serial path (0 = serial, today's behavior).
    host_pipeline_depth: int = 0
    # Worker threads fanning cohort materialization out over clients inside
    # one prefetched round (per-client RNG streams are independent, so the
    # parallel materialization is bit-identical to serial). 0 = auto.
    host_pipeline_workers: int = 0
    # fedsched cohort-selection policy (data/sched.py): how the round's
    # cohort is drawn from the client population. "uniform" (default) is
    # today's deterministic draw, bit-identical by construction. "speed"
    # packs cohorts from the fedpulse ClientProfiler's observed EMA
    # train-ms (an oversampled uniform pool, keep the fastest) so one slow
    # client no longer gates the round; "fair" is speed packing with a
    # fixed fraction of the cohort reserved for the least-participated
    # candidates. Profiler-driven policies are pure in (seed, round,
    # profiler-snapshot-at-schedule-time); with no profiler (pulse plane
    # off) they schedule uniform cold-starts and warn once.
    cohort_policy: str = "uniform"
    # fedbuff: asynchronous buffered aggregation (algorithms/fedbuff.py +
    # distributed/fedbuff_edge.py). The server folds each client upload
    # (an update delta against the model version the client trained from)
    # into a StreamAccumulator with a staleness-decayed weight
    # ``n * (1 + staleness)^-buffer_staleness_alpha`` where staleness =
    # server_version - trained_version, and emits a new model version every
    # ``buffer_k`` contributions — no round barrier, no straggler deadline:
    # slow clients contribute with decayed weight instead of being dropped.
    buffer_k: int = 4
    buffer_staleness_alpha: float = 0.5
    # Fold-order contract (mirrors --stream_aggregate): "arrival" folds
    # each upload the moment it lands (the production fast path — results
    # depend on arrival order through float summation + version grouping);
    # "deterministic" folds in the canonical (train-tag, worker) frontier
    # order, making the WHOLE async schedule a pure function of
    # (seed, chaos_seed) — bit-identical replayable under chaos
    # (tests/test_fedbuff.py pins it on local + grpc).
    buffer_mode: str = "arrival"
    # Streaming server-side aggregation (core/streaming.py + the chunked
    # host round path): fold each client contribution into a running
    # weighted accumulator instead of buffering the whole cohort — O(1)
    # memory in cohort size. "off" (default) keeps today's batch
    # aggregation, bit-identical. "deterministic" folds in the fixed plan
    # order (chunk order on the sim path, worker-index order on the edge
    # via hold-and-fold) so results are independent of arrival timing;
    # unchunked it is bit-identical to batch aggregation by construction.
    # "arrival" folds strictly on arrival (the O(1)-strict edge mode);
    # numerics match batch within the fedseg tolerance (float summation
    # order only).
    stream_aggregate: str = "off"
    # Sub-cohort chunk size for the streaming host round path: the sampled
    # cohort materializes, ships and trains in chunks of this many clients,
    # each folded into the streaming accumulator as it finishes — cohort
    # size is bounded by the accumulator (one model copy), not by one
    # jitted program's buffers, which is what thousand-client cohorts
    # need. 0 = whole cohort in one program. Requires stream_aggregate on.
    # With pack_lanes > 0 each chunk rides the packed-lanes round program
    # (clients packed back-to-back in scan lanes — the MXU fast path).
    cohort_chunk: int = 0
    # Cohort execution schedule: 0 (default) trains the whole sampled cohort
    # under one vmap — per-client convs fuse into ONE grouped convolution
    # (feature_group_count = cohort), which XLA's TPU lowering expands
    # ~cohort-fold (docs/mfu_experiments.md H4). k > 0 instead runs the
    # cohort as lax.map over chunks of k vmapped clients (k=1 = fully
    # sequential clients, plain convs). EXACT same per-client math and
    # aggregate either way — this only reorders independent client programs.
    # Simulation paradigm only (measured FLAT there, H4); the cross-silo
    # mesh rounds always vmap the per-device client block and warn if set.
    cohort_vmap_width: int = 0

    # observability
    run_name: str = "fedml_tpu"
    enable_wandb: bool = False
    # fedtrace span tracing (fedml_tpu/obs, DESIGN.md §12): when set, every
    # rank writes <trace_dir>/trace-rank<r>.jsonl — spans for rounds,
    # message send/recv (stitched cross-rank by message id), pipeline
    # stages, wire retransmits — for tools/trace_report.py or a Perfetto
    # export. None (default) disables tracing entirely: the hot paths see
    # one global flag check and allocate nothing, and a traced run is
    # bit-identical to an untraced one (the tracer only reads clocks).
    trace_dir: Optional[str] = None
    # ring-buffer bound per rank: oldest events fall off instead of
    # growing the heap on a weeks-long federation
    trace_buffer_events: int = 65536
    # fedsketch head-based span sampling (obs/tracer.span_sampled): keep
    # only this fraction of the ROUND span trees — the keep/drop verdict
    # is a pure hash of (seed, round), so every rank/host/re-run samples
    # the SAME rounds and the trace stays a consistent subset. Sampled-out
    # rounds still feed counters, pulse snapshots and the sketch lanes —
    # percentiles stay exact while span volume is bounded. 1.0 = keep all.
    trace_sample_rate: float = 1.0
    # fedsketch relative accuracy for the profiler's distribution lanes
    # (train-ms / upload-latency / payload-bytes / staleness): a quantile
    # estimate is within this fraction of the true value. Smaller = more
    # buckets (memory grows ~1/alpha, still structurally capped).
    sketch_alpha: float = 0.01
    # fedcost static roofline attribution (obs/cost, DESIGN.md §13): when
    # on, every round program built through obs/compile.timed_build is
    # ALSO lowered to HLO and read back as a per-op GEMM table (conv/dot
    # M/K/N shapes, FLOPs, MXU lane fills, flop-weighted lane ceiling),
    # stored process-wide (obs.cost_tables()) and — under tracing — emitted
    # as a "program_cost" event for tools/trace_report.py's cost section.
    # Pure static analysis: one extra trace per program build (no compile,
    # no device sync), numerics bit-identical on or off.
    cost_attribution: bool = False
    # fedpulse live telemetry plane (obs/live + obs/profile, DESIGN.md §14):
    # when set, every round boundary appends ONE atomic JSON snapshot
    # (registry time/wire/chaos/compile lanes, host-stage row, per-client
    # profiler aggregates, cost-attribution MFU, health verdict) to this
    # file — tail it live with tools/fedtop.py. None (default) disables the
    # whole plane: the hot path sees one global read and allocates nothing,
    # and a pulse-on run is bit-identical to a pulse-off run (the plane
    # only reads counters and clocks).
    pulse_path: Optional[str] = None
    # optional Prometheus textfile-collector mirror: each snapshot also
    # atomically rewrites <dir>/fedpulse.prom as flat gauges (requires
    # pulse_path)
    pulse_prometheus_dir: Optional[str] = None
    # fedpulse health watchdog (obs/health): rules evaluated at every round
    # boundary while the plane is on. NaN-loss and wire gave_up are always
    # armed; the knobs below arm/tune the rest (0/None = that rule off).
    health_loss_limit: float = 0.0        # loss > limit -> divergent_loss
    health_stall_sec: Optional[float] = None  # round wall > this -> stall
    health_stale_spike: int = 8           # stale_uploads delta/round -> warn
    health_skew: float = 4.0              # p95/p50 EMA train-ms -> warn
    # fedbuff version-lag rule: warn when THIS round's staleness-sketch
    # delta p99 (rounds/versions behind per contribution) reaches this
    # many versions; escalates to critical when the p99 grows strictly
    # monotonically for VERSION_LAG_MONOTONIC_N consecutive snapshots —
    # the buffered-async divergence signature (clients falling ever
    # further behind the emitted version). 0 = rule off (sync runs keep
    # their stale_spike rule; async launchers arm this one).
    health_version_lag: float = 0.0
    # fedlens learning-signal attribution rules (require --lens on to have
    # data): warn when THIS round's update-norm / drift sketch delta p99
    # reaches the threshold, carrying the round's top-k suspect client
    # ids. 0 = rule off. The aligned_suspects critical rule needs no knob:
    # it arms whenever the lens surfaces suspects.
    health_update_norm: float = 0.0
    health_drift: float = 0.0
    # escalate-to-raise: any critical health event raises
    # FederationHealthError AFTER its pulse snapshot is written
    health_escalate: bool = False
    # fedlens in-program learning-signal telemetry (obs/lens, DESIGN.md
    # §22): 'on' arms per-client update-norm / loss-delta / alignment
    # reductions INSIDE the round programs (output-only — aggregation is
    # bit-identical to 'off', pinned by tests/test_lens.py) and feeds the
    # pulse plane's `learning` block, the profiler's update_norm/drift
    # sketch lanes, and the attributed watchdog rules. 'off' (default)
    # builds the exact lens-free programs.
    lens: str = "off"
    # how many ranked suspect client ids each learning block / watchdog
    # event / incident bundle carries
    lens_topk: int = 5
    # fedflight anomaly-triggered flight recorder (obs/flight, DESIGN.md
    # §21): when set, the process retains the last --flight_window rounds
    # of FULL-rate round spans (a second per-rank ring beside the sampled
    # trace stream — the head sampler keeps gating what streams, the
    # recorder keeps everything recent), pulse snapshots with per-round
    # counter-lane deltas, and watchdog transitions — and dumps a
    # self-contained incident-<id>/ bundle into this directory when a
    # trigger fires (watchdog escalation BEFORE the raise, gateway
    # quarantine, reliable-layer peer_dead, manual/SIGUSR2). The bundle
    # manifest names the EXACT replay command from (seed, chaos_seed,
    # non-default flags); incident ids are pure in (seed, round, rule) so
    # every rank converges on one bundle; analyze with tools/fedpost.py.
    # None (default) disarms the recorder: hot paths see one attribute
    # check and allocate nothing, and a recorder-on run is bit-identical
    # to a recorder-off run (the recorder only reads what the round
    # already produced).
    flight_dir: Optional[str] = None
    # rounds of full-rate retrospective capture retained per rank
    # (ring bound = flight_window * obs.flight.EVENTS_PER_ROUND events)
    flight_window: int = 8
    # comma list arming the trigger inventory: escalate (watchdog),
    # quarantine (gateway lane), peer_dead (reliable layer), manual
    # (obs.flight.trigger() / SIGUSR2)
    flight_on: str = "escalate,quarantine,peer_dead,manual"
    # fedscope device-memory sampler: when tracing is on, snapshot
    # jax.local_devices() memory_stats (bytes_in_use + peak watermark) at
    # every round boundary into a "device" counter lane (one allocator read
    # per device per round, host-side, never syncs the device stream; CPU
    # backends fall back to one process-RSS read). Off = spans only.
    trace_device_sampler: bool = True

    # checkpoint/resume (absent in the reference, SURVEY.md §5.4)
    checkpoint_dir: Optional[str] = None
    checkpoint_frequency: int = 10   # rounds between checkpoints when dir set
    resume_from: Optional[str] = None

    # failure injection / elastic rounds (SURVEY.md §5.3: reference has none)
    failure_prob: float = 0.0        # P(sampled client fails a round)
    # Fault-tolerant EDGE rounds (reference: one dead worker hangs the
    # federation until MPI.Abort, client_manager.py:66-69; the mesh path
    # here already has elastic rounds). When set, the edge server
    # aggregates whichever uploads arrived within this many seconds of a
    # round's broadcast, marks missing workers dead (skipping their sends
    # so a dead peer can't stall the loop), re-deals their logical clients
    # to survivors next round, and accepts rejoining workers. None (default)
    # keeps the strict all-workers barrier.
    straggler_deadline_sec: Optional[float] = None

    # jax profiler (SURVEY.md §5.1): device traces for TensorBoard
    profile_dir: Optional[str] = None

    def __post_init__(self):
        if self.client_num_per_round > self.client_num_in_total:
            raise ValueError(
                f"client_num_per_round ({self.client_num_per_round}) > "
                f"client_num_in_total ({self.client_num_in_total})"
            )
        if self.partition_method not in ("homo", "hetero", "hetero-fix", "given"):
            raise ValueError(f"unknown partition_method {self.partition_method!r}")
        if self.dtype not in ("float32", "bfloat16"):
            raise ValueError(f"dtype must be float32|bfloat16, got {self.dtype!r}")
        if self.device_data not in ("auto", "on", "off"):
            raise ValueError(f"device_data must be auto|on|off, got {self.device_data!r}")
        if self.bucket_groups < 1:
            raise ValueError(f"bucket_groups must be >= 1, got {self.bucket_groups}")
        if self.pack_lanes < 0:
            raise ValueError(f"pack_lanes must be >= 0, got {self.pack_lanes}")
        if self.packed_conv not in ("off", "blockdiag", "grouped", "auto"):
            raise ValueError(
                f"packed_conv must be off|blockdiag|grouped|auto, got "
                f"{self.packed_conv!r}")
        if self.cohort_policy not in ("uniform", "speed", "fair"):
            raise ValueError(
                f"cohort_policy must be uniform|speed|fair, got "
                f"{self.cohort_policy!r}")
        if self.stream_aggregate not in ("off", "deterministic", "arrival"):
            raise ValueError(
                f"stream_aggregate must be off|deterministic|arrival, got "
                f"{self.stream_aggregate!r}")
        if self.wire_retry_base_s <= 0:
            raise ValueError(
                f"wire_retry_base_s must be > 0, got {self.wire_retry_base_s}")
        if self.wire_retry_max < 1:
            raise ValueError(
                f"wire_retry_max must be >= 1, got {self.wire_retry_max}")
        if self.wire_inbox_cap < 0:
            raise ValueError(
                f"wire_inbox_cap must be >= 0 (0 = unbounded), got "
                f"{self.wire_inbox_cap}")
        if self.gateway_max_tenants < 1:
            raise ValueError(
                f"gateway_max_tenants must be >= 1, got "
                f"{self.gateway_max_tenants}")
        if self.gateway_tenant_workers < 0:
            raise ValueError(
                f"gateway_tenant_workers must be >= 0 (0 = unlimited), got "
                f"{self.gateway_tenant_workers}")
        if self.buffer_k < 1:
            raise ValueError(
                f"buffer_k must be >= 1, got {self.buffer_k}: a version "
                "emits every buffer_k folded contributions")
        if self.buffer_staleness_alpha < 0.0:
            raise ValueError(
                f"buffer_staleness_alpha must be >= 0, got "
                f"{self.buffer_staleness_alpha} (0 = no staleness decay)")
        if self.buffer_mode not in ("deterministic", "arrival"):
            raise ValueError(
                f"buffer_mode must be deterministic|arrival, got "
                f"{self.buffer_mode!r}")
        if self.cohort_chunk < 0:
            raise ValueError(
                f"cohort_chunk must be >= 0, got {self.cohort_chunk}")
        if self.cohort_chunk > 0 and self.stream_aggregate == "off":
            raise ValueError(
                "cohort_chunk > 0 needs stream_aggregate: sub-cohort chunks "
                "only exist to be folded into the streaming accumulator — "
                "set --stream_aggregate deterministic (or arrival)")
        if self.rounds_per_step < 1:
            raise ValueError(
                f"rounds_per_step must be >= 1, got {self.rounds_per_step}")
        if self.host_pipeline_depth < 0:
            raise ValueError(
                f"host_pipeline_depth must be >= 0, got {self.host_pipeline_depth}")
        if self.host_pipeline_workers < 0:
            raise ValueError(
                f"host_pipeline_workers must be >= 0, got {self.host_pipeline_workers}")
        if self.trace_buffer_events < 1:
            raise ValueError(
                f"trace_buffer_events must be >= 1, got {self.trace_buffer_events}")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(
                f"trace_sample_rate must be in [0, 1], got "
                f"{self.trace_sample_rate}")
        if not 0.0 < self.sketch_alpha < 0.5:
            raise ValueError(
                f"sketch_alpha must be in (0, 0.5), got {self.sketch_alpha}")
        if self.pulse_prometheus_dir and not self.pulse_path:
            raise ValueError(
                "pulse_prometheus_dir requires pulse_path: the Prometheus "
                "mirror re-renders the pulse snapshots, which only exist "
                "when the pulse stream is on")
        if self.health_loss_limit < 0:
            raise ValueError(
                f"health_loss_limit must be >= 0, got {self.health_loss_limit}")
        if self.health_stall_sec is not None and self.health_stall_sec <= 0:
            raise ValueError(
                f"health_stall_sec must be > 0, got {self.health_stall_sec}")
        if self.health_stale_spike < 0:
            raise ValueError(
                f"health_stale_spike must be >= 0, got {self.health_stale_spike}")
        if self.health_skew < 0:
            raise ValueError(
                f"health_skew must be >= 0, got {self.health_skew}")
        if self.flight_window < 1:
            raise ValueError(
                f"flight_window must be >= 1, got {self.flight_window}")
        _flight_allowed = {"escalate", "quarantine", "peer_dead", "manual"}
        _flight_toks = {t.strip() for t in (self.flight_on or "").split(",")
                        if t.strip()}
        if _flight_toks - _flight_allowed:
            raise ValueError(
                f"flight_on has unknown trigger(s) "
                f"{sorted(_flight_toks - _flight_allowed)}; allowed: "
                f"{sorted(_flight_allowed)}")
        if self.checkpoint_frequency < 1:
            raise ValueError(
                f"checkpoint_frequency must be >= 1, got {self.checkpoint_frequency}"
            )
        if not 0.0 <= self.failure_prob < 1.0:
            raise ValueError(
                f"failure_prob must be in [0, 1), got {self.failure_prob}"
            )
        if self.straggler_deadline_sec is not None and self.straggler_deadline_sec <= 0:
            raise ValueError(
                f"straggler_deadline_sec must be > 0 (got "
                f"{self.straggler_deadline_sec}); a non-positive deadline "
                "would mark every worker dead before it can train"
            )
        if self.rank is not None:
            if self.world_size is None or self.world_size < 2:
                raise ValueError(
                    "--rank requires --world_size >= 2 (1 server + >=1 worker)"
                )
            if not 0 <= self.rank < self.world_size:
                raise ValueError(
                    f"rank {self.rank} out of range for world_size {self.world_size}"
                )
        for f_ in ("chaos_drop", "chaos_dup", "chaos_reorder"):
            v = getattr(self, f_)
            if not 0.0 <= v < 1.0:
                raise ValueError(f"{f_} must be in [0, 1), got {v}")
        if self.chaos_delay_ms < 0:
            raise ValueError(
                f"chaos_delay_ms must be >= 0, got {self.chaos_delay_ms}")
        if (self.chaos_drop or self.chaos_dup or self.chaos_reorder) \
                and not self.wire_reliable:
            raise ValueError(
                "chaos drop/dup/reorder need wire_reliable=True: without the "
                "reliable layer a dropped message hangs the message-counting "
                "barriers and a duplicated upload double-aggregates"
            )
        if (self.chaos_crash_rank is None) != (self.chaos_crash_after is None):
            raise ValueError(
                "chaos_crash_rank and chaos_crash_after must be set together"
            )
        if self.chaos_crash_restart_s is not None:
            if self.chaos_crash_rank is None:
                raise ValueError(
                    "chaos_crash_restart_s needs chaos_crash_rank/"
                    "chaos_crash_after: a restart delay without a crash "
                    "fate has nothing to revive")
            if self.chaos_crash_restart_s <= 0:
                raise ValueError(
                    f"chaos_crash_restart_s must be > 0, got "
                    f"{self.chaos_crash_restart_s}")
        if self.health_version_lag < 0:
            raise ValueError(
                f"health_version_lag must be >= 0, got "
                f"{self.health_version_lag}")
        if self.lens not in ("off", "on"):
            raise ValueError(
                f"lens must be 'off' or 'on', got {self.lens!r}")
        if self.lens_topk < 1:
            raise ValueError(
                f"lens_topk must be >= 1, got {self.lens_topk}")
        if self.health_update_norm < 0:
            raise ValueError(
                f"health_update_norm must be >= 0, got "
                f"{self.health_update_norm}")
        if self.health_drift < 0:
            raise ValueError(
                f"health_drift must be >= 0, got {self.health_drift}")
        from fedml_tpu.core.compression import parse_codec

        parse_codec(self.wire_codec)   # raises on an unknown codec spec
        if self.wire_codec.startswith("topk") and not self.wire_delta:
            raise ValueError(
                "wire_codec='topk:..' sparsifies uploads destructively unless "
                "they are error-feedback deltas; set wire_delta=True (q8 and "
                "raw work with either mode)"
            )
        if self.ci:
            # CI fast path: shrink everything (reference fedavg_api.py:157-162).
            self.comm_round = min(self.comm_round, 2)
            self.epochs = min(self.epochs, 1)

    def replace(self, **kw) -> "FedConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FedConfig":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    @classmethod
    def from_yaml(cls, path: str) -> "FedConfig":
        if yaml is None:
            raise RuntimeError("pyyaml not available")
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})

    def to_yaml(self, path: str) -> None:
        if yaml is None:
            raise RuntimeError("pyyaml not available")
        with open(path, "w") as f:
            yaml.safe_dump(self.to_dict(), f)


def add_args(parser: Optional[argparse.ArgumentParser] = None) -> argparse.ArgumentParser:
    """Argparse bridge exposing the reference's flag names
    (main_fedavg.py:48-120) so launch scripts translate 1:1."""
    p = parser or argparse.ArgumentParser(description="fedml_tpu experiment")
    defaults = FedConfig()
    p.add_argument("--model", type=str, default=defaults.model)
    p.add_argument("--dataset", type=str, default=defaults.dataset)
    p.add_argument("--data_dir", type=str, default=defaults.data_dir)
    p.add_argument("--partition_method", type=str, default=defaults.partition_method)
    p.add_argument("--partition_alpha", type=float, default=defaults.partition_alpha)
    p.add_argument("--client_num_in_total", type=int, default=defaults.client_num_in_total)
    p.add_argument("--client_num_per_round", type=int, default=defaults.client_num_per_round)
    p.add_argument("--comm_round", type=int, default=defaults.comm_round)
    p.add_argument("--group_num", type=int, default=defaults.group_num)
    p.add_argument("--group_comm_round", type=int, default=defaults.group_comm_round)
    p.add_argument("--unrolled", type=int, default=defaults.unrolled)
    p.add_argument("--batch_size", type=int, default=defaults.batch_size)
    p.add_argument("--client_optimizer", type=str, default=defaults.client_optimizer)
    p.add_argument("--lr", type=float, default=defaults.lr)
    p.add_argument("--wd", type=float, default=defaults.wd)
    p.add_argument("--momentum", type=float, default=defaults.momentum)
    p.add_argument("--epochs", type=int, default=defaults.epochs)
    p.add_argument("--server_optimizer", type=str, default=defaults.server_optimizer)
    p.add_argument("--server_lr", type=float, default=defaults.server_lr)
    p.add_argument("--server_momentum", type=float, default=defaults.server_momentum)
    p.add_argument("--fedprox_mu", type=float, default=defaults.fedprox_mu)
    p.add_argument("--norm_bound", type=float, default=None)
    p.add_argument("--stddev", type=float, default=None)
    p.add_argument("--temperature", type=float, default=defaults.temperature)
    p.add_argument("--alpha_distill", type=float, default=defaults.alpha_distill)
    p.add_argument("--model_client", type=str, default=defaults.model_client)
    p.add_argument("--model_server", type=str, default=defaults.model_server)
    p.add_argument("--epochs_server", type=int, default=defaults.epochs_server)
    p.add_argument("--backend", type=str, default=defaults.backend)
    p.add_argument("--rank", type=int, default=None,
                   help="start ONLY this rank as its own OS process (0=server)")
    p.add_argument("--world_size", type=int, default=None,
                   help="total ranks (1 server + N workers) for --rank mode")
    p.add_argument("--grpc_ipconfig_path", type=str, default=None,
                   help="rank->IP csv (reference grpc_ipconfig.csv); default loopback")
    p.add_argument("--grpc_base_port", type=int, default=defaults.grpc_base_port)
    p.add_argument("--frequency_of_the_test", type=int, default=defaults.frequency_of_the_test)
    # reference-parity flag: its JSON wire format lives in
    # core/serialization.tree_to_jsonable and is superseded by --wire_codec;
    # kept so reference launch scripts parse unchanged.
    p.add_argument("--is_mobile", type=int, default=defaults.is_mobile)  # fedlint: disable=config-flag-drift
    p.add_argument("--seed", type=int, default=defaults.seed)
    p.add_argument("--ci", type=int, default=defaults.ci)
    p.add_argument("--dtype", type=str, default=defaults.dtype)
    p.add_argument("--device_data", type=str, default=defaults.device_data,
                   choices=("auto", "on", "off"))
    p.add_argument("--device_data_max_bytes", type=int,
                   default=defaults.device_data_max_bytes)
    p.add_argument("--bucket_quantum_batches", type=int,
                   default=defaults.bucket_quantum_batches)
    p.add_argument("--bucket_groups", type=int, default=defaults.bucket_groups)
    p.add_argument("--rounds_per_step", type=int,
                   default=defaults.rounds_per_step,
                   help="fold H cross-silo rounds into one scanned program "
                        "(docs/mfu_experiments.md H7); 1 = off")
    p.add_argument("--pack_lanes", type=int, default=defaults.pack_lanes,
                   help="pack the cohort into N scan lanes (0 = off)")
    p.add_argument("--packed_conv", type=str, default=defaults.packed_conv,
                   choices=("off", "blockdiag", "grouped", "auto"),
                   help="fedpack conv lowering for the packed lanes: one "
                        "block-diagonal GEMM / grouped conv across the K "
                        "lanes instead of the per-lane vmap (off = vmap); "
                        "auto = fedplan picks per conv stage from the "
                        "static roofline table (obs/plan.py)")
    p.add_argument("--host_pipeline_depth", type=int,
                   default=defaults.host_pipeline_depth,
                   help="prefetch this many future rounds' cohorts on "
                        "background threads (host round paths; 0 = serial)")
    p.add_argument("--host_pipeline_workers", type=int,
                   default=defaults.host_pipeline_workers,
                   help="threads fanning one cohort's materialization out "
                        "over its clients (0 = auto)")
    p.add_argument("--cohort_policy", type=str,
                   default=defaults.cohort_policy,
                   choices=("uniform", "speed", "fair"),
                   help="fedsched cohort selection: uniform draw (default, "
                        "bit-identical), speed packing from the profiler's "
                        "EMA train-ms, or fairness-bounded speed packing")
    p.add_argument("--stream_aggregate", type=str,
                   default=defaults.stream_aggregate,
                   choices=("off", "deterministic", "arrival"),
                   help="streaming server-side aggregation: fold client "
                        "updates into a running weighted accumulator (O(1) "
                        "memory in cohort size) in fixed plan order "
                        "(deterministic) or strictly on arrival")
    p.add_argument("--buffer_k", type=int, default=defaults.buffer_k,
                   help="fedbuff: emit a model version every K folded "
                        "contributions (async buffered aggregation)")
    p.add_argument("--buffer_staleness_alpha", type=float,
                   default=defaults.buffer_staleness_alpha,
                   help="fedbuff staleness decay: fold weight = "
                        "n * (1 + staleness)^-alpha (0 = no decay)")
    p.add_argument("--buffer_mode", type=str, default=defaults.buffer_mode,
                   choices=("deterministic", "arrival"),
                   help="fedbuff fold order: canonical (tag, worker) "
                        "frontier — bit-identical replayable from (seed, "
                        "chaos_seed) — or strictly on arrival (fast path)")
    p.add_argument("--cohort_chunk", type=int, default=defaults.cohort_chunk,
                   help="stream the host round in sub-cohorts of this many "
                        "clients through the accumulator (0 = whole cohort; "
                        "requires --stream_aggregate)")
    p.add_argument("--scan_unroll", type=int, default=defaults.scan_unroll)
    p.add_argument("--cohort_vmap_width", type=int,
                   default=defaults.cohort_vmap_width)
    p.add_argument("--wire_codec", type=str, default=defaults.wire_codec,
                   help="edge payload compression: raw | q8 | topk:<ratio>")
    p.add_argument("--wire_delta", type=lambda s: bool(int(s)),
                   default=defaults.wire_delta,
                   help="edge FedAvg uploads error-feedback deltas (0|1)")
    p.add_argument("--wire_reliable", type=lambda s: bool(int(s)),
                   default=defaults.wire_reliable,
                   help="ACK/retransmit + dedup wire layer (0|1)")
    p.add_argument("--wire_retry_base_s", type=float,
                   default=defaults.wire_retry_base_s,
                   help="reliable-layer backoff base (cap = 20x base)")
    p.add_argument("--wire_retry_max", type=int,
                   default=defaults.wire_retry_max,
                   help="retransmits before a message gives up (the "
                        "dead-peer detection budget)")
    p.add_argument("--wire_inbox_cap", type=int,
                   default=defaults.wire_inbox_cap,
                   help="bounded inbox / gateway lane depth (0 = unbounded; "
                        "gateway lanes answer WIRE_BUSY over the cap)")
    p.add_argument("--gateway_max_tenants", type=int,
                   default=defaults.gateway_max_tenants,
                   help="concurrent federations one gateway admits (excess "
                        "gets a typed NACK)")
    p.add_argument("--gateway_tenant_workers", type=int,
                   default=defaults.gateway_tenant_workers,
                   help="per-tenant worker quota at the gateway (0 = "
                        "unlimited)")
    p.add_argument("--chaos_seed", type=int, default=defaults.chaos_seed)
    p.add_argument("--chaos_drop", type=float, default=defaults.chaos_drop,
                   help="P(drop) per transmission (needs --wire_reliable 1)")
    p.add_argument("--chaos_dup", type=float, default=defaults.chaos_dup,
                   help="P(duplicate) per transmission")
    p.add_argument("--chaos_delay_ms", type=float,
                   default=defaults.chaos_delay_ms,
                   help="max per-message injected latency in ms")
    p.add_argument("--chaos_reorder", type=float,
                   default=defaults.chaos_reorder,
                   help="P(hold a message until the next send overtakes it)")
    p.add_argument("--chaos_crash_rank", type=int, default=None,
                   help="crash-stop this rank after --chaos_crash_after sends")
    p.add_argument("--chaos_crash_after", type=int, default=None)
    p.add_argument("--chaos_crash_restart_s", type=float, default=None,
                   help="crash_restart fate: revive the crash-stopped rank "
                        "after this many seconds (None = crash is final)")
    p.add_argument("--trace_dir", type=str, default=None,
                   help="write per-rank span traces (fedml_tpu/obs) here; "
                        "analyze with tools/trace_report.py")
    p.add_argument("--trace_buffer_events", type=int,
                   default=defaults.trace_buffer_events,
                   help="per-rank trace ring-buffer bound (events)")
    p.add_argument("--trace_sample_rate", type=float,
                   default=defaults.trace_sample_rate,
                   help="keep this fraction of round span trees — "
                        "deterministic head sampling keyed on (seed, "
                        "round); sampled-out rounds still feed sketches "
                        "(1.0 = trace every round)")
    p.add_argument("--sketch_alpha", type=float,
                   default=defaults.sketch_alpha,
                   help="fedsketch relative accuracy for the percentile "
                        "lanes (smaller = more buckets)")
    p.add_argument("--pulse_path", type=str, default=None,
                   help="fedpulse live telemetry: append one atomic JSON "
                        "snapshot per round boundary to this file; tail it "
                        "with tools/fedtop.py (None = plane off)")
    p.add_argument("--pulse_prometheus_dir", type=str, default=None,
                   help="also mirror each pulse snapshot as Prometheus "
                        "textfile gauges (<dir>/fedpulse.prom)")
    p.add_argument("--health_loss_limit", type=float,
                   default=defaults.health_loss_limit,
                   help="watchdog: loss above this is divergent_loss "
                        "(0 = rule off; NaN loss is always critical)")
    p.add_argument("--health_stall_sec", type=float, default=None,
                   help="watchdog: a round wall beyond this many seconds "
                        "is a round_stall (None = rule off)")
    p.add_argument("--health_stale_spike", type=int,
                   default=defaults.health_stale_spike,
                   help="watchdog: stale_uploads growth per round that "
                        "counts as a spike (0 = rule off)")
    p.add_argument("--health_skew", type=float, default=defaults.health_skew,
                   help="watchdog: p95/p50 EMA train-ms ratio flagged as "
                        "straggler skew (0 = rule off)")
    p.add_argument("--health_version_lag", type=float,
                   default=defaults.health_version_lag,
                   help="watchdog: per-round staleness-sketch delta p99 "
                        "(versions behind) that warns; monotonic growth "
                        "escalates to critical (0 = rule off)")
    p.add_argument("--health_update_norm", type=float,
                   default=defaults.health_update_norm,
                   help="watchdog (fedlens): per-round update-norm sketch "
                        "delta p99 that warns with suspect client ids "
                        "(0 = rule off; needs --lens on)")
    p.add_argument("--health_drift", type=float,
                   default=defaults.health_drift,
                   help="watchdog (fedlens): per-round drift sketch delta "
                        "p99 (1 - cosine vs aggregate) that warns with "
                        "suspect client ids (0 = rule off; needs --lens on)")
    p.add_argument("--health_escalate", type=lambda s: bool(int(s)),
                   default=defaults.health_escalate,
                   help="raise FederationHealthError on critical health "
                        "events (0|1; snapshot is written first)")
    p.add_argument("--lens", type=str, choices=("off", "on"),
                   default=defaults.lens,
                   help="fedlens in-program learning-signal telemetry: "
                        "per-client update norm / loss delta / alignment "
                        "computed inside the round programs (output-only; "
                        "aggregation bit-identical to off)")
    p.add_argument("--lens_topk", type=int, default=defaults.lens_topk,
                   help="ranked suspect client ids carried by each "
                        "learning block / attributed watchdog event")
    p.add_argument("--flight_dir", type=str, default=None,
                   help="fedflight black-box recorder: retain the last "
                        "--flight_window rounds at FULL rate and dump a "
                        "self-contained incident-<id>/ bundle here on "
                        "trigger (watchdog escalation before the raise, "
                        "gateway quarantine, peer_dead, SIGUSR2); analyze "
                        "with tools/fedpost.py (None = recorder off)")
    p.add_argument("--flight_window", type=int,
                   default=defaults.flight_window,
                   help="rounds of full-rate retrospective capture the "
                        "flight recorder retains per rank")
    p.add_argument("--flight_on", type=str, default=defaults.flight_on,
                   help="comma list arming flight triggers: escalate, "
                        "quarantine, peer_dead, manual")
    p.add_argument("--trace_device_sampler", type=lambda s: bool(int(s)),
                   default=defaults.trace_device_sampler,
                   help="sample per-device memory at round boundaries into "
                        "the trace's device lane (0|1; traced runs only)")
    p.add_argument("--cost_attribution", type=lambda s: bool(int(s)),
                   default=defaults.cost_attribution,
                   help="fedcost static roofline attribution of every built "
                        "round program (0|1): per-op GEMM/lane-fill table "
                        "via obs/cost; report with tools/trace_report.py or "
                        "tools/roofline_report.py")
    p.add_argument("--run_name", type=str, default=defaults.run_name)
    p.add_argument("--checkpoint_dir", type=str, default=None)
    p.add_argument("--checkpoint_frequency", type=int, default=defaults.checkpoint_frequency)
    p.add_argument("--resume_from", type=str, default=None)
    p.add_argument("--failure_prob", type=float, default=defaults.failure_prob)
    p.add_argument("--straggler_deadline_sec", type=float, default=None,
                   help="edge rounds: aggregate the received subset after "
                        "this many seconds instead of waiting forever")
    p.add_argument("--profile_dir", type=str, default=None)
    p.add_argument("--config_yaml", type=str, default=None, help="optional YAML overriding flags")
    return p


def config_from_args(args: argparse.Namespace) -> FedConfig:
    d = vars(args).copy()
    yaml_path = d.pop("config_yaml", None)
    cfg = FedConfig.from_dict(d)
    if yaml_path:
        if yaml is None:
            raise RuntimeError("pyyaml not available but --config_yaml was passed")
        base = cfg.to_dict()
        with open(yaml_path) as f:
            base.update(yaml.safe_load(f) or {})
        cfg = FedConfig.from_dict(base)
    return cfg
