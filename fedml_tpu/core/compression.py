"""Lossy wire codecs for bandwidth-constrained edge federation.

Reference counterpart: the ``--is_mobile 1`` path ships models as JSON
nested lists (fedavg/utils.py:7-16, FedAvgServerManager.py:36-37) — a
format conversion that INFLATES bytes. Here the edge transport can
genuinely compress pytree payloads:

- ``"q8"`` — per-leaf affine uint8 quantization of float leaves: 4x
  smaller than f32, max error = half a quantization step of the leaf's
  value range.
- ``"topk:R"`` — magnitude top-k sparsification keeping fraction R of
  each float leaf (int32 indices + f32 values). Meant for UPDATE/delta
  payloads (pair with error feedback at the sender); destructive on full
  weight tensors.
- ``"raw"`` — exact passthrough (the default everywhere).

Frames are self-describing (codec + per-leaf metadata ride the JSON
header), so decode needs no out-of-band configuration and raw/compressed
frames can mix on one connection. Integer/bool leaves and tiny leaves
(< 64 elements: biases, BN scales — negligible bytes, outsized error
impact) always ride raw inside a lossy frame.
"""

from __future__ import annotations

import json
from typing import Any

import jax
import numpy as np

from fedml_tpu.core.serialization import (
    _treedef_from_json,
    _treedef_to_json,
    frame_pack,
    frame_unpack,
)

MAGIC = b"FTPC1"

#: leaves smaller than this are stored raw even under a lossy codec
MIN_LOSSY_ELEMENTS = 64


def parse_codec(codec: str) -> tuple[str, float]:
    """'raw' -> ('raw', 0), 'q8' -> ('q8', 0), 'topk:0.05' -> ('topk', .05)."""
    if codec == "raw" or codec == "q8":
        return codec, 0.0
    if codec.startswith("topk:"):
        ratio = float(codec.split(":", 1)[1])
        if not 0.0 < ratio <= 1.0:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")
        return "topk", ratio
    raise ValueError(f"unknown wire codec {codec!r} (raw | q8 | topk:<ratio>)")


def _encode_leaf(x: np.ndarray, kind: str, ratio: float):
    """-> (meta dict, payload bytes). Lossy kinds apply to float leaves of
    >= MIN_LOSSY_ELEMENTS elements; everything else stores raw."""
    lossy = (kind != "raw" and np.issubdtype(x.dtype, np.floating)
             and x.size >= MIN_LOSSY_ELEMENTS)
    meta = {"shape": list(x.shape), "dtype": x.dtype.name}
    if not lossy:
        meta["enc"] = "raw"
        return meta, np.ascontiguousarray(x).tobytes()
    if kind == "q8":
        xf = np.asarray(x, np.float32)
        lo = float(xf.min())
        hi = float(xf.max())
        scale = (hi - lo) / 255.0 or 1.0
        q = np.rint((xf - lo) / scale).astype(np.uint8)
        meta.update(enc="q8", lo=lo, scale=scale)
        return meta, q.tobytes()
    # topk: keep the largest-|value| fraction of entries, exactly
    flat = np.asarray(x, np.float32).reshape(-1)
    k = max(1, int(round(ratio * flat.size)))
    idx = np.argpartition(np.abs(flat), -k)[-k:].astype(np.int32)
    idx.sort()
    meta.update(enc="topk", k=int(k))
    return meta, idx.tobytes() + flat[idx].tobytes()


def _decode_leaf(meta: dict, buf: bytes) -> np.ndarray:
    shape = tuple(meta["shape"])
    dtype = np.dtype(meta["dtype"])
    enc = meta["enc"]
    if enc == "raw":
        # copy: frombuffer returns a read-only view that pins the whole
        # frame (all blobs) alive and breaks in-place consumers
        return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    if enc == "q8":
        q = np.frombuffer(buf, dtype=np.uint8).astype(np.float32)
        x = meta["lo"] + q * meta["scale"]
        return x.astype(dtype).reshape(shape)
    if enc == "topk":
        k = meta["k"]
        idx = np.frombuffer(buf[: 4 * k], dtype=np.int32)
        vals = np.frombuffer(buf[4 * k:], dtype=np.float32)
        out = np.zeros(int(np.prod(shape)) if shape else 1, np.float32)
        out[idx] = vals
        return out.astype(dtype).reshape(shape)
    raise ValueError(f"unknown leaf encoding {enc!r}")


def encode_tree(tree: Any, codec: str) -> bytes:
    """Serialize a pytree of arrays under ``codec``. The frame carries the
    codec and per-leaf encodings, so :func:`decode_tree` needs nothing else."""
    kind, ratio = parse_codec(codec)
    leaves, treedef = jax.tree.flatten(tree)
    metas, payloads = [], []
    for leaf in leaves:
        m, b = _encode_leaf(np.asarray(leaf), kind, ratio)
        metas.append(m)
        payloads.append(b)
    header = {
        "codec": codec,
        "treedef": _treedef_to_json(treedef),
        "leaves": metas,
        "lens": [len(b) for b in payloads],
    }
    return frame_pack(MAGIC, header, *payloads)


def decode_tree(buf: bytes) -> Any:
    header, off = frame_unpack(MAGIC, buf)
    leaves = []
    for meta, n in zip(header["leaves"], header["lens"]):
        leaves.append(_decode_leaf(meta, buf[off: off + n]))
        off += n
    return jax.tree.unflatten(_treedef_from_json(header["treedef"]), leaves)


def is_compressed_frame(buf: bytes) -> bool:
    return buf[: len(MAGIC)] == MAGIC
