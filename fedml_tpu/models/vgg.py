"""VGG (reference fedml_api/model/cv/vgg.py), CIFAR-sized, NHWC."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model

_CFG: dict[str, Sequence] = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"),
    "vgg19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence
    output_dim: int = 10
    use_bn: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME", use_bias=not self.use_bn, dtype=self.dtype)(x)
                if self.use_bn:
                    x = nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))


def _bundle(name: str, output_dim: int, dtype):
    return ModelBundle(
        name=name,
        module=VGG(_CFG[name], output_dim, dtype=dtype),
        input_shape=(32, 32, 3),
        has_batch_stats=True,
    )


@register_model("vgg11")
def _vgg11(output_dim: int, dtype=jnp.float32, **_):
    return _bundle("vgg11", output_dim, dtype)


@register_model("vgg16")
def _vgg16(output_dim: int, dtype=jnp.float32, **_):
    return _bundle("vgg16", output_dim, dtype)


@register_model("vgg19")
def _vgg19(output_dim: int, dtype=jnp.float32, **_):
    return _bundle("vgg19", output_dim, dtype)
