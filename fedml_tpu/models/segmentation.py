"""Semantic-segmentation models (FedSeg).

Counterpart of the reference's DeepLabV3+-style segmentation stack used by
fedml_api/distributed/fedseg/ (trainers feed image batches, take per-pixel
logits; metrics via the confusion-matrix Evaluator, fedseg/utils.py:246+).

TPU design: NHWC throughout; the decoder upsamples with
``jax.image.resize`` (bilinear) which lowers to dense MXU-friendly ops;
atrous (dilated) convs express the ASPP context module without dynamic
shapes. Two registered entries:

- ``deeplab_lite`` — stride-8 residual encoder + ASPP-lite + 1x1 classifier
  + bilinear upsample (DeepLabV3 recipe, compact),
- ``unet`` — classic encoder/decoder with skip concats.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model
from fedml_tpu.models.resnet import BasicBlock


class ASPPLite(nn.Module):
    """Parallel atrous branches + image-level pooling, fused by 1x1 conv."""

    channels: int
    rates: Sequence[int] = (1, 3, 6)

    @nn.compact
    def __call__(self, x, train: bool = False):
        branches = [
            nn.Conv(self.channels, (1, 1), use_bias=False)(x)
        ]
        for r in self.rates[1:]:
            branches.append(
                nn.Conv(self.channels, (3, 3), padding="SAME",
                        kernel_dilation=(r, r), use_bias=False)(x)
            )
        # image-level context
        pooled = jnp.mean(x, axis=(1, 2), keepdims=True)
        pooled = nn.Conv(self.channels, (1, 1), use_bias=False)(pooled)
        pooled = jnp.broadcast_to(pooled, x.shape[:3] + (self.channels,))
        y = jnp.concatenate(branches + [pooled], axis=-1)
        y = nn.Conv(self.channels, (1, 1), use_bias=False)(y)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9)(y)
        return nn.relu(y)


class DeepLabLite(nn.Module):
    """Stride-8 encoder (residual blocks) + ASPP + upsampled classifier."""

    output_dim: int
    width: int = 32
    blocks_per_stage: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, w = x.shape[1], x.shape[2]
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=self.dtype)(x))
        for stage, mult in enumerate((1, 2, 4)):
            for block in range(self.blocks_per_stage):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(self.width * mult, strides, dtype=self.dtype)(x, train=train)
        x = ASPPLite(self.width * 4)(x, train)
        logits = nn.Conv(self.output_dim, (1, 1), dtype=jnp.float32)(x.astype(jnp.float32))
        return jax.image.resize(logits, (logits.shape[0], h, w, self.output_dim), "bilinear")


class UNet(nn.Module):
    output_dim: int
    width: int = 16
    depth: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        def conv_block(y, c):
            for _ in range(2):
                y = nn.Conv(c, (3, 3), padding="SAME", use_bias=False)(y)
                y = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9)(y))
            return y

        skips = []
        c = self.width
        for _ in range(self.depth):
            x = conv_block(x, c)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            c *= 2
        x = conv_block(x, c)
        for skip in reversed(skips):
            c //= 2
            x = jax.image.resize(
                x, (x.shape[0], skip.shape[1], skip.shape[2], x.shape[3]), "bilinear"
            )
            x = jnp.concatenate([x, skip], axis=-1)
            x = conv_block(x, c)
        return nn.Conv(self.output_dim, (1, 1))(x)


@register_model("deeplab_lite")
def _deeplab(output_dim: int, input_shape=(32, 32, 3), dtype=jnp.float32, **_):
    return ModelBundle(
        name="deeplab_lite",
        module=DeepLabLite(output_dim, dtype=dtype),
        input_shape=tuple(input_shape),
        task="segmentation",
        has_batch_stats=True,
    )


@register_model("unet")
def _unet(output_dim: int, input_shape=(32, 32, 3), **_):
    return ModelBundle(
        name="unet",
        module=UNet(output_dim),
        input_shape=tuple(input_shape),
        task="segmentation",
        has_batch_stats=True,
    )
