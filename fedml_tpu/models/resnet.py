"""CIFAR ResNets (ResNet-56/110) — the cross-silo flagship.

Counterpart of reference fedml_api/model/cv/resnet.py (resnet56 factory):
3 stages of BasicBlocks (depth = 6n+2), widths 16/32/64, BatchNorm + ReLU,
option A/B shortcut = 1x1 conv projection when shape changes.

TPU notes: NHWC layout, bf16-friendly (params fp32, compute dtype pluggable),
BatchNorm uses flax 'batch_stats' collection which the federated trainers
average like any other leaf (FedAvg averages running stats too).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32
    bn_axis: Any = None  # mapped-axis name for cross-device sync-BN
    use_norm: bool = True  # False: perf-experiment variant without BN
    bn_impl: str = "xla"   # "pallas": fused stats+normalize(+relu) kernel

    def _norms(self, train: bool):
        """norm(fuse_relu) -> module; fuse_relu folds the following ReLU
        into the norm (only the pallas impl actually fuses it)."""
        if not self.use_norm:
            return lambda fuse_relu=False: (
                nn.relu if fuse_relu else (lambda y: y))
        if self.bn_impl == "pallas" and self.bn_axis is None:
            from fedml_tpu.models.norm import PallasBatchNorm

            return lambda fuse_relu=False: PallasBatchNorm(
                use_running_average=not train, momentum=0.9,
                dtype=self.dtype, fuse_relu=fuse_relu)

        def make(fuse_relu=False):
            bn = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                              dtype=self.dtype, axis_name=self.bn_axis)
            return (lambda y: nn.relu(bn(y))) if fuse_relu else bn

        return make

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = self._norms(train)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides), padding="SAME")(x)
        y = norm(fuse_relu=True)(y)
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides))(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    """depth = 6n+2; blocks_per_stage = n.

    ``widths`` defaults to the standard 16/32/64; the perf-experiment
    variants (docs/mfu_experiments.md) override it to isolate how MXU lane
    utilization scales with channel count on TPU."""

    blocks_per_stage: int
    output_dim: int = 10
    dtype: Any = jnp.float32
    bn_axis: Any = None  # sync-BN over this mapped axis (batchnorm_utils.py counterpart)
    widths: tuple = (16, 32, 64)
    use_norm: bool = True
    bn_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        if self.use_norm:
            if self.bn_impl == "pallas" and self.bn_axis is None:
                from fedml_tpu.models.norm import PallasBatchNorm

                x = PallasBatchNorm(use_running_average=not train,
                                    momentum=0.9, dtype=self.dtype,
                                    fuse_relu=True)(x)
            else:
                x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=self.dtype, axis_name=self.bn_axis)(x)
                x = nn.relu(x)
        else:
            x = nn.relu(x)
        for stage, filters in enumerate(self.widths):
            for block in range(self.blocks_per_stage):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(filters, strides, dtype=self.dtype,
                               bn_axis=self.bn_axis,
                               use_norm=self.use_norm,
                               bn_impl=self.bn_impl)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))


def _make(depth: int, output_dim: int, dtype=jnp.float32, bn_axis=None,
          bn_impl="xla") -> CifarResNet:
    assert (depth - 2) % 6 == 0, "CIFAR ResNet depth must be 6n+2"
    return CifarResNet((depth - 2) // 6, output_dim, dtype=dtype,
                       bn_axis=bn_axis, bn_impl=bn_impl)


@register_model("resnet56")
def _resnet56(output_dim: int, dtype=jnp.float32, bn_axis=None, bn_impl="xla", **_):
    return ModelBundle(
        name="resnet56",
        module=_make(56, output_dim, dtype, bn_axis, bn_impl),
        input_shape=(32, 32, 3),
        has_batch_stats=True,
    )


@register_model("resnet110")
def _resnet110(output_dim: int, dtype=jnp.float32, bn_axis=None, bn_impl="xla", **_):
    return ModelBundle(
        name="resnet110",
        module=_make(110, output_dim, dtype, bn_axis, bn_impl),
        input_shape=(32, 32, 3),
        has_batch_stats=True,
    )


@register_model("resnet20")
def _resnet20(output_dim: int, dtype=jnp.float32, bn_axis=None, bn_impl="xla", **_):
    """Small variant for CI/tests (not in the reference zoo but same family)."""
    return ModelBundle(
        name="resnet20",
        module=_make(20, output_dim, dtype, bn_axis, bn_impl),
        input_shape=(32, 32, 3),
        has_batch_stats=True,
    )


def _register_width_variant(name: str, widths: tuple):
    """Perf-experiment variants (docs/mfu_experiments.md): same depth-56
    topology with uniform channel widths, used to measure how flagship MFU
    scales with MXU lane occupancy (Cout/128). Not part of the reference
    zoo — benchmarking instruments, not training recipes."""

    @register_model(name)
    def _variant(output_dim: int, dtype=jnp.float32, bn_axis=None, **_):
        return ModelBundle(
            name=name,
            module=CifarResNet(9, output_dim, dtype=dtype, bn_axis=bn_axis,
                               widths=widths),
            input_shape=(32, 32, 3),
            has_batch_stats=True,
        )
    return _variant


_register_width_variant("resnet56_w64", (64, 64, 64))
_register_width_variant("resnet56_w128", (128, 128, 128))


@register_model("resnet56_nonorm")
def _resnet56_nonorm(output_dim: int, dtype=jnp.float32, **_):
    """Perf-experiment variant: standard widths, NO BatchNorm anywhere —
    isolates normalization's share of the flagship step time (BN is a
    spatial reduction XLA cannot fuse into the convs)."""
    return ModelBundle(
        name="resnet56_nonorm",
        module=CifarResNet(9, output_dim, dtype=dtype, use_norm=False),
        input_shape=(32, 32, 3),
        has_batch_stats=False,
    )
