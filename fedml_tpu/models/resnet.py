"""CIFAR ResNets (ResNet-56/110) — the cross-silo flagship.

Counterpart of reference fedml_api/model/cv/resnet.py (resnet56 factory):
3 stages of BasicBlocks (depth = 6n+2), widths 16/32/64, BatchNorm + ReLU,
option A/B shortcut = 1x1 conv projection when shape changes.

TPU notes: NHWC layout, bf16-friendly (params fp32, compute dtype pluggable),
BatchNorm uses flax 'batch_stats' collection which the federated trainers
average like any other leaf (FedAvg averages running stats too).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32
    bn_axis: Any = None  # mapped-axis name for cross-device sync-BN
    use_norm: bool = True  # False: perf-experiment variant without BN
    bn_impl: str = "xla"   # "pallas": fused stats+normalize(+relu) kernel
    conv_impl: str = "xla"  # "lanes": spatial-in-lanes Pallas conv
    #                         (ops/conv_lanes.py); "packed": fedpack client-
    #                         packed convs on lane-major [K,N,H,W,C] input
    #                         (ops/packed_conv.py)
    packed_impl: Any = "blockdiag"  # packed lowering name (blockdiag |
    #                                 grouped) or a per-stage fedplan
    #                                 LoweringPlan (obs/plan.py)
    hw: tuple = (0, 0)      # static input (H, W) — lanes layout only

    def _norms(self, train: bool, axis: int = -1):
        """norm(fuse_relu) -> module; fuse_relu folds the following ReLU
        into the norm (only the pallas impl actually fuses it)."""
        if not self.use_norm:
            return lambda fuse_relu=False: (
                nn.relu if fuse_relu else (lambda y: y))
        if self.bn_impl == "pallas" and self.bn_axis is None and axis == -1:
            from fedml_tpu.models.norm import PallasBatchNorm

            return lambda fuse_relu=False: PallasBatchNorm(
                use_running_average=not train, momentum=0.9,
                dtype=self.dtype, fuse_relu=fuse_relu)

        def make(fuse_relu=False):
            bn = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                              dtype=self.dtype, axis=axis,
                              axis_name=self.bn_axis)
            return (lambda y: nn.relu(bn(y))) if fuse_relu else bn

        return make

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.conv_impl == "lanes":
            return self._call_lanes(x, train)
        if self.conv_impl == "packed":
            return self._call_packed(x, train)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = self._norms(train)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides), padding="SAME")(x)
        y = norm(fuse_relu=True)(y)
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides))(x)
            residual = norm()(residual)
        return nn.relu(y + residual)

    def _call_lanes(self, x, train: bool):
        """Lanes-layout body ([N, C, H*W], pixels in the lane dim): same
        submodule call order as the NHWC body — the LanesConv class is
        named 'Conv' — so the parameter pytree is identical."""
        from fedml_tpu.ops.conv_lanes import Conv as LanesConv

        h, w = self.hw
        s = self.strides
        norm = self._norms(train, axis=1)
        residual = x
        y = LanesConv(self.filters, hw=(h, w), strides=s, dtype=self.dtype)(x)
        y = norm(fuse_relu=True)(y)
        y = LanesConv(self.filters, hw=(h // s, w // s), dtype=self.dtype)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = LanesConv(self.filters, hw=(h, w), kernel_size=1,
                                 strides=s, dtype=self.dtype)(x)
            residual = norm()(residual)
        return nn.relu(y + residual)

    def _call_packed(self, x, train: bool):
        """fedpack body (x [K, N, H, W, C], lane-major): same submodule
        call order as the NHWC body — the packed classes are named 'Conv'/
        'BatchNorm' — so the parameter pytree is the standard tree with a
        leading K (lane) axis on every leaf (ops/packed_conv contract)."""
        from fedml_tpu.ops.packed_conv import BatchNorm as PBatchNorm
        from fedml_tpu.ops.packed_conv import Conv as PConv

        conv = partial(PConv, use_bias=False, impl=self.packed_impl,
                       dtype=self.dtype)
        if self.use_norm:
            norm = lambda: PBatchNorm(use_running_average=not train,
                                      momentum=0.9, dtype=self.dtype)
        else:
            norm = lambda: (lambda y: y)
        residual = x
        y = conv(self.filters, 3, self.strides)(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, 3)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, 1, self.strides)(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    """depth = 6n+2; blocks_per_stage = n.

    ``widths`` defaults to the standard 16/32/64; the perf-experiment
    variants (docs/mfu_experiments.md) override it to isolate how MXU lane
    utilization scales with channel count on TPU."""

    blocks_per_stage: int
    output_dim: int = 10
    dtype: Any = jnp.float32
    bn_axis: Any = None  # sync-BN over this mapped axis (batchnorm_utils.py counterpart)
    widths: tuple = (16, 32, 64)
    use_norm: bool = True
    bn_impl: str = "xla"
    conv_impl: str = "xla"  # "lanes": Pallas spatial-in-lanes convs for the
    #                         C<=32 stages (docs/mfu_experiments.md H6);
    #                         "packed": fedpack client-packed convs over a
    #                         leading lane axis (ops/packed_conv.py)
    packed_impl: Any = "blockdiag"  # name or per-stage LoweringPlan

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.conv_impl == "packed":
            return self._call_packed(x, train)
        x = x.astype(self.dtype)
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        if self.use_norm:
            if self.bn_impl == "pallas" and self.bn_axis is None:
                from fedml_tpu.models.norm import PallasBatchNorm

                x = PallasBatchNorm(use_running_average=not train,
                                    momentum=0.9, dtype=self.dtype,
                                    fuse_relu=True)(x)
            else:
                x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=self.dtype, axis_name=self.bn_axis)(x)
                x = nn.relu(x)
        else:
            x = nn.relu(x)
        # lanes layout: stages at C<=32 run pixels-in-lanes Pallas convs;
        # wider stages convert back to NHWC and keep XLA's conv + fusion
        # (at C>=64 the two MXU mappings cost the same passes).
        lanes = self.conv_impl == "lanes"
        h, w = int(x.shape[1]), int(x.shape[2])
        in_lanes = False
        if lanes:
            from fedml_tpu.ops.conv_lanes import from_lanes, to_lanes
        for stage, filters in enumerate(self.widths):
            stage_lanes = lanes and filters <= 32
            for block in range(self.blocks_per_stage):
                strides = 2 if stage > 0 and block == 0 else 1
                if in_lanes and not stage_lanes:
                    x = from_lanes(x, h, w)
                    in_lanes = False
                elif stage_lanes and not in_lanes:
                    x = to_lanes(x)
                    in_lanes = True
                x = BasicBlock(filters, strides, dtype=self.dtype,
                               bn_axis=self.bn_axis,
                               use_norm=self.use_norm,
                               bn_impl=self.bn_impl,
                               conv_impl="lanes" if stage_lanes else "xla",
                               hw=(h, w))(x, train=train)
                if strides == 2:
                    h, w = h // 2, w // 2
        if in_lanes:
            x = from_lanes(x, h, w)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))

    def _call_packed(self, x, train: bool):
        """fedpack body: x [K, N, 32, 32, 3] lane-major; every stage runs
        client-packed convs (at any K*C >= 128 the contraction keeps at
        least one full MXU dimension). Submodule call order matches the
        NHWC body, so the parameter tree is the standard tree + leading K."""
        from fedml_tpu.ops.packed_conv import BatchNorm as PBatchNorm
        from fedml_tpu.ops.packed_conv import Conv as PConv
        from fedml_tpu.ops.packed_conv import Dense as PDense

        x = x.astype(self.dtype)
        x = PConv(self.widths[0], 3, use_bias=False, impl=self.packed_impl,
                  dtype=self.dtype)(x)
        if self.use_norm:
            x = PBatchNorm(use_running_average=not train, momentum=0.9,
                           dtype=self.dtype)(x)
        x = nn.relu(x)
        for stage, filters in enumerate(self.widths):
            for block in range(self.blocks_per_stage):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(filters, strides, dtype=self.dtype,
                               use_norm=self.use_norm,
                               conv_impl="packed",
                               packed_impl=self.packed_impl)(x, train=train)
        x = jnp.mean(x, axis=(2, 3))
        return PDense(self.output_dim, dtype=jnp.float32)(
            x.astype(jnp.float32))


def _make(depth: int, output_dim: int, dtype=jnp.float32, bn_axis=None,
          bn_impl="xla", conv_impl="xla",
          packed_impl="blockdiag") -> CifarResNet:
    assert (depth - 2) % 6 == 0, "CIFAR ResNet depth must be 6n+2"
    if conv_impl in ("lanes", "packed") and bn_impl == "pallas":
        raise ValueError(f"conv_impl={conv_impl!r} uses XLA-lowered "
                         "BatchNorm on its own layout; combine with "
                         "bn_impl='xla'")
    return CifarResNet((depth - 2) // 6, output_dim, dtype=dtype,
                       bn_axis=bn_axis, bn_impl=bn_impl, conv_impl=conv_impl,
                       packed_impl=packed_impl)


def _register_resnet(name: str, depth: int):
    @register_model(name)
    def _factory(output_dim: int, dtype=jnp.float32, bn_axis=None,
                 bn_impl="xla", conv_impl="xla", packed_impl="blockdiag", **_):
        bundle = ModelBundle(
            name=name,
            module=_make(depth, output_dim, dtype, bn_axis, bn_impl,
                         conv_impl, packed_impl),
            input_shape=(32, 32, 3),
            has_batch_stats=True,
        )
        if conv_impl == "xla" and bn_impl == "xla" and bn_axis is None:
            # fedpack hook: the packed schedule's joint-lane program swaps
            # in this train-only twin (lane-major input, stacked params —
            # ops/packed_conv.py) when --packed_conv is on
            bundle.packed_variant = lambda impl: ModelBundle(
                name=f"{name}_packed",
                module=_make(depth, output_dim, dtype, None, "xla",
                             "packed", impl),
                input_shape=(32, 32, 3),
                has_batch_stats=True,
            )
        return bundle
    return _factory


_register_resnet("resnet56", 56)
_register_resnet("resnet110", 110)
# small variant for CI/tests (not in the reference zoo but same family)
_register_resnet("resnet20", 20)


def _register_width_variant(name: str, widths: tuple):
    """Perf-experiment variants (docs/mfu_experiments.md): same depth-56
    topology with uniform channel widths, used to measure how flagship MFU
    scales with MXU lane occupancy (Cout/128). Not part of the reference
    zoo — benchmarking instruments, not training recipes."""

    @register_model(name)
    def _variant(output_dim: int, dtype=jnp.float32, bn_axis=None, **_):
        return ModelBundle(
            name=name,
            module=CifarResNet(9, output_dim, dtype=dtype, bn_axis=bn_axis,
                               widths=widths),
            input_shape=(32, 32, 3),
            has_batch_stats=True,
        )
    return _variant


_register_width_variant("resnet56_w64", (64, 64, 64))
_register_width_variant("resnet56_w128", (128, 128, 128))


@register_model("resnet56_nonorm")
def _resnet56_nonorm(output_dim: int, dtype=jnp.float32, **_):
    """Perf-experiment variant: standard widths, NO BatchNorm anywhere —
    isolates normalization's share of the flagship step time (BN is a
    spatial reduction XLA cannot fuse into the convs)."""
    return ModelBundle(
        name="resnet56_nonorm",
        module=CifarResNet(9, output_dim, dtype=dtype, use_norm=False),
        input_shape=(32, 32, 3),
        has_batch_stats=False,
    )
