"""EfficientNet-B0..B7 in flax.

Counterpart of reference fedml_api/model/cv/efficientnet.py +
efficientnet_utils.py (MBConv blocks with expansion, squeeze-excite, swish,
stochastic depth, compound width/depth scaling).

TPU notes: NHWC, bf16-friendly; drop-path (stochastic depth) uses the flax
'dropout' rng collection; batch-norm momentum 0.99 like the original recipe.
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model

# (expand_ratio, channels, repeats, stride, kernel) — the B0 backbone
_B0_BLOCKS = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)

# width_mult, depth_mult, resolution, dropout
_SCALING = {
    "b0": (1.0, 1.0, 224, 0.2),
    "b1": (1.0, 1.1, 240, 0.2),
    "b2": (1.1, 1.2, 260, 0.3),
    "b3": (1.2, 1.4, 300, 0.3),
    "b4": (1.4, 1.8, 380, 0.4),
    "b5": (1.6, 2.2, 456, 0.4),
    "b6": (1.8, 2.6, 528, 0.5),
    "b7": (2.0, 3.1, 600, 0.5),
}


def _round_filters(filters: float, width_mult: float, divisor: int = 8) -> int:
    f = filters * width_mult
    new = max(divisor, int(f + divisor / 2) // divisor * divisor)
    if new < 0.9 * f:
        new += divisor
    return int(new)


def _round_repeats(repeats: int, depth_mult: float) -> int:
    return int(math.ceil(repeats * depth_mult))


class SqueezeExcite(nn.Module):
    reduced: int

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.Conv(self.reduced, (1, 1))(s)
        s = nn.swish(s)
        s = nn.Conv(x.shape[-1], (1, 1))(s)
        return x * jax.nn.sigmoid(s)


class MBConv(nn.Module):
    c_out: int
    expand: int
    stride: int
    kernel: int
    drop_rate: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        c_in = x.shape[-1]
        norm = lambda: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.99, dtype=self.dtype
        )
        y = x
        if self.expand != 1:
            y = nn.Conv(c_in * self.expand, (1, 1), use_bias=False, dtype=self.dtype)(y)
            y = nn.swish(norm()(y))
        y = nn.Conv(
            y.shape[-1], (self.kernel, self.kernel),
            strides=(self.stride, self.stride), padding="SAME",
            feature_group_count=y.shape[-1], use_bias=False, dtype=self.dtype,
        )(y)
        y = nn.swish(norm()(y))
        y = SqueezeExcite(max(1, c_in // 4))(y)
        y = nn.Conv(self.c_out, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = norm()(y)
        if self.stride == 1 and c_in == self.c_out:
            if train and self.drop_rate > 0:
                # stochastic depth: drop the whole residual branch per sample
                keep = 1.0 - self.drop_rate
                rng = self.make_rng("dropout")
                mask = jax.random.bernoulli(rng, keep, (y.shape[0], 1, 1, 1))
                y = jnp.where(mask, y / keep, 0.0)
            y = y + x
        return y


class EfficientNet(nn.Module):
    variant: str = "b0"
    output_dim: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        width, depth, _, dropout = _SCALING[self.variant]
        x = x.astype(self.dtype)
        norm = lambda: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.99, dtype=self.dtype
        )
        x = nn.Conv(_round_filters(32, width), (3, 3), strides=(2, 2),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.swish(norm()(x))
        total_blocks = sum(_round_repeats(r, depth) for _, _, r, _, _ in _B0_BLOCKS)
        block_idx = 0
        for expand, c, repeats, stride, kernel in _B0_BLOCKS:
            c_out = _round_filters(c, width)
            for i in range(_round_repeats(repeats, depth)):
                # linearly increasing stochastic depth, survival 0.8 at the top
                drop = 0.2 * block_idx / max(total_blocks, 1)
                x = MBConv(
                    c_out, expand, stride if i == 0 else 1, kernel,
                    drop_rate=drop, dtype=self.dtype,
                )(x, train=train)
                block_idx += 1
        x = nn.Conv(_round_filters(1280, width), (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.swish(norm()(x))
        x = jnp.mean(x, axis=(1, 2))
        if train and dropout > 0:
            x = nn.Dropout(dropout, deterministic=False)(x)
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))


def _make(variant: str):
    @register_model(f"efficientnet-{variant}")
    def _f(output_dim: int, input_shape=(32, 32, 3), dtype=jnp.float32, **_):
        return ModelBundle(
            name=f"efficientnet-{variant}",
            module=EfficientNet(variant, output_dim, dtype=dtype),
            input_shape=tuple(input_shape),
            has_batch_stats=True,
            uses_dropout=True,
        )
    return _f


for _v in _SCALING:
    _make(_v)
