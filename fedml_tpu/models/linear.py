"""Linear models (reference fedml_api/model/linear/lr.py:4-11)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class LogisticRegression(nn.Module):
    """Single dense layer; logits out (loss applies softmax/sigmoid).

    The reference applies torch.sigmoid at the output (lr.py:10) and pairs it
    with CrossEntropyLoss anyway; we output raw logits, the numerically sound
    equivalent.
    """

    output_dim: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        return nn.Dense(self.output_dim, name="linear")(x)


@register_model("lr")
def _lr(output_dim: int, input_dim: int = 784, task: str = "classification", **_):
    return ModelBundle(
        name="lr",
        module=LogisticRegression(output_dim),
        input_shape=(input_dim,),
        task=task,
    )
