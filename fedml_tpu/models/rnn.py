"""Recurrent models for federated NLP (reference fedml_api/model/nlp/rnn.py:4-70).

- ``rnn`` / RNN_OriginalFedAvg: embed(8) -> 2xLSTM(256) -> dense(vocab) for
  char-level Shakespeare next-char prediction (seq len 80).
- ``rnn_stackoverflow`` / RNN_StackOverFlow: embed(96) -> LSTM(670) ->
  dense(96) -> dense(vocab+special) for StackOverflow next-word prediction.

Outputs logits for EVERY position [B, T, V] (the reference returns the full
sequence too) — pairs with the ``nwp`` task. lax.scan-based flax RNN keeps
the compiled graph static-shaped for XLA.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class CharLSTM(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x.astype(jnp.int32))
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        return nn.Dense(self.vocab_size)(h)


class StackOverflowNWP(nn.Module):
    # 10000 words + 4 special tokens (pad/bos/eos/oov), per the TFF baseline.
    vocab_size: int = 10004
    embedding_dim: int = 96
    hidden: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x.astype(jnp.int32))
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)


@register_model("rnn")
def _rnn(output_dim: int = 90, seq_len: int = 80, **_):
    return ModelBundle(
        name="rnn",
        module=CharLSTM(vocab_size=output_dim or 90),
        input_shape=(seq_len,),
        input_dtype=jnp.int32,
        task="nwp",
    )


@register_model("rnn_stackoverflow")
def _rnn_so(output_dim: int = 10004, seq_len: int = 20, **_):
    return ModelBundle(
        name="rnn_stackoverflow",
        module=StackOverflowNWP(vocab_size=output_dim or 10004),
        input_shape=(seq_len,),
        input_dtype=jnp.int32,
        task="nwp",
    )
