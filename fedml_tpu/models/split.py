"""Split-model pairs for SplitNN (reference fedml_api/distributed/split_nn).

The reference splits an arbitrary torch model into client-side lower layers
and server-side upper layers, with activations crossing the process
boundary (split_nn/client.py:24-34, server.py:40-60). Here a split pair is
two ModelBundles — the client bundle maps input -> cut activations, the
server bundle maps activations -> logits — so both halves stay pure jit
functions and the in-mesh trainer can fuse them into ONE program (the
boundary only materializes for genuinely off-pod clients).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle


class _ClientCNN(nn.Module):
    """Lower half: two conv blocks -> flattened feature activations."""

    features: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.features * 2, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x.reshape((x.shape[0], -1))


class _ServerMLP(nn.Module):
    """Upper half: dense head on the cut activations."""

    hidden: int = 128
    output_dim: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.output_dim)(x)


def create_split_cnn(
    output_dim: int,
    input_shape: Sequence[int] = (28, 28, 1),
    features: int = 32,
    hidden: int = 128,
) -> tuple[ModelBundle, ModelBundle]:
    """(client_bundle, server_bundle) for a CNN split at the flatten point."""
    input_shape = tuple(input_shape)
    h, w = input_shape[0] // 4, input_shape[1] // 4
    act_dim = h * w * features * 2
    client = ModelBundle(
        name="splitnn_client_cnn",
        module=_ClientCNN(features=features),
        input_shape=input_shape,
    )
    server = ModelBundle(
        name="splitnn_server_mlp",
        module=_ServerMLP(hidden=hidden, output_dim=output_dim),
        input_shape=(act_dim,),
    )
    return client, server


def create_split_mlp(
    output_dim: int,
    input_shape: Sequence[int],
    cut_dim: int = 64,
) -> tuple[ModelBundle, ModelBundle]:
    """Dense/dense split for flat-feature datasets (synthetic, tabular)."""
    input_shape = tuple(input_shape)

    class _ClientDense(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(cut_dim)(x)
            return nn.relu(x)

    client = ModelBundle(name="splitnn_client_mlp", module=_ClientDense(), input_shape=input_shape)
    server = ModelBundle(
        name="splitnn_server_mlp",
        module=_ServerMLP(hidden=cut_dim, output_dim=output_dim),
        input_shape=(cut_dim,),
    )
    return client, server
