"""Model zoo registry.

Counterpart of ``fedml_api/model/`` + the ``create_model`` factory embedded in
every reference main (fedml_experiments/distributed/fedavg/main_fedavg.py:232-267).
Models are flax modules; ``create_model(name, ...)`` returns a ``ModelBundle``
with pure init/apply functions so algorithms never touch module objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

_REGISTRY: dict[str, Callable[..., "ModelBundle"]] = {}


def register_model(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


@dataclass
class ModelBundle:
    """A model as pure functions over variable pytrees.

    ``variables`` is the full flax collection dict {'params': ..., maybe
    'batch_stats': ...}. ``apply_train`` returns (logits, new_variables) with
    mutable collections updated; ``apply_eval`` is deterministic.
    """

    name: str
    module: nn.Module
    input_shape: tuple          # single-example shape, no batch dim
    input_dtype: Any = jnp.float32
    task: str = "classification"
    has_batch_stats: bool = False
    uses_dropout: bool = False
    #: explicit-key dropout (ops/packed_conv.seed_dropout): apply_train
    #: hands ``rng`` to the module as a ``dropout_rng`` kwarg instead of a
    #: flax rng stream, so the derivation is replayable per lane by the
    #: packed twin (which receives the [K] vector of lane keys). Models
    #: opt in per-module; a dropout model WITHOUT it keeps the vmap
    #: fallback under --packed_conv (parallel/packed.packed_fallback_reason).
    explicit_dropout: bool = False
    #: fedpack hook (ops/packed_conv.py): ``packed_variant(impl)`` returns a
    #: TRAIN-ONLY bundle whose module consumes lane-major [K, N, ...] input
    #: and whose parameter tree is the standard tree with a leading K axis
    #: on every leaf (stack_variables/unstack_variables are the bridges).
    #: None = this model family has no packed conv lowering; the packed
    #: schedule keeps its per-lane vmap.
    packed_variant: Optional[Callable[[str], "ModelBundle"]] = None

    def init(self, rng: jax.Array, batch_size: int = 2) -> dict:
        x = jnp.zeros((batch_size,) + tuple(self.input_shape), self.input_dtype)
        return self.module.init({"params": rng}, x, train=False)

    def apply_train(self, variables: dict, x: jax.Array, rng: jax.Array):
        rngs, kwargs = {}, {}
        if self.explicit_dropout:
            kwargs["dropout_rng"] = rng     # raw key(s); module derives masks
        elif self.uses_dropout:
            rngs = {"dropout": rng}
        if self.has_batch_stats:
            logits, updated = self.module.apply(
                variables, x, train=True, mutable=["batch_stats"], rngs=rngs,
                **kwargs
            )
            new_vars = dict(variables)
            new_vars.update(updated)
            return logits, new_vars
        out = self.module.apply(variables, x, train=True, rngs=rngs, **kwargs)
        return out, variables

    def apply_eval(self, variables: dict, x: jax.Array) -> jax.Array:
        return self.module.apply(variables, x, train=False)


def create_model(model_name: str, output_dim: int, input_shape: Optional[Sequence[int]] = None, **kw) -> ModelBundle:
    """Factory keyed by the reference's --model flag values
    (main_fedavg.py:232-267: lr, cnn, resnet18_gn, rnn, resnet56, mobilenet,
    ...)."""
    # Import lazily so optional model families don't slow cold start.
    from fedml_tpu.models import cnn, linear, mobilenet, resnet, resnet_gn, rnn, segmentation, transformer, vgg  # noqa: F401
    try:
        from fedml_tpu.models import efficientnet  # noqa: F401
    except ImportError:
        pass
    if model_name not in _REGISTRY:
        raise KeyError(f"unknown model {model_name!r}; known: {sorted(_REGISTRY)}")
    bundle = _REGISTRY[model_name](output_dim=output_dim, **kw)
    if input_shape is not None:
        bundle.input_shape = tuple(input_shape)
    return bundle


def known_models() -> list[str]:
    from fedml_tpu.models import cnn, linear, mobilenet, resnet, resnet_gn, rnn, segmentation, transformer, vgg  # noqa: F401
    try:
        from fedml_tpu.models import efficientnet  # noqa: F401
    except ImportError:
        pass
    return sorted(_REGISTRY)
