"""Mixture-of-experts transformer blocks with expert parallelism.

The reference has no MoE (and no LLM-era parallelism at all, SURVEY.md
§2.6); this exists so the framework's parallelism surface covers the EP
axis alongside dp/tp/sp/clients/group.

Design: the MoE MLP keeps expert weights stacked on a leading expert axis
``[E, ...]`` — sharding that axis over an 'ep' mesh axis IS expert
parallelism (each device stores and computes only its experts). Routing is
a dense softmax-weighted top-k dispatch expressed as einsums over the
expert axis, which makes the layer exactly equal to its single-device
form under GSPMD (no capacity dropping, no load-balancing noise) — the
right correctness baseline for a framework; a capacity-limited all_to_all
dispatch is a performance specialization of the same parameter layout.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.transformer import SelfAttention


def top_k_probs(router_logits: jax.Array, top_k: int) -> jax.Array:
    """Softmax the router logits, keep each token's top-k experts, and
    renormalize so the kept weights sum to 1 (fully differentiable)."""
    E = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits, axis=-1)
    if top_k < E:
        kth = jnp.sort(probs, axis=-1)[..., E - top_k][..., None]
        probs = jnp.where(probs >= kth, probs, 0.0)
        probs = probs / jnp.maximum(jnp.sum(probs, axis=-1, keepdims=True), 1e-9)
    return probs


class MoeMlp(nn.Module):
    """Softmax-routed top-k mixture of expert MLPs (dense dispatch)."""

    dim: int
    num_experts: int = 4
    mlp_ratio: int = 4
    top_k: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h):
        E, D, F = self.num_experts, self.dim, self.mlp_ratio * self.dim
        router = nn.Dense(E, dtype=jnp.float32, name="router")(
            h.astype(jnp.float32))                      # [B, T, E]
        probs = top_k_probs(router, self.top_k)
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (E, D, F), jnp.float32).astype(self.dtype)
        b_up = self.param("b_up", nn.initializers.zeros, (E, F), jnp.float32)
        w_dn = self.param("w_dn", nn.initializers.lecun_normal(),
                          (E, F, D), jnp.float32).astype(self.dtype)
        b_dn = self.param("b_dn", nn.initializers.zeros, (E, D), jnp.float32)
        h = h.astype(self.dtype)
        # every expert computes every token; the router weights combine.
        # einsum over the (sharded) expert axis -> per-device partial sums,
        # one psum inserted by GSPMD at the combine.
        up = jnp.einsum("btd,edf->ebtf", h, w_up) + b_up[:, None, None, :].astype(self.dtype)
        act = nn.gelu(up)
        down = jnp.einsum("ebtf,efd->ebtd", act, w_dn) + b_dn[:, None, None, :].astype(self.dtype)
        out = jnp.einsum("bte,ebtd->btd", probs.astype(self.dtype), down)
        return out


class MoeBlock(nn.Module):
    dim: int
    heads: int
    num_experts: int = 4
    mlp_ratio: int = 4
    top_k: int = 2
    attn_impl: str = "auto"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h, train: bool = False):
        a = SelfAttention(self.dim, self.heads, self.attn_impl,
                          dtype=self.dtype, name="attn")(
            nn.LayerNorm(dtype=self.dtype)(h))
        h = h + a
        m = MoeMlp(self.dim, self.num_experts, self.mlp_ratio, self.top_k,
                   self.dtype, name="moe")(nn.LayerNorm(dtype=self.dtype)(h))
        return h + m


class MoeTransformerLM(nn.Module):
    """Decoder-only LM with MoE MLPs — the EP counterpart of TransformerLM."""

    vocab_size: int
    dim: int = 256
    heads: int = 8
    layers: int = 4
    num_experts: int = 4
    mlp_ratio: int = 4
    top_k: int = 2
    max_len: int = 4096
    attn_impl: str = "auto"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, pos_offset=0):
        t = x.shape[1]
        h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype,
                     name="tok_embed")(x.astype(jnp.int32))
        pos = pos_offset + jnp.arange(t)
        h = h + nn.Embed(self.max_len, self.dim, dtype=self.dtype,
                         name="pos_embed")(pos)[None]
        for i in range(self.layers):
            h = MoeBlock(self.dim, self.heads, self.num_experts,
                         self.mlp_ratio, self.top_k, self.attn_impl,
                         self.dtype, name=f"block{i}")(h, train)
        h = nn.LayerNorm(dtype=self.dtype)(h)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(h)
