"""MobileNet v1 and v3 (reference fedml_api/model/cv/mobilenet.py:1-209,
cv/mobilenet_v3.py:1-257), CIFAR-sized.

Depthwise-separable convs map well onto TPU: the depthwise stage runs on the
VPU, pointwise 1x1 convs are MXU matmuls. NHWC throughout.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class DepthwiseSeparable(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9, dtype=self.dtype)
        cin = x.shape[-1]
        x = nn.Conv(cin, (3, 3), strides=(self.strides, self.strides), padding="SAME",
                    feature_group_count=cin, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(norm()(x))
        x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        return nn.relu(norm()(x))


class MobileNetV1(nn.Module):
    """Standard v1 stack (channel, stride) schedule, CIFAR stem (stride 1)."""

    output_dim: int = 10
    width: float = 1.0
    dtype: Any = jnp.float32
    schedule: Sequence[tuple] = (
        (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
        (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1),
    )

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(int(32 * self.width), (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=self.dtype)(x))
        for ch, s in self.schedule:
            x = DepthwiseSeparable(int(ch * self.width), s, dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))


def hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def hard_swish(x):
    return x * hard_sigmoid(x)


class SqueezeExcite(nn.Module):
    reduce: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(c // self.reduce, 8), dtype=self.dtype)(s))
        s = hard_sigmoid(nn.Dense(c, dtype=self.dtype)(s))
        return x * s[:, None, None, :]


class InvertedResidual(nn.Module):
    exp: int
    filters: int
    kernel: int
    strides: int
    use_se: bool
    use_hs: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9, dtype=self.dtype)
        act = hard_swish if self.use_hs else nn.relu
        inp = x
        cin = x.shape[-1]
        y = x
        if self.exp != cin:
            y = nn.Conv(self.exp, (1, 1), use_bias=False, dtype=self.dtype)(y)
            y = act(norm()(y))
        y = nn.Conv(self.exp, (self.kernel, self.kernel), strides=(self.strides, self.strides),
                    padding="SAME", feature_group_count=self.exp, use_bias=False, dtype=self.dtype)(y)
        y = act(norm()(y))
        if self.use_se:
            y = SqueezeExcite(dtype=self.dtype)(y)
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = norm()(y)
        if self.strides == 1 and cin == self.filters:
            y = y + inp
        return y


# (kernel, exp, out, SE, HS, stride) — v3-large / v3-small schedules
_V3_LARGE = (
    (3, 16, 16, False, False, 1), (3, 64, 24, False, False, 2), (3, 72, 24, False, False, 1),
    (5, 72, 40, True, False, 2), (5, 120, 40, True, False, 1), (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2), (3, 200, 80, False, True, 1), (3, 184, 80, False, True, 1),
    (3, 184, 80, False, True, 1), (3, 480, 112, True, True, 1), (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2), (5, 960, 160, True, True, 1), (5, 960, 160, True, True, 1),
)
_V3_SMALL = (
    (3, 16, 16, True, False, 2), (3, 72, 24, False, False, 2), (3, 88, 24, False, False, 1),
    (5, 96, 40, True, True, 2), (5, 240, 40, True, True, 1), (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1), (5, 144, 48, True, True, 1), (5, 288, 96, True, True, 2),
    (5, 576, 96, True, True, 1), (5, 576, 96, True, True, 1),
)


class MobileNetV3(nn.Module):
    output_dim: int = 10
    mode: str = "small"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        sched = _V3_LARGE if self.mode == "large" else _V3_SMALL
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), strides=(1, 1), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = hard_swish(norm()(x))
        for k, exp, out, se, hs, s in sched:
            x = InvertedResidual(exp, out, k, s, se, hs, dtype=self.dtype)(x, train=train)
        last = 960 if self.mode == "large" else 576
        x = nn.Conv(last, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = hard_swish(norm()(x))
        x = jnp.mean(x, axis=(1, 2))
        x = hard_swish(nn.Dense(1280 if self.mode == "large" else 1024, dtype=self.dtype)(x))
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))


@register_model("mobilenet")
def _mobilenet(output_dim: int, dtype=jnp.float32, **_):
    return ModelBundle(
        name="mobilenet",
        module=MobileNetV1(output_dim, dtype=dtype),
        input_shape=(32, 32, 3),
        has_batch_stats=True,
    )


@register_model("mobilenet_v3")
def _mobilenet_v3(output_dim: int, mode: str = "small", dtype=jnp.float32, **_):
    return ModelBundle(
        name="mobilenet_v3",
        module=MobileNetV3(output_dim, mode=mode, dtype=dtype),
        input_shape=(32, 32, 3),
        has_batch_stats=True,
    )
