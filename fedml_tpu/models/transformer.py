"""Decoder-only transformer LM — the TPU-first upgrade of the reference's
RNN family (fedml_api/model/nlp/rnn.py:4-70 only ships 80/20-token LSTMs).

Attention goes through :mod:`fedml_tpu.ops.attention` (fused blockwise
kernel, MXU-shaped). When ``ring_axis`` is set the module must be applied
inside a ``shard_map`` over that mesh axis: the sequence is sharded, K/V
rotate around the ring (fedml_tpu/parallel/sequence.py), and
``pos_offset`` gives the shard's global position for positional embeddings
and causal masks — this is the framework's long-context path.

Registered as ``transformer`` (char-level shakespeare default) and
``transformer_nwp`` (stackoverflow word-level default) so every federated
algorithm can train it like any other zoo model.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model
from fedml_tpu.ops.attention import attention


class SelfAttention(nn.Module):
    dim: int
    heads: int
    attn_impl: str = "auto"
    ring_axis: Optional[str] = None
    ring_size: int = 1
    sp_mode: str = "ring"            # ring | ulysses (all-to-all)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h):
        b, t, _ = h.shape
        d = self.dim // self.heads
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads_first(a):
            return a.reshape(b, t, self.heads, d).transpose(0, 2, 1, 3)

        q, k, v = heads_first(q), heads_first(k), heads_first(v)
        if self.ring_axis is not None and self.ring_size > 1:
            from fedml_tpu.parallel.sequence import sequence_attention

            o = sequence_attention(q, k, v, axis_name=self.ring_axis,
                                   axis_size=self.ring_size, causal=True,
                                   impl=self.attn_impl, mode=self.sp_mode)
        else:
            o = attention(q, k, v, causal=True, impl=self.attn_impl)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, name="out")(o)


class Block(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    attn_impl: str = "auto"
    ring_axis: Optional[str] = None
    ring_size: int = 1
    sp_mode: str = "ring"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h, train: bool):
        a = SelfAttention(self.dim, self.heads, self.attn_impl,
                          self.ring_axis, self.ring_size, self.sp_mode,
                          self.dtype,
                          name="attn")(nn.LayerNorm(dtype=self.dtype)(h))
        if self.dropout:
            a = nn.Dropout(self.dropout, deterministic=not train)(a)
        h = h + a
        m = nn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype)(
            nn.LayerNorm(dtype=self.dtype)(h))
        m = nn.gelu(m)
        m = nn.Dense(self.dim, dtype=self.dtype)(m)
        if self.dropout:
            m = nn.Dropout(self.dropout, deterministic=not train)(m)
        return h + m


class TransformerLM(nn.Module):
    vocab_size: int
    dim: int = 256
    heads: int = 8
    layers: int = 4
    mlp_ratio: int = 4
    max_len: int = 4096
    dropout: float = 0.0
    attn_impl: str = "auto"
    ring_axis: Optional[str] = None     # set to 'sp' for sequence parallelism
    ring_size: int = 1
    sp_mode: str = "ring"               # ring (ppermute) | ulysses (all-to-all)
    remat: bool = False                 # rematerialize blocks on backward
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, pos_offset=0):
        t = x.shape[1]
        h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype,
                     name="tok_embed")(x.astype(jnp.int32))
        pos = pos_offset + jnp.arange(t)
        h = h + nn.Embed(self.max_len, self.dim, dtype=self.dtype,
                         name="pos_embed")(pos)[None]
        # remat: drop each block's activations on the forward pass and
        # recompute them during backward — long-context training is HBM-bound
        # on activations (B x T x D per layer), and the recompute rides the
        # MXU headroom the small per-block matmuls leave anyway.
        block_cls = (nn.remat(Block, static_argnums=(2,)) if self.remat
                     else Block)
        for i in range(self.layers):
            h = block_cls(self.dim, self.heads, self.mlp_ratio, self.dropout,
                          self.attn_impl, self.ring_axis, self.ring_size,
                          self.sp_mode, self.dtype, name=f"block{i}")(h, train)
        h = nn.LayerNorm(dtype=self.dtype)(h)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(h)


def _bundle(name, vocab, seq_len, **kw):
    sizes = dict(dim=kw.pop("dim", 256), heads=kw.pop("heads", 8),
                 layers=kw.pop("layers", 4), dropout=kw.pop("dropout", 0.0),
                 mlp_ratio=kw.pop("mlp_ratio", 4))
    module = TransformerLM(vocab_size=vocab, max_len=max(4096, seq_len),
                           attn_impl=kw.pop("attn_impl", "auto"),
                           ring_axis=kw.pop("ring_axis", None),
                           ring_size=kw.pop("ring_size", 1),
                           sp_mode=kw.pop("sp_mode", "ring"),
                           remat=kw.pop("remat", False),
                           dtype=kw.pop("dtype", jnp.float32), **sizes)
    return ModelBundle(
        name=name, module=module, input_shape=(seq_len,),
        input_dtype=jnp.int32, task="nwp",
        uses_dropout=sizes["dropout"] > 0,
    )


@register_model("transformer")
def _transformer(output_dim: int = 90, seq_len: int = 80, **kw):
    return _bundle("transformer", output_dim or 90, seq_len, **kw)


@register_model("transformer_nwp")
def _transformer_nwp(output_dim: int = 10004, seq_len: int = 20, **kw):
    return _bundle("transformer_nwp", output_dim or 10004, seq_len, **kw)
