"""DARTS search space in flax (for FedNAS).

Counterpart of reference fedml_api/model/cv/darts/{operations.py,
model_search.py, model.py, genotypes.py}: the 8-primitive mixed-op cell
search space (operations.py:4-20), the over-parameterized search network
(model_search.py:172-257), genotype derivation (model_search.py:258-297),
and the discrete network built from a genotype (model.py).

JAX re-design:
- architecture parameters (alphas) are NOT flax params — they are a separate
  pytree passed as an input to ``apply``. That makes DARTS' bilevel structure
  native: ``jax.grad`` w.r.t. weights and w.r.t. alphas are two argnums of
  the same pure function, no parameter-group bookkeeping
  (architect.py:15-30's concat/clone machinery disappears),
- every mixed op evaluates all primitives and contracts with softmax(alpha)
  — a dense weighted sum XLA fuses well; there is no dynamic op dispatch,
- BatchNorms in the search net are affine-free (reference affine=False) and
  use running stats only at eval.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

PRIMITIVES = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)


class Genotype(NamedTuple):
    normal: list          # [(op_name, input_node), ...]
    normal_concat: list
    reduce: list
    reduce_concat: list


def num_edges(steps: int) -> int:
    return sum(2 + i for i in range(steps))


# ---------------------------------------------------------------- primitives

def _bn(train: bool):
    return nn.BatchNorm(
        use_running_average=not train, momentum=0.9,
        use_scale=False, use_bias=False,
    )


def _avg_pool_3x3(x, stride):
    """count_include_pad=False semantics (operations.py:6): divide by the
    number of REAL elements under the window."""
    ones = jnp.ones_like(x[..., :1])
    s = nn.avg_pool(x, (3, 3), strides=(stride, stride), padding="SAME")
    c = nn.avg_pool(ones, (3, 3), strides=(stride, stride), padding="SAME")
    return s / jnp.maximum(c, 1e-12)


def _max_pool_3x3(x, stride):
    return nn.max_pool(x, (3, 3), strides=(stride, stride), padding="SAME")


class ReLUConvBN(nn.Module):
    c_out: int
    kernel: int = 1
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        x = nn.Conv(self.c_out, (self.kernel, self.kernel),
                    strides=(self.stride, self.stride), padding="SAME",
                    use_bias=False)(x)
        return _bn(train)(x)


class DilConv(nn.Module):
    c_out: int
    kernel: int
    stride: int
    dilation: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        c_in = x.shape[-1]
        x = nn.relu(x)
        x = nn.Conv(c_in, (self.kernel, self.kernel),
                    strides=(self.stride, self.stride), padding="SAME",
                    kernel_dilation=(self.dilation, self.dilation),
                    feature_group_count=c_in, use_bias=False)(x)
        x = nn.Conv(self.c_out, (1, 1), use_bias=False)(x)
        return _bn(train)(x)


class SepConv(nn.Module):
    c_out: int
    kernel: int
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        c_in = x.shape[-1]
        for i, stride in enumerate((self.stride, 1)):
            x = nn.relu(x)
            x = nn.Conv(c_in, (self.kernel, self.kernel),
                        strides=(stride, stride), padding="SAME",
                        feature_group_count=c_in, use_bias=False)(x)
            x = nn.Conv(c_in if i == 0 else self.c_out, (1, 1), use_bias=False)(x)
            x = _bn(train)(x)
        return x


class FactorizedReduce(nn.Module):
    c_out: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        a = nn.Conv(self.c_out // 2, (1, 1), strides=(2, 2), use_bias=False)(x)
        b = nn.Conv(self.c_out // 2, (1, 1), strides=(2, 2), use_bias=False)(
            jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))[:, 1:, 1:, :]
        )
        return _bn(train)(jnp.concatenate([a, b], axis=-1))


def _zero(x, stride):
    if stride == 1:
        return x * 0.0
    return x[:, ::stride, ::stride, :] * 0.0


class MixedOp(nn.Module):
    """All 8 primitives evaluated, contracted with the edge's softmax weights
    (model_search.py:10-23). Pool ops get a trailing affine-free BN like the
    reference (model_search.py:17-18)."""

    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, weights, train: bool = False):
        c = self.channels
        outs = [
            _zero(x, self.stride),
            _bn(train)(_max_pool_3x3(x, self.stride)),
            _bn(train)(_avg_pool_3x3(x, self.stride)),
            x if self.stride == 1 else FactorizedReduce(c)(x, train),
            SepConv(c, 3, self.stride)(x, train),
            SepConv(c, 5, self.stride)(x, train),
            DilConv(c, 3, self.stride, 2)(x, train),
            DilConv(c, 5, self.stride, 2)(x, train),
        ]
        stacked = jnp.stack(outs, axis=0)           # [n_ops, B, H, W, C]
        return jnp.einsum("o,obhwc->bhwc", weights, stacked)


class SearchCell(nn.Module):
    """DAG cell: `steps` intermediate nodes, each summing mixed-op edges from
    all predecessors; output = concat of the last `multiplier` nodes
    (model_search.py:26-60)."""

    steps: int
    multiplier: int
    channels: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1, weights, train: bool = False):
        c = self.channels
        if self.reduction_prev:
            s0 = FactorizedReduce(c)(s0, train)
        else:
            s0 = ReLUConvBN(c)(s0, train)
        s1 = ReLUConvBN(c)(s1, train)
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            s = sum(
                MixedOp(c, 2 if self.reduction and j < 2 else 1)(
                    h, weights[offset + j], train
                )
                for j, h in enumerate(states)
            )
            offset += len(states)
            states.append(s)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


class DartsSearchNetwork(nn.Module):
    """Over-parameterized search net (model_search.py:172-231): stem, cells
    with reductions at 1/3 and 2/3 depth, global pool + classifier. Alphas
    arrive as inputs: {'normal': [k, 8], 'reduce': [k, 8]}."""

    channels: int = 16
    layers: int = 8
    steps: int = 4
    multiplier: int = 4
    stem_multiplier: int = 3
    output_dim: int = 10

    @nn.compact
    def __call__(self, x, alphas: dict, train: bool = False):
        w_normal = jax.nn.softmax(alphas["normal"], axis=-1)
        w_reduce = jax.nn.softmax(alphas["reduce"], axis=-1)
        c_curr = self.stem_multiplier * self.channels
        s = nn.Conv(c_curr, (3, 3), padding="SAME", use_bias=False)(x)
        s = nn.BatchNorm(use_running_average=not train, momentum=0.9)(s)
        s0 = s1 = s
        c_curr = self.channels
        reduction_prev = False
        for layer in range(self.layers):
            reduction = layer in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                c_curr *= 2
            cell = SearchCell(self.steps, self.multiplier, c_curr,
                              reduction, reduction_prev)
            s0, s1 = s1, cell(s0, s1, w_reduce if reduction else w_normal, train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.output_dim)(out)


def init_alphas(rng: jax.Array, steps: int = 4) -> dict:
    """1e-3 * randn, like model_search.py:232-242."""
    k = num_edges(steps)
    k1, k2 = jax.random.split(rng)
    return {
        "normal": 1e-3 * jax.random.normal(k1, (k, len(PRIMITIVES))),
        "reduce": 1e-3 * jax.random.normal(k2, (k, len(PRIMITIVES))),
    }


def derive_genotype(alphas: dict, steps: int = 4, multiplier: int = 4) -> Genotype:
    """Discretize: per node keep the 2 strongest input edges (ranked by their
    best non-'none' op weight), each with its best non-'none' op
    (model_search.py:263-297)."""

    def parse(w: np.ndarray):
        gene, offset = [], 0
        for i in range(steps):
            n_in = 2 + i
            W = w[offset : offset + n_in]
            edge_strength = [
                max(W[j][k] for k in range(len(PRIMITIVES)) if PRIMITIVES[k] != "none")
                for j in range(n_in)
            ]
            top2 = sorted(range(n_in), key=lambda j: -edge_strength[j])[:2]
            for j in sorted(top2):
                k_best = max(
                    (k for k in range(len(PRIMITIVES)) if PRIMITIVES[k] != "none"),
                    key=lambda k: W[j][k],
                )
                gene.append((PRIMITIVES[k_best], j))
            offset += n_in
        return gene

    wn = np.asarray(jax.nn.softmax(alphas["normal"], axis=-1))
    wr = np.asarray(jax.nn.softmax(alphas["reduce"], axis=-1))
    concat = tuple(range(2 + steps - multiplier, steps + 2))
    # tuples, not lists: the genotype becomes a static (hashable) attribute
    # of the discrete flax module
    return Genotype(tuple(parse(wn)), concat, tuple(parse(wr)), concat)


# --------------------------------------------------- discrete (train) network

class _DiscreteOp(nn.Module):
    op_name: str                # 'name' is reserved by flax
    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        c, s = self.channels, self.stride
        n = self.op_name
        if n == "none":
            return _zero(x, s)
        if n == "max_pool_3x3":
            return _max_pool_3x3(x, s)
        if n == "avg_pool_3x3":
            return _avg_pool_3x3(x, s)
        if n == "skip_connect":
            return x if s == 1 else FactorizedReduce(c)(x, train)
        if n == "sep_conv_3x3":
            return SepConv(c, 3, s)(x, train)
        if n == "sep_conv_5x5":
            return SepConv(c, 5, s)(x, train)
        if n == "dil_conv_3x3":
            return DilConv(c, 3, s, 2)(x, train)
        if n == "dil_conv_5x5":
            return DilConv(c, 5, s, 2)(x, train)
        raise ValueError(f"unknown op {n!r}")


class DiscreteCell(nn.Module):
    genotype_edges: tuple      # ((op_name, input_idx) x 2*steps)
    concat: tuple
    channels: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1, train: bool = False):
        c = self.channels
        if self.reduction_prev:
            s0 = FactorizedReduce(c)(s0, train)
        else:
            s0 = ReLUConvBN(c)(s0, train)
        s1 = ReLUConvBN(c)(s1, train)
        states = [s0, s1]
        steps = len(self.genotype_edges) // 2
        for i in range(steps):
            parts = []
            for (op_name, j) in self.genotype_edges[2 * i : 2 * i + 2]:
                stride = 2 if self.reduction and j < 2 else 1
                parts.append(_DiscreteOp(op_name, c, stride)(states[j], train))
            states.append(sum(parts))
        return jnp.concatenate([states[i] for i in self.concat], axis=-1)


class DartsNetwork(nn.Module):
    """Discrete network built from a derived genotype (model.py counterpart);
    used for FedNAS' post-search federated training phase."""

    genotype: Any              # Genotype (hashable tuple-of-tuples form)
    channels: int = 16
    layers: int = 8
    stem_multiplier: int = 3
    output_dim: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        g = self.genotype
        c_curr = self.stem_multiplier * self.channels
        s = nn.Conv(c_curr, (3, 3), padding="SAME", use_bias=False)(x)
        s = nn.BatchNorm(use_running_average=not train, momentum=0.9)(s)
        s0 = s1 = s
        c_curr = self.channels
        reduction_prev = False
        for layer in range(self.layers):
            reduction = layer in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                c_curr *= 2
            edges = tuple(g.reduce) if reduction else tuple(g.normal)
            concat = tuple(g.reduce_concat) if reduction else tuple(g.normal_concat)
            cell = DiscreteCell(edges, concat, c_curr, reduction, reduction_prev)
            s0, s1 = s1, cell(s0, s1, train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.output_dim)(out)
