"""FedAvg-paper CNNs for FEMNIST/MNIST (reference fedml_api/model/cv/cnn.py:5-142).

Two variants, matching the reference capabilities:

- ``cnn`` / CNN_OriginalFedAvg (cnn.py:5-70): 2x[conv5x5 -> maxpool2] ->
  dense(512) -> softmax head, McMahan et al. 2016 table 2 sizing.
- ``cnn_dropout`` / CNN_DropOut (cnn.py:74-142): the TFF baseline flavor with
  3x3 convs and dropout.

NHWC layout (TPU-native; torch reference is NCHW).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class CNNOriginalFedAvg(nn.Module):
    output_dim: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat 784 -> 28x28x1
            x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(self.output_dim)(x)


class CNNDropOut(nn.Module):
    output_dim: int = 62

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.output_dim)(x)


@register_model("cnn")
def _cnn(output_dim: int, **_):
    return ModelBundle(
        name="cnn",
        module=CNNOriginalFedAvg(output_dim),
        input_shape=(28, 28, 1),
    )


@register_model("cnn_dropout")
def _cnn_dropout(output_dim: int, **_):
    return ModelBundle(
        name="cnn_dropout",
        module=CNNDropOut(output_dim),
        input_shape=(28, 28, 1),
        uses_dropout=True,
    )
