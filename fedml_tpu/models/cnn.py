"""FedAvg-paper CNNs for FEMNIST/MNIST (reference fedml_api/model/cv/cnn.py:5-142).

Two variants, matching the reference capabilities:

- ``cnn`` / CNN_OriginalFedAvg (cnn.py:5-70): 2x[conv5x5 -> maxpool2] ->
  dense(512) -> softmax head, McMahan et al. 2016 table 2 sizing.
- ``cnn_dropout`` / CNN_DropOut (cnn.py:74-142): the TFF baseline flavor with
  3x3 convs and dropout.

NHWC layout (TPU-native; torch reference is NCHW).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class CNNOriginalFedAvg(nn.Module):
    output_dim: int = 62
    only_digits: bool = False
    conv_impl: str = "xla"   # "packed": fedpack client-packed convs over a
    #                          leading lane axis (ops/packed_conv.py)
    packed_impl: Any = "blockdiag"  # name or per-stage LoweringPlan

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.conv_impl == "packed":
            return self._call_packed(x)
        if x.ndim == 2:  # flat 784 -> 28x28x1
            x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(self.output_dim)(x)

    def _call_packed(self, x):
        """fedpack body (x [K, N, 28, 28, 1] or [K, N, 784] lane-major):
        same submodule call order as the per-client body, so the parameter
        tree is the standard tree with a leading K axis (ops/packed_conv
        contract). Pooling folds the lane axis into the batch axis — it is
        per-image work with no cross-lane terms."""
        from fedml_tpu.ops.packed_conv import Conv as PConv
        from fedml_tpu.ops.packed_conv import Dense as PDense

        if x.ndim == 3:  # [K, N, 784] -> [K, N, 28, 28, 1]
            x = x.reshape(x.shape[:2] + (28, 28, 1))
        k = x.shape[0]

        def pool(y):
            flat = y.reshape((-1,) + y.shape[2:])
            flat = nn.max_pool(nn.relu(flat), (2, 2), strides=(2, 2))
            return flat.reshape((k, -1) + flat.shape[1:])

        x = PConv(32, 5, impl=self.packed_impl)(x)
        x = pool(x)
        x = PConv(64, 5, impl=self.packed_impl)(x)
        x = pool(x)
        x = x.reshape(x.shape[:2] + (-1,))
        x = nn.relu(PDense(512)(x))
        return PDense(self.output_dim)(x)


class CNNDropOut(nn.Module):
    """Dropout masks derive from an EXPLICIT key (`dropout_rng`, the step's
    batch key) via ops/packed_conv.seed_dropout instead of a flax rng
    stream, so the packed lane-major twin replays each lane's masks
    bit-for-bit from that lane's own key (ModelBundle.explicit_dropout)."""

    output_dim: int = 62
    conv_impl: str = "xla"   # "packed": fedpack lane-major body
    packed_impl: Any = "blockdiag"  # name or per-stage LoweringPlan

    @nn.compact
    def __call__(self, x, train: bool = False, dropout_rng=None):
        from fedml_tpu.ops.packed_conv import seed_dropout

        if self.conv_impl == "packed":
            return self._call_packed(x, train, dropout_rng)
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 28, 28, 1))
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = seed_dropout(x, dropout_rng, 0.25, 0, not train)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = seed_dropout(x, dropout_rng, 0.5, 1, not train)
        return nn.Dense(self.output_dim)(x)

    def _call_packed(self, x, train: bool, dropout_rng):
        """fedpack body (x [K, N, 28, 28, 1] or [K, N, 784] lane-major;
        dropout_rng the [K] vector of per-lane batch keys): same submodule
        call order as the per-client body, so the parameter tree is the
        standard tree with a leading K axis; lane l's dropout masks are
        bit-identical to the per-client body's under dropout_rng[l]."""
        from fedml_tpu.ops.packed_conv import Conv as PConv
        from fedml_tpu.ops.packed_conv import Dense as PDense
        from fedml_tpu.ops.packed_conv import lane_dropout

        if x.ndim == 3:  # [K, N, 784] -> [K, N, 28, 28, 1]
            x = x.reshape(x.shape[:2] + (28, 28, 1))
        k = x.shape[0]

        def pool(y):
            flat = y.reshape((-1,) + y.shape[2:])
            flat = nn.max_pool(flat, (2, 2), strides=(2, 2))
            return flat.reshape((k, -1) + flat.shape[1:])

        x = nn.relu(PConv(32, 3, padding="VALID", impl=self.packed_impl)(x))
        x = nn.relu(PConv(64, 3, padding="VALID", impl=self.packed_impl)(x))
        x = pool(x)
        x = lane_dropout(x, dropout_rng, 0.25, 0, not train)
        x = x.reshape(x.shape[:2] + (-1,))
        x = nn.relu(PDense(128)(x))
        x = lane_dropout(x, dropout_rng, 0.5, 1, not train)
        return PDense(self.output_dim)(x)


@register_model("cnn")
def _cnn(output_dim: int, **_):
    bundle = ModelBundle(
        name="cnn",
        module=CNNOriginalFedAvg(output_dim),
        input_shape=(28, 28, 1),
    )
    # fedpack hook (ops/packed_conv.py): train-only lane-major twin for the
    # packed schedule's joint-lane program (--packed_conv)
    bundle.packed_variant = lambda impl: ModelBundle(
        name="cnn_packed",
        module=CNNOriginalFedAvg(output_dim, conv_impl="packed",
                                 packed_impl=impl),
        input_shape=(28, 28, 1),
    )
    return bundle


@register_model("cnn_dropout")
def _cnn_dropout(output_dim: int, **_):
    bundle = ModelBundle(
        name="cnn_dropout",
        module=CNNDropOut(output_dim),
        input_shape=(28, 28, 1),
        uses_dropout=True,
        explicit_dropout=True,
    )
    # fedpack hook: explicit_dropout marks the twin's per-lane key stream,
    # which is what clears packed_fallback_reason's dropout gate
    bundle.packed_variant = lambda impl: ModelBundle(
        name="cnn_dropout_packed",
        module=CNNDropOut(output_dim, conv_impl="packed", packed_impl=impl),
        input_shape=(28, 28, 1),
        uses_dropout=True,
        explicit_dropout=True,
    )
    return bundle
