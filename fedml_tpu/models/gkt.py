"""Group-Knowledge-Transfer split ResNet pair (client edge net + server net).

Counterpart of reference fedml_api/model/cv/resnet56_gkt/{resnet_client.py,
resnet_server.py}: the client runs a small ResNet-8-style net that returns
BOTH its auxiliary logits and the extracted feature map
(resnet_client.py:189-203 returns ``logits, extracted_features``); the server
runs the remaining ResNet-56-style stages taking that feature map as input
(resnet_server.py:185+).

TPU design: both halves are flax modules over NHWC feature maps; the client
half is small enough to ``vmap`` a whole cohort of per-client models on one
chip, and the server half trains on the union of all clients' features as one
large dense batch — the MXU-friendly re-expression of the reference's
DataParallel server loop (GKTServerTrainer.py:28-29).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.resnet import BasicBlock


class GKTClientNet(nn.Module):
    """Edge net: stem + `blocks` 16-filter BasicBlocks; returns
    (aux_logits, feature_map[B,32,32,16])."""

    blocks: int = 3
    output_dim: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=self.dtype)(x))
        for _ in range(self.blocks):
            x = BasicBlock(16, 1, dtype=self.dtype)(x, train=train)
        features = x
        pooled = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.output_dim, dtype=jnp.float32)(pooled.astype(jnp.float32))
        return logits, features


class GKTServerNet(nn.Module):
    """Server net: consumes the client feature map [B,32,32,16] and runs the
    32- and 64-filter stages (strided) + classifier head."""

    blocks_per_stage: int = 9
    output_dim: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, f, train: bool = False):
        x = f.astype(self.dtype)
        for stage, filters in enumerate((32, 64)):
            for block in range(self.blocks_per_stage):
                strides = 2 if block == 0 else 1
                x = BasicBlock(filters, strides, dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))


@dataclass
class GKTHalfBundle:
    """init/apply pure-function wrapper for one half of the split pair
    (plays the ModelBundle role; separate class because the client half
    returns a (logits, features) tuple)."""

    name: str
    module: nn.Module
    input_shape: tuple
    input_dtype: Any = jnp.float32

    def init(self, rng: jax.Array, batch_size: int = 2) -> dict:
        x = jnp.zeros((batch_size,) + tuple(self.input_shape), self.input_dtype)
        return self.module.init({"params": rng}, x, train=False)

    def apply_train(self, variables: dict, x: jax.Array):
        out, updated = self.module.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        new_vars = dict(variables)
        new_vars.update(updated)
        return out, new_vars

    def apply_eval(self, variables: dict, x: jax.Array):
        return self.module.apply(variables, x, train=False)


@dataclass
class GKTPair:
    client: GKTHalfBundle
    server: GKTHalfBundle
    feature_shape: tuple          # single-example feature-map shape


def gkt_blocks_from_names(model_client: str, model_server: str) -> tuple:
    """--model_client/--model_server (reference names ``resnet8`` /
    ``resnet56_server``) -> (client_blocks, server_blocks_per_stage).

    The client half is a single-stage CIFAR ResNet, depth = 2n + 2, so
    resnet8 -> 3 blocks; the server half is the standard 3-stage CIFAR
    ResNet, depth = 6n + 2, so resnet56_server -> 9 blocks per stage.
    """
    def depth(name: str) -> int:
        m = re.search(r"(\d+)", name)
        if not m:
            raise ValueError(f"cannot parse a ResNet depth out of {name!r}")
        return int(m.group(1))

    client_blocks = max((depth(model_client) - 2) // 2, 1)
    server_blocks = max((depth(model_server) - 2) // 6, 1)
    return client_blocks, server_blocks


def create_gkt_pair(
    output_dim: int = 10,
    input_shape: tuple = (32, 32, 3),
    client_blocks: int = 3,
    server_blocks_per_stage: int = 9,
    dtype=jnp.float32,
) -> GKTPair:
    """Defaults mirror the reference pair resnet8_56 (client,
    resnet_client.py:230) + resnet56_server (resnet_server.py); pass smaller
    block counts for CI-sized nets."""
    feature_shape = tuple(input_shape[:-1]) + (16,)
    return GKTPair(
        client=GKTHalfBundle(
            name="gkt_client",
            module=GKTClientNet(client_blocks, output_dim, dtype=dtype),
            input_shape=tuple(input_shape),
        ),
        server=GKTHalfBundle(
            name="gkt_server",
            module=GKTServerNet(server_blocks_per_stage, output_dim, dtype=dtype),
            input_shape=feature_shape,
        ),
        feature_shape=feature_shape,
    )
