"""ResNet-18 with GroupNorm for fed_cifar100 cross-device FedAvg.

Counterpart of reference fedml_api/model/cv/resnet_gn.py +
cv/group_normalization.py: the TFF baseline replaces BatchNorm with
GroupNorm(2 groups) so there is no cross-client batch statistic — the right
choice for federated averaging and also stateless (pure params) on TPU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models import ModelBundle, register_model


class GNBasicBlock(nn.Module):
    filters: int
    strides: int = 1
    groups: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        gn = partial(nn.GroupNorm, num_groups=self.groups, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides), padding="SAME")(x)
        y = nn.relu(gn()(y))
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = gn()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides))(x)
            residual = gn()(residual)
        return nn.relu(y + residual)


class ResNet18GN(nn.Module):
    output_dim: int = 100
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    groups: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.GroupNorm(num_groups=self.groups, dtype=self.dtype)(x))
        for stage, (filters, nblocks) in enumerate(zip((64, 128, 256, 512), self.stage_sizes)):
            for block in range(nblocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = GNBasicBlock(filters, strides, self.groups, dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.output_dim, dtype=jnp.float32)(x.astype(jnp.float32))


@register_model("resnet18_gn")
def _resnet18_gn(output_dim: int, dtype=jnp.float32, **_):
    return ModelBundle(
        name="resnet18_gn",
        module=ResNet18GN(output_dim, dtype=dtype),
        input_shape=(24, 24, 3),  # fed_cifar100 crops to 24x24 (TFF preprocessing)
    )
