"""Flax wrapper for the Pallas fused BatchNorm(+ReLU) kernel.

Drop-in for ``nn.BatchNorm`` on the train path: identical leaf names
("scale"/"bias" params, "mean"/"var" batch_stats with the same momentum
update) and identical shapes — only the module-path prefix differs
(``PallasBatchNorm_i`` vs ``BatchNorm_i``), so the A/B is a constructor
flag (models/resnet.py ``bn_impl``) with equal parameter counts. Eval
(running-average) mode is a plain elementwise pass — nothing to fuse
beyond what XLA already does.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from fedml_tpu.ops.batchnorm import fused_bn_relu


class PallasBatchNorm(nn.Module):
    use_running_average: bool
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    fuse_relu: bool = False

    @nn.compact
    def __call__(self, x):
        C = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones_init(), (C,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (C,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((C,), jnp.float32))
        if self.use_running_average:
            y = (x.astype(jnp.float32) - ra_mean.value) \
                * jax.lax.rsqrt(ra_var.value + self.epsilon) * scale + bias
            if self.fuse_relu:
                y = nn.relu(y)
            return y.astype(self.dtype or x.dtype)
        y, mean, var = fused_bn_relu(x, scale, bias, self.epsilon,
                                     self.fuse_relu)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return y
