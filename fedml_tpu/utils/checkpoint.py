"""Checkpoint/resume.

The reference has NO general checkpoint mechanism (SURVEY.md §5.4): the silo
fork duck-types ``save_model`` per validation round (silo_fedavg.py:82-92)
and nothing can resume. Here any training state (variables + server state +
round index + config) round-trips through one file, using the same
self-describing pytree wire format as the edge transport.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from fedml_tpu.core.serialization import (
    frame_pack,
    frame_unpack,
    tree_from_bytes,
    tree_to_bytes,
)

_MAGIC = b"FTCKPT1"


def save_checkpoint(path: str, variables: Any, server_state: Any = None,
                    round_idx: int = 0, extra: Optional[dict] = None) -> None:
    payload = tree_to_bytes({"variables": variables, "server_state": server_state or {}})
    buf = frame_pack(_MAGIC, {"round_idx": round_idx, "extra": extra or {}}, payload)
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(buf)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint


def load_checkpoint(path: str) -> dict:
    with open(path, "rb") as f:
        buf = f.read()
    meta, off = frame_unpack(_MAGIC, buf)
    tree = tree_from_bytes(buf[off:])
    return {
        "variables": tree["variables"],
        "server_state": tree["server_state"],
        "round_idx": meta["round_idx"],
        "extra": meta["extra"],
    }


# --- orbax path: sharded/multi-host checkpoints ----------------------------
#
# The binary format above gathers arrays to host — right for single-host and
# for shipping over the edge transport, wrong for pod-scale state that lives
# sharded over a Mesh. Orbax writes each shard from its owning host and
# restores with the original shardings, which is the TPU-native answer the
# reference (no checkpointing at all, SURVEY.md §5.4) never needed.

def save_checkpoint_orbax(path: str, variables: Any, server_state: Any = None,
                          round_idx: int = 0) -> None:
    """Sharded checkpoint via orbax; ``path`` becomes a directory."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(
        os.path.abspath(path),
        {"variables": variables, "server_state": server_state or {},
         "round_idx": round_idx},
        force=True,
    )
    ckptr.wait_until_finished()


def load_checkpoint_orbax(path: str, template: Any = None) -> dict:
    """Restore an orbax checkpoint; ``template`` (matching pytree of arrays
    or ShapeDtypeStructs with shardings) restores onto the original mesh."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    target = None
    if template is not None:
        target = {"variables": template.get("variables"),
                  "server_state": template.get("server_state", {}),
                  "round_idx": 0}
    out = ckptr.restore(os.path.abspath(path), target)
    return {"variables": out["variables"], "server_state": out["server_state"],
            "round_idx": int(out["round_idx"]), "extra": {}}
