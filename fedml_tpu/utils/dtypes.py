"""Host-side dtype policy helpers shared across trainers."""

from __future__ import annotations

import numpy as np


def host_bf16_cast(x: np.ndarray, config_dtype: str) -> np.ndarray:
    """Cast float train data to bf16 ON HOST when training in bf16 — the
    cast happens before device_put so each shard ships straight to its
    device (a jnp cast would materialize the full array on one device
    first). No-op for non-float data or non-bf16 configs."""
    if config_dtype == "bfloat16" and np.issubdtype(x.dtype, np.floating):
        import ml_dtypes

        return x.astype(ml_dtypes.bfloat16)
    return x
