"""Observability: round timing, metric logging, profiler hooks.

The reference's observability is wandb-everywhere (init on rank 0,
main_fedavg.py:300-308; Train/Acc, Train/Loss, Test/Acc, Test/Loss keyed by
round, fedavg_api.py:173-179) plus wall-clock pairs around aggregation
(FedAVGAggregator.py:59,85-86) and setproctitle. SURVEY.md §5.1 asks the
TPU build to make per-round timing and rounds/sec FIRST-CLASS, and to hook
the jax profiler.

This module provides:
- :class:`RoundTimer` — per-phase wall-clock sums (train/aggregate/eval) and
  rounds/sec, cheap enough to always run,
- :class:`MetricsLogger` — wandb-compatible metric names; logs to an
  in-memory history + optional JSONL file + optional wandb (import-gated:
  this environment has no wandb and no egress),
- :func:`profile_trace` — context manager around ``jax.profiler.trace`` for
  TensorBoard-consumable device traces.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from collections import defaultdict
from typing import Optional

log = logging.getLogger(__name__)


class RoundTimer:
    """Accumulates per-phase seconds; `with timer.phase("train"): ...`."""

    def __init__(self):
        self.sums: dict[str, float] = defaultdict(float)
        self.rounds = 0
        self._start = time.time()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.sums[name] += time.perf_counter() - t0

    def tick_round(self):
        self.rounds += 1

    def summary(self) -> dict:
        wall = max(time.time() - self._start, 1e-9)
        out = {f"time/{k}_s": round(v, 4) for k, v in self.sums.items()}
        out["time/wall_s"] = round(wall, 4)
        out["rounds_per_sec"] = round(self.rounds / wall, 4) if self.rounds else 0.0
        return out


def round_stats(rows, depth: int = 0) -> dict:
    """Aggregate per-round stage timings for the host round pipeline
    (data/pipeline.CohortPrefetcher) into one observability record.

    Each row is one executed round: ``materialize_ms`` (host cohort
    materialization + cast), ``h2d_ms`` (host->device transfer),
    ``compute_ms`` (the blocking round-step call), ``wait_ms`` (how long
    the consumer actually blocked on the round's inputs — the EXPOSED part
    of the host stages; the serial path records wait = materialize + h2d
    since nothing overlaps there).

    ``overlap_frac`` is the share of host-stage milliseconds hidden behind
    device compute: 1 - wait/(materialize + h2d). 0 on the serial path by
    construction; -> 1 as the pipeline fully hides cohort preparation."""
    rows = list(rows)
    keys = ("materialize_ms", "h2d_ms", "compute_ms", "wait_ms")
    if not rows:
        return {"rounds": 0, "pipeline_depth": int(depth), "overlap_frac": 0.0,
                **{k: 0.0 for k in keys}}
    tot = {k: float(sum(r.get(k, 0.0) for r in rows)) for k in keys}
    host = tot["materialize_ms"] + tot["h2d_ms"]
    overlap = max(0.0, 1.0 - tot["wait_ms"] / host) if host > 0 else 0.0
    out = {k: round(tot[k] / len(rows), 3) for k in keys}
    out["rounds"] = len(rows)
    out["pipeline_depth"] = int(depth)
    out["overlap_frac"] = round(overlap, 4)
    return out


class MetricsLogger:
    """wandb-compatible logger with gated backends.

    Names follow the reference exactly ('Train/Acc', 'Test/Acc', 'Test/Loss'
    keyed by 'round', fedavg_api.py:173-179; per-client 'Client.<id>' and
    'GLOBAL' in the silo fork, silo_fedavg.py:126-127)."""

    def __init__(
        self,
        run_name: str = "fedml_tpu",
        enable_wandb: bool = False,
        jsonl_path: Optional[str] = None,
        config: Optional[dict] = None,
    ):
        self.history: list[dict] = []
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self._wandb = None
        if enable_wandb:
            try:
                import wandb

                self._wandb = wandb
                wandb.init(project=run_name, config=config or {})
            except ImportError:
                log.warning("wandb requested but not installed; logging locally only")

    def log(self, metrics: dict, round_idx: Optional[int] = None):
        rec = dict(metrics)
        if round_idx is not None:
            rec["round"] = round_idx
        self.history.append(rec)
        if self._jsonl:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self._wandb:
            self._wandb.log(rec)
        log.info("metrics %s", rec)

    def last(self, key: str):
        for rec in reversed(self.history):
            if key in rec:
                return rec[key]
        return None

    def series(self, key: str) -> list:
        return [r[key] for r in self.history if key in r]

    def close(self):
        if self._jsonl:
            self._jsonl.close()
        if self._wandb:
            self._wandb.finish()


def wire_stats(comm) -> dict:
    """Flatten the retry/dedup/drop counters of a wire middleware stack
    (comm/reliable.py over comm/chaos.py over a bare transport) into
    wandb-style keys — ``wire/retransmits``, ``wire/dup_dropped``,
    ``chaos/dropped``, ... — so a lossy run is diagnosable from the same
    metrics surface as everything else. A bare transport (no wrappers)
    yields {}; counters are read without locks (monotonic ints, summary
    use only)."""
    from fedml_tpu.comm.chaos import ChaosCommManager
    from fedml_tpu.comm.reliable import ReliableCommManager

    out: dict = {}
    node = comm
    while node is not None:
        prefix = ("wire" if isinstance(node, ReliableCommManager)
                  else "chaos" if isinstance(node, ChaosCommManager)
                  else None)
        if prefix is not None:
            for k, v in getattr(node, "stats", {}).items():
                key = f"{prefix}/{k}"
                out[key] = out.get(key, 0) + v
        node = getattr(node, "inner", None)
    return out


def merge_wire_stats(comms) -> dict:
    """Sum wire_stats across a federation's managers (one entry per rank)."""
    total: dict = {}
    for c in comms:
        for k, v in wire_stats(c).items():
            total[k] = total.get(k, 0) + v
    return total


def notify_sweep_complete(pipe_path: Optional[str] = None) -> bool:
    """Signal an external sweep orchestrator that this run finished.

    Counterpart of the reference's ``post_complete_message_to_sweep_process``
    (fedavg/utils.py:19-26: open a FIFO ``./tmp/fedml`` and write
    'training is finished!'). Path comes from the FEDML_SWEEP_PIPE env var
    (or the argument); no-op when unset or the FIFO has no reader — a
    missing orchestrator must never block or fail training. Returns
    whether the message was written."""
    import errno
    import os

    path = pipe_path or os.environ.get("FEDML_SWEEP_PIPE")
    if not path:
        return False
    try:
        # O_NONBLOCK: never hang when no sweep process is reading
        fd = os.open(path, os.O_WRONLY | os.O_NONBLOCK)
    except OSError as e:
        if e.errno != errno.ENXIO:  # ENXIO = FIFO exists but no reader
            log.debug("sweep pipe %s unavailable: %s", path, e)
        return False
    try:
        os.write(fd, b"training is finished!\n")
        return True
    except OSError:
        return False
    finally:
        os.close(fd)


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]):
    """Wrap a region in a jax profiler trace (TensorBoard format). No-op
    when logdir is falsy, so call sites need no gating."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
