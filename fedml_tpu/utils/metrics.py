"""Observability: round timing, metric logging, profiler hooks.

The reference's observability is wandb-everywhere (init on rank 0,
main_fedavg.py:300-308; Train/Acc, Train/Loss, Test/Acc, Test/Loss keyed by
round, fedavg_api.py:173-179) plus wall-clock pairs around aggregation
(FedAVGAggregator.py:59,85-86) and setproctitle. SURVEY.md §5.1 asks the
TPU build to make per-round timing and rounds/sec FIRST-CLASS, and to hook
the jax profiler.

This module provides:
- :class:`RoundTimer` — per-phase wall-clock sums (train/aggregate/eval) and
  rounds/sec, cheap enough to always run,
- :class:`MetricsLogger` — wandb-compatible metric names; logs to an
  in-memory history + optional JSONL file + optional wandb (import-gated:
  this environment has no wandb and no egress),
- :func:`profile_trace` — context manager around ``jax.profiler.trace`` for
  TensorBoard-consumable device traces.

Since the fedtrace PR these surfaces are VIEWS over the unified registry
(fedml_tpu/obs, DESIGN.md §12): ``RoundTimer.sums`` is a ``CounterGroup``
attached to the process registry's ``time`` namespace, phase blocks emit
tracer spans when tracing is on, and ``wire_stats`` reads counter groups
the reliable/chaos managers attach under ``wire``/``chaos``. Public
signatures and metric key names are unchanged.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Optional

log = logging.getLogger(__name__)


class RoundTimer:
    """Accumulates per-phase seconds; `with timer.phase("train"): ...`.

    Phase sums live in a ``CounterGroup`` under the unified registry's
    ``time`` namespace (``rank`` tags whose wall clock this is in a
    multi-rank process); each phase block also opens a tracer span, so the
    same instrumentation feeds the summary dict AND the trace timeline."""

    def __init__(self, rank: int = 0):
        from fedml_tpu.obs import default_registry

        self.rank = int(rank)
        self.sums = default_registry().group("time", rank=self.rank)
        self.rounds = 0
        # monotonic base: time.time() is NTP-step sensitive, and summary()
        # divides phase sums measured on perf_counter by this wall — mixing
        # clock domains made rounds_per_sec wrong across a clock step
        self._start = time.perf_counter()

    @contextlib.contextmanager
    def phase(self, name: str):
        from fedml_tpu.obs import tracer_if_enabled

        tr = tracer_if_enabled(self.rank)
        t0 = time.perf_counter()
        try:
            if tr is None:
                yield
            else:
                with tr.span(name, cat="phase"):
                    yield
        finally:
            self.sums[name] = self.sums.get(name, 0.0) + (
                time.perf_counter() - t0)

    def tick_round(self):
        self.rounds += 1

    def summary(self) -> dict:
        wall = max(time.perf_counter() - self._start, 1e-9)
        out = {f"time/{k}_s": round(v, 4) for k, v in self.sums.items()}
        out["time/wall_s"] = round(wall, 4)
        out["rounds_per_sec"] = round(self.rounds / wall, 4) if self.rounds else 0.0
        return out


def round_stats(rows, depth: int = 0) -> dict:
    """Aggregate per-round stage timings for the host round pipeline
    (data/pipeline.CohortPrefetcher) into one observability record.

    Each row is one executed round: ``materialize_ms`` (host cohort
    materialization + cast), ``h2d_ms`` (host->device transfer),
    ``compute_ms`` (the blocking round-step call), ``wait_ms`` (how long
    the consumer actually blocked on the round's inputs — the EXPOSED part
    of the host stages; the serial path records wait = materialize + h2d
    since nothing overlaps there).

    ``overlap_frac`` is the share of host-stage milliseconds hidden behind
    device compute: 1 - wait/(materialize + h2d). 0 on the serial path by
    construction; -> 1 as the pipeline fully hides cohort preparation."""
    rows = list(rows)
    keys = ("materialize_ms", "h2d_ms", "compute_ms", "wait_ms")
    if not rows:
        return {"rounds": 0, "pipeline_depth": int(depth), "overlap_frac": 0.0,
                **{k: 0.0 for k in keys}}
    tot = {k: float(sum(r.get(k, 0.0) for r in rows)) for k in keys}
    host = tot["materialize_ms"] + tot["h2d_ms"]
    overlap = max(0.0, 1.0 - tot["wait_ms"] / host) if host > 0 else 0.0
    out = {k: round(tot[k] / len(rows), 3) for k in keys}
    out["rounds"] = len(rows)
    out["pipeline_depth"] = int(depth)
    out["overlap_frac"] = round(overlap, 4)
    return out


class MetricsLogger:
    """wandb-compatible logger with gated backends.

    Names follow the reference exactly ('Train/Acc', 'Test/Acc', 'Test/Loss'
    keyed by 'round', fedavg_api.py:173-179; per-client 'Client.<id>' and
    'GLOBAL' in the silo fork, silo_fedavg.py:126-127).

    Usable as a context manager (the JSONL handle is guaranteed closed even
    when the run raises); ``history_cap`` bounds the in-memory history like
    the tracer's ring buffer — a weeks-long federation keeps the latest N
    records instead of growing without bound."""

    def __init__(
        self,
        run_name: str = "fedml_tpu",
        enable_wandb: bool = False,
        jsonl_path: Optional[str] = None,
        config: Optional[dict] = None,
        history_cap: Optional[int] = None,
    ):
        from collections import deque

        self.history = (deque(maxlen=int(history_cap)) if history_cap
                        else [])
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self._wandb = None
        if enable_wandb:
            try:
                import wandb

                self._wandb = wandb
                wandb.init(project=run_name, config=config or {})
            except ImportError:
                log.warning("wandb requested but not installed; logging locally only")

    def log(self, metrics: dict, round_idx: Optional[int] = None):
        rec = dict(metrics)
        if round_idx is not None:
            rec["round"] = round_idx
        self.history.append(rec)
        if self._jsonl:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self._wandb:
            self._wandb.log(rec)
        log.info("metrics %s", rec)

    def log_registry(self, registry=None, round_idx: Optional[int] = None,
                     namespace: Optional[str] = None):
        """Log a snapshot of the unified registry (fedml_tpu/obs) — wire
        counters, phase sums, chaos stats — as one record, flat-keyed
        ``<namespace>/<counter>`` exactly like ``wire_stats``."""
        from fedml_tpu.obs import default_registry

        reg = registry if registry is not None else default_registry()
        snap = reg.snapshot(namespace)
        if namespace is not None:
            snap = {f"{namespace}/{k}": v for k, v in snap.items()}
        if snap:
            self.log(snap, round_idx)
        return snap

    def last(self, key: str):
        for rec in reversed(self.history):
            if key in rec:
                return rec[key]
        return None

    def series(self, key: str) -> list:
        return [r[key] for r in self.history if key in r]

    def close(self):
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
        if self._wandb:
            self._wandb.finish()
            self._wandb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        # last-resort handle close for callers that never reach close()
        # (an exception between construction and the finally); harmless
        # after an explicit close
        jsonl = getattr(self, "_jsonl", None)
        if jsonl is not None:
            try:
                jsonl.close()
            except Exception:
                pass


def wire_stats(comm) -> dict:
    """Flatten the retry/dedup/drop counters of a wire middleware stack
    (comm/reliable.py over comm/chaos.py over a bare transport) into
    wandb-style keys — ``wire/retransmits``, ``wire/dup_dropped``,
    ``chaos/dropped``, ... — so a lossy run is diagnosable from the same
    metrics surface as everything else. A bare transport (no wrappers)
    yields {}; counters are read without locks (monotonic ints, summary
    use only)."""
    from fedml_tpu.comm.chaos import ChaosCommManager
    from fedml_tpu.comm.reliable import ReliableCommManager

    out: dict = {}
    node = comm
    while node is not None:
        prefix = ("wire" if isinstance(node, ReliableCommManager)
                  else "chaos" if isinstance(node, ChaosCommManager)
                  else None)
        if prefix is not None:
            for k, v in getattr(node, "stats", {}).items():
                key = f"{prefix}/{k}"
                out[key] = out.get(key, 0) + v
        node = getattr(node, "inner", None)
    return out


def merge_wire_stats(comms) -> dict:
    """Sum wire_stats across a federation's managers (one entry per rank)."""
    total: dict = {}
    for c in comms:
        for k, v in wire_stats(c).items():
            total[k] = total.get(k, 0) + v
    return total


def notify_sweep_complete(pipe_path: Optional[str] = None) -> bool:
    """Signal an external sweep orchestrator that this run finished.

    Counterpart of the reference's ``post_complete_message_to_sweep_process``
    (fedavg/utils.py:19-26: open a FIFO ``./tmp/fedml`` and write
    'training is finished!'). Path comes from the FEDML_SWEEP_PIPE env var
    (or the argument); no-op when unset or the FIFO has no reader — a
    missing orchestrator must never block or fail training. Returns
    whether the message was written."""
    import errno
    import os

    path = pipe_path or os.environ.get("FEDML_SWEEP_PIPE")
    if not path:
        return False
    try:
        # O_NONBLOCK: never hang when no sweep process is reading
        fd = os.open(path, os.O_WRONLY | os.O_NONBLOCK)
    except OSError as e:
        if e.errno != errno.ENXIO:  # ENXIO = FIFO exists but no reader
            log.debug("sweep pipe %s unavailable: %s", path, e)
        return False
    try:
        os.write(fd, b"training is finished!\n")
        return True
    except OSError:
        return False
    finally:
        os.close(fd)


@contextlib.contextmanager
def profile_trace(logdir: Optional[str]):
    """Wrap a region in a jax profiler trace (TensorBoard format). No-op
    when logdir is falsy, so call sites need no gating."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
