"""Utilities: logging, metrics sinks (wandb-compatible), checkpointing,
timing (counterpart of fedml_api/utils + the wandb plumbing the reference
scatters through every main)."""
