"""Graph topologies for decentralized FL — weighted mixing matrices.

Counterpart of reference fedml_core/distributed/topology/:
- SymmetricTopologyManager (symmetric_topology_manager.py:21-52): ring with
  ``neighbor_num`` undirected neighbors plus Watts-Strogatz random rewiring,
  rows normalized to a doubly-stochastic-ish mixing matrix.
- AsymmetricTopologyManager (asymmetric_topology_manager.py:23-74): directed
  graph with distinct out/in degrees; row-normalized (out-weights).

The matrix IS the communication pattern: one gossip round is
``new_params = W @ stacked_params`` — a client-axis matmul XLA maps onto the
MXU, or a sequence of ``ppermute`` rounds on a real ring (SURVEY.md §2.6.6).
"""

from __future__ import annotations

import numpy as np


class BaseTopologyManager:
    """Interface parity with the reference (base_topology_manager.py:4-24)."""

    topology: np.ndarray

    def generate_topology(self) -> None:
        raise NotImplementedError

    def get_in_neighbor_weights(self, node_index: int) -> np.ndarray:
        return self.topology[:, node_index]

    def get_out_neighbor_weights(self, node_index: int) -> np.ndarray:
        return self.topology[node_index]

    def get_in_neighbor_idx_list(self, node_index: int) -> list[int]:
        col = self.topology[:, node_index]
        return [i for i in range(len(col)) if col[i] > 0 and i != node_index]

    def get_out_neighbor_idx_list(self, node_index: int) -> list[int]:
        row = self.topology[node_index]
        return [i for i in range(len(row)) if row[i] > 0 and i != node_index]


class SymmetricTopologyManager(BaseTopologyManager):
    """Undirected ring + random extra links, uniform row weights."""

    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = n
        self.neighbor_num = min(neighbor_num, max(n - 1, 1))
        self.seed = seed
        self.topology = np.zeros((n, n))

    def generate_topology(self) -> None:
        import networkx as nx

        if self.n <= 2:
            # watts_strogatz needs k>=2 edges per node; for 1-2 nodes the
            # only sensible mixing matrix is plain averaging
            self.topology = np.full((self.n, self.n), 1.0 / self.n)
            return
        k = max(self.neighbor_num, 2)
        g = nx.connected_watts_strogatz_graph(self.n, min(k, self.n - 1),
                                              p=0.3, seed=self.seed)
        adj = nx.to_numpy_array(g) + np.eye(self.n)
        adj = np.minimum(adj + adj.T, 1.0)  # symmetrize
        self.topology = adj / adj.sum(axis=1, keepdims=True)

    @property
    def mixing_matrix(self) -> np.ndarray:
        return self.topology


class AsymmetricTopologyManager(BaseTopologyManager):
    """Directed ring + random out-links; rows normalized (column sums vary —
    the PushSum correction handles that)."""

    def __init__(self, n: int, undirected_neighbor_num: int = 2,
                 out_directed_neighbor: int = 2, seed: int = 0):
        self.n = n
        self.undirected_neighbor_num = undirected_neighbor_num
        self.out_directed_neighbor = out_directed_neighbor
        self.seed = seed
        self.topology = np.zeros((n, n))

    def generate_topology(self) -> None:
        rng = np.random.default_rng(self.seed)
        n = self.n
        adj = np.eye(n)
        for i in range(n):
            # directed ring links
            for d in range(1, self.undirected_neighbor_num + 1):
                adj[i, (i + d) % n] = 1.0
            # random extra out-links
            extra = rng.choice(n, size=min(self.out_directed_neighbor, n - 1), replace=False)
            for j in extra:
                if j != i:
                    adj[i, j] = 1.0
        self.topology = adj / adj.sum(axis=1, keepdims=True)

    @property
    def mixing_matrix(self) -> np.ndarray:
        return self.topology
