"""Peer-to-peer message-driven template over a topology (no server).

Reference: fedml_api/distributed/decentralized_framework/
decentralized_worker_manager.py:8-56 — each worker trains, sends its result
to its out-neighbors (:41-46), and advances the round when all in-neighbor
results have arrived (:29-39), with mixing weights from the topology matrix
row. The gossip MATH for the in-mesh paradigm lives in
algorithms/decentralized.py (mixing-matrix matmul); this module is the
edge-transport variant for workers that are genuinely separate processes.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

import numpy as np

from fedml_tpu.comm import ClientManager, Message
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.distributed.topology import SymmetricTopologyManager

LOG = logging.getLogger(__name__)

MSG_TYPE_SEND_MSG_TO_NEIGHBOR = 7
MSG_ARG_KEY_PARAMS = "params"


class DecentralizedWorkerManager(ClientManager):
    """One gossip worker; reference decentralized_worker_manager.py:8-56."""

    def __init__(self, args, comm, rank, size, topology_manager, local_fn: Optional[Callable] = None):
        super().__init__(args, comm, rank, size)
        self.topology_manager = topology_manager
        self.comm_round = int(args.comm_round)
        self.round_idx = 0
        # local "training": (round_idx, mixed_state) -> new local state (pytree)
        self.local_fn = local_fn or (lambda r, s: s)
        self.local_state = np.asarray([float(rank)], np.float32)
        # round -> {sender -> params}: a fast neighbor may already be in round
        # r+1 while we're in r; buffering per round keeps the barrier exact
        # (the reference is implicitly synchronized by MPI rank lockstep).
        self.neighbor_results: dict[int, dict[int, object]] = {}
        self.history: list[np.ndarray] = []

    @property
    def in_neighbors(self) -> list[int]:
        w = self.topology_manager.get_in_neighbor_weights(self.rank)
        return [j for j, wt in enumerate(w) if wt > 0 and j != self.rank]

    @property
    def out_neighbors(self) -> list[int]:
        w = self.topology_manager.get_out_neighbor_weights(self.rank)
        return [j for j, wt in enumerate(w) if wt > 0 and j != self.rank]

    def run(self):
        self.register_message_receive_handlers()
        self.start_training()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_SEND_MSG_TO_NEIGHBOR, self.handle_msg_from_neighbor)

    def start_training(self):
        self.local_state = self.local_fn(self.round_idx, self.local_state)
        self._send_to_neighbors()

    def _send_to_neighbors(self):
        for j in self.out_neighbors:
            m = Message(MSG_TYPE_SEND_MSG_TO_NEIGHBOR, self.rank, j)
            m.add_params(MSG_ARG_KEY_PARAMS, self.local_state)
            m.add_params("round", self.round_idx)
            self.send_message(m)
        # degenerate topology (no neighbors): round completes immediately
        self._maybe_finish_round()

    def handle_msg_from_neighbor(self, msg: Message):
        r = int(msg.get("round"))
        self.neighbor_results.setdefault(r, {})[msg.get_sender_id()] = msg.get(MSG_ARG_KEY_PARAMS)
        self._maybe_finish_round()

    def _maybe_finish_round(self):
        current = self.neighbor_results.setdefault(self.round_idx, {})
        if len(current) < len(self.in_neighbors):
            return
        # mix with the ROW of the mixing matrix: x_i <- sum_j W[i,j] x_j
        # (symmetric_topology_manager.py:54-62), renormalized over the
        # senders actually present — for a symmetric topology this is a
        # no-op (row support == in-support), while for an asymmetric one it
        # keeps the mixing mass at 1 (plain row weights would leak mass and
        # drain states toward zero; unbiased asymmetric gossip is PushSum,
        # algorithms/decentralized.py).
        weights = np.asarray(self.topology_manager.topology[self.rank], np.float32)
        mass = weights[self.rank] + sum(weights[j] for j in current)
        mixed = (weights[self.rank] / mass) * np.asarray(self.local_state, np.float32)
        for j, res in current.items():
            mixed = mixed + (weights[j] / mass) * np.asarray(res, np.float32)
        del self.neighbor_results[self.round_idx]
        self.history.append(mixed)
        self.round_idx += 1
        if self.round_idx >= self.comm_round:
            self.finish()
            return
        self.local_state = self.local_fn(self.round_idx, mixed)
        self._send_to_neighbors()


def run_decentralized_framework(worker_num: int, comm_round: int = 3, neighbor_num: int = 2,
                                wire_roundtrip: bool = True, config=None):
    """In-process gossip launch; returns the per-worker mixed histories.

    With a doubly-stochastic symmetric topology the mixed values converge to
    the global mean — the property the test asserts.

    ``config`` layers the reliable/chaos wire middleware over the transport
    (closing the ROADMAP wire-reliability gap for this protocol): gossip
    advances each worker's round by counting in-neighbor messages, so a
    single dropped neighbor result hangs the whole mesh — exactly the
    barrier the reliable layer exists to protect.
    """
    from fedml_tpu.comm.reliable import wire_wrap_factory
    from fedml_tpu.obs import configure_from

    class Args:
        pass

    args = Args()
    args.comm_round = comm_round
    topo = SymmetricTopologyManager(worker_num, neighbor_num=neighbor_num, seed=0)
    topo.generate_topology()
    if config is not None:
        configure_from(config)

    def make(rank, comm):
        return DecentralizedWorkerManager(args, comm, rank, worker_num, topo)

    managers = run_ranks(make, worker_num, wire_roundtrip=wire_roundtrip,
                         wrap=wire_wrap_factory(config) if config is not None
                         else None)
    return [m.history for m in managers]
