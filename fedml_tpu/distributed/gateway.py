"""fedgate: multi-tenant federation gateway (DESIGN.md §19).

One long-lived gateway process multiplexes N concurrent federations
("tenants") over ONE shared transport listener — the deployment shape a
fleet operator actually runs (one ingress, many model programs), where the
tree previously assumed one process per federation. Three pillars:

- **Tenant isolation.** Every envelope carries ``__tenant__`` (stamped by
  ``_ManagerBase.send_message`` and backstopped by the worker-side
  :class:`~fedml_tpu.comm.flow.TenantChannel` for layer-generated acks).
  The :class:`GatewayMux` routes by tenant into per-tenant handler lanes,
  each with its OWN reliable-layer state, its own
  :class:`~fedml_tpu.obs.registry.MetricsRegistry` (every counter surface
  the lane touches attaches there via ``registry_scope``), its own pulse
  stream (``pulse-<tenant>.jsonl``) and its own delta-baselined watchdog.
  A tenant whose watchdog escalates (NaN/divergent loss, gave-up storm,
  version lag) is QUARANTINED: its lane drains, its workers get a terminal
  eviction, its dedup windows and pending maps are released — while every
  other tenant continues bit-identically to a standalone run (pinned by
  tests/test_gateway.py).
- **Backpressure.** Lane inboxes are bounded (``--wire_inbox_cap``). Over
  the high-water mark the mux answers WIRE_BUSY with a retry-after derived
  from the tenant's ``retry_budget_s``; the sender's reliable layer holds
  the message and backs off without burning retries (busy is not dead).
- **Load-shedding + admission.** ``--gateway_max_tenants`` /
  ``--gateway_tenant_workers`` quotas reject over-admission with a typed
  terminal NACK. When a lane is full, the shed policy evicts the queued
  upload with the strictly-oldest round tag first — counted on the
  tenant's wire lane, never silently (the evicted sender is busy-notified
  and retransmits).

The lanes run the UNMODIFIED edge protocol stack: tenant-local rank space
(server 0, workers 1..W), the same ``build_edge_rank`` construction as
``run_fedavg_edge`` — the gateway is pure routing + flow control, which is
what makes the bit-identity pin possible.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.flow import (
    MSG_ARG_KEY_GW_SRC,
    BoundedInbox,
    TenantChannel,
    TenantLink,
)
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_TENANT,
    MSG_ARG_KEY_WIRE_MID,
    MSG_TYPE_WIRE_ACK,
    MSG_TYPE_WIRE_BUSY,
    Message,
)
from fedml_tpu.comm.reliable import (
    KEY_BUSY_MID,
    KEY_BUSY_REASON,
    KEY_BUSY_RETRY_S,
    KEY_BUSY_TERMINAL,
    ReliableCommManager,
    build_wire_stack,
    retry_budget_s,
    retry_schedule,
)
from fedml_tpu.obs import (
    HealthWatchdog,
    LiveExporter,
    PulsePlane,
    FederationHealthError,
    MetricsRegistry,
    plane_scope,
    registry_scope,
)
from fedml_tpu.obs.profile import ClientProfiler

LOG = logging.getLogger(__name__)

#: round tag key (fedavg_edge.MSG_ARG_KEY_ROUND; literal to keep comm-layer
#: imports out of the shed path) — the shed policy orders uploads by it
_KEY_ROUND = "round_idx"


class TenantLane:
    """One tenant's gateway-side state: registry, pulse plane, bounded
    inbox, wire-lane counters, worker global ranks, quarantine flag."""

    def __init__(self, tenant: str, config, worker_num: int, base_rank: int,
                 inbox_cap: int, pulse_path: Optional[str]):
        self.tenant = str(tenant)
        self.config = config
        self.worker_num = int(worker_num)
        self.base_rank = int(base_rank)
        self.quarantined = False
        self.error: Optional[str] = None
        self.registry = MetricsRegistry()
        self.inbox = BoundedInbox(cap=inbox_cap)
        # the mux's per-tenant counters live on THIS tenant's wire lane so
        # its pulse snapshots and the cross-tenant leakage pin both see them
        self.wire = self.registry.group("wire", rank=0, keys=(
            "gw_enqueued", "gw_dup_suppressed", "gw_busy_sent",
            "gw_shed_stale", "gw_drained", "gw_inbox_peak"))
        # derived push-back delay: roughly the mean backoff of the tenant's
        # retry schedule — long enough to let the lane drain, short enough
        # that a held upload lands well inside the retry budget
        _, _, retry_max = retry_schedule(config)
        self.retry_after_s = retry_budget_s(config) / max(1, retry_max + 1)
        self.pulse_path = pulse_path
        exporter = LiveExporter(pulse_path) if pulse_path else None
        profiler = ClientProfiler() if exporter is not None else None
        # escalation is ALWAYS on at the gateway: a critical tenant is
        # quarantined (lane-local), never allowed to take the process down —
        # the per-run --health_escalate flag governs standalone runs only
        watchdog = HealthWatchdog(
            loss_limit=getattr(config, "health_loss_limit", 0.0),
            stall_sec=getattr(config, "health_stall_sec", None),
            stale_spike=getattr(config, "health_stale_spike", 8),
            skew=getattr(config, "health_skew", 4.0),
            version_lag=getattr(config, "health_version_lag", 0.0),
            escalate=True)
        watchdog.baseline(self.registry.snapshot("wire"))
        self.plane = PulsePlane(exporter=exporter, profiler=profiler,
                                watchdog=watchdog, registry=self.registry)
        # fedflight tenant scoping: the recorder keys this lane's round
        # window (and any quarantine bundle) to the tenant id, so one
        # tenant's incident never interleaves another's rounds
        self.plane.tenant = self.tenant
        self.aggregator = None
        self.comm: Optional[BaseCommunicationManager] = None

    @property
    def worker_global_ranks(self) -> List[int]:
        return [self.base_rank + r for r in range(1, self.worker_num + 1)]


class GatewayMux(Observer):
    """Observer of the gateway's shared transport (global rank 0): routes
    by tenant into lanes, answers over-cap traffic with WIRE_BUSY, sheds
    stale uploads, NACKs unknown/rejected/quarantined tenants. Runs on the
    single gateway receive thread; lane threads only ever TAKE from the
    inboxes, so the routing path is lock-light."""

    def __init__(self, transport: BaseCommunicationManager,
                 registry: MetricsRegistry):
        self.transport = transport
        self.lanes: Dict[str, TenantLane] = {}
        self.rejected: Dict[str, str] = {}
        # gateway-level (cross-tenant) counters: admission rejections and
        # untagged drops belong to the gateway, not to any tenant registry
        self.stats = registry.group("gateway", rank=0, keys=(
            "routed", "untagged_dropped", "nack_unknown", "nack_rejected",
            "nack_quarantined", "no_reply_addr"))

    # -- routing -----------------------------------------------------------
    def receive_message(self, msg_type, msg: Message) -> None:
        tenant = msg.get(MSG_ARG_KEY_TENANT)
        if tenant is None:
            # a tenant-less envelope cannot be routed; counted, never silent
            self.stats["untagged_dropped"] += 1
            LOG.warning("gateway: dropped untagged %r", msg_type)
            return
        lane = self.lanes.get(tenant)
        if lane is None:
            reason = self.rejected.get(tenant)
            if reason is None:
                self.stats["nack_unknown"] += 1
                reason = f"unknown tenant {tenant!r}"
            else:
                self.stats["nack_rejected"] += 1
            self._nack(msg, reason)
            return
        if lane.quarantined:
            self.stats["nack_quarantined"] += 1
            self._nack(msg, f"tenant {tenant!r} quarantined")
            return
        self.stats["routed"] += 1
        if msg_type == MSG_TYPE_WIRE_ACK:
            # acks must flow even through a full lane, or backpressure on
            # uploads would also stall the ack stream that relieves it
            lane.inbox.put_control(msg)
            return
        mid = msg.get(MSG_ARG_KEY_WIRE_MID)
        if mid is not None and lane.inbox.has_mid(mid):
            # retransmitted copy of a still-queued (unacked) message: the
            # queued copy will be acked when the lane processes it
            lane.wire["gw_dup_suppressed"] += 1
            return
        if lane.inbox.try_put(msg):
            lane.wire["gw_enqueued"] += 1
            lane.wire["gw_inbox_peak"] = max(
                lane.wire["gw_inbox_peak"], lane.inbox.peak)
            return
        # lane over its high-water mark: shed a strictly-older queued
        # upload in favour of current-round traffic, else push back
        rnd = msg.get(_KEY_ROUND)
        victim = (lane.inbox.shed_older_than(int(rnd))
                  if rnd is not None else None)
        if victim is not None:
            lane.wire["gw_shed_stale"] += 1
            self._busy(victim, lane)   # re-arm the evicted sender's clock
            if not lane.inbox.try_put(msg):
                lane.wire["gw_busy_sent"] += 1
                self._busy(msg, lane)
            else:
                lane.wire["gw_enqueued"] += 1
        else:
            lane.wire["gw_busy_sent"] += 1
            self._busy(msg, lane)

    # -- push-back replies -------------------------------------------------
    def _reply_rank(self, msg: Message) -> Optional[int]:
        return msg.get(MSG_ARG_KEY_GW_SRC)

    def _busy(self, msg: Message, lane: TenantLane) -> None:
        src = self._reply_rank(msg)
        if src is None:
            self.stats["no_reply_addr"] += 1
            return
        out = Message(MSG_TYPE_WIRE_BUSY, 0, int(src))
        out.add_params(KEY_BUSY_MID, msg.get(MSG_ARG_KEY_WIRE_MID))
        out.add_params(KEY_BUSY_RETRY_S, lane.retry_after_s)
        try:
            self.transport.send_message(out)
        except Exception as e:  # push-back is best-effort: retries cover it
            LOG.debug("gateway: busy reply to %s failed (%s)", src, e)

    def _nack(self, msg: Message, reason: str) -> None:
        src = self._reply_rank(msg)
        if src is None:
            self.stats["no_reply_addr"] += 1
            return
        self._evict_rank(int(src), reason)

    def _evict_rank(self, global_rank: int, reason: str) -> None:
        out = Message(MSG_TYPE_WIRE_BUSY, 0, int(global_rank))
        out.add_params(KEY_BUSY_TERMINAL, True)
        out.add_params(KEY_BUSY_REASON, reason)
        try:
            self.transport.send_message(out)
        except Exception as e:
            LOG.debug("gateway: eviction to %d failed (%s)", global_rank, e)

    # -- quarantine --------------------------------------------------------
    def quarantine(self, tenant: str, reason: str) -> None:
        """Fault-isolate one tenant: flag the lane (subsequent traffic is
        NACKed), drain its inbox, send every worker a terminal eviction.
        The lane thread calls this when its watchdog escalates; other
        tenants' lanes are untouched by construction (own threads, own
        queues, own registries)."""
        lane = self.lanes.get(tenant)
        if lane is None or lane.quarantined:
            return
        lane.quarantined = True
        drained = lane.inbox.drain()
        lane.wire["gw_drained"] += len(drained)
        LOG.warning("gateway: quarantined tenant %r (%s); drained %d queued",
                    tenant, reason, len(drained))
        for g in lane.worker_global_ranks:
            self._evict_rank(g, f"tenant {tenant!r} quarantined: {reason}")


def _make_local_factory(size: int, wire_roundtrip: bool):
    from fedml_tpu.comm.local import LocalCommunicationManager, LocalRouter

    # the SHARED router is unbounded: backpressure is the lanes' protocol
    # (BoundedInbox + WIRE_BUSY), and a capped rank-0 queue could stall the
    # mux's own push-back replies behind the flood they answer
    router = LocalRouter(size)

    def make(global_rank: int) -> BaseCommunicationManager:
        return LocalCommunicationManager(router, global_rank,
                                         wire_roundtrip=wire_roundtrip)

    return make


def _make_grpc_factory(size: int, base_port: int):
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    def make(global_rank: int) -> BaseCommunicationManager:
        return GRPCCommManager(rank=global_rank, size=size,
                               base_port=base_port, host="127.0.0.1")

    return make


def run_gateway(tenants, transport: str = "local", timeout: float = 300.0,
                pulse_dir: Optional[str] = None, inbox_cap: Optional[int] = None,
                max_tenants: Optional[int] = None,
                tenant_workers: Optional[int] = None,
                grpc_base_port: int = 57200, wire_roundtrip: bool = True):
    """Run N federations through one in-process gateway.

    ``tenants`` is a list of ``(tenant_id, dataset, config, worker_num)``.
    Each tenant runs the unmodified FedAvg edge protocol in tenant-local
    rank space behind its own gateway lane; quotas
    (``max_tenants``/``tenant_workers``, defaulting to the first tenant
    config's ``gateway_max_tenants``/``gateway_tenant_workers``) reject
    over-admission with a typed reason. Returns ``{tenant_id: result}``
    where result carries ``admitted``/``reject_reason``/``quarantined``/
    ``error``/``aggregator``/``wire`` (the tenant registry's wire
    snapshot)/``pulse_path``/``plane``.

    Per-tenant ``pulse_path`` configs are ignored here — the process-wide
    pulse plane is a singleton; tenants stream to
    ``<pulse_dir>/pulse-<tenant>.jsonl`` instead (fedtop's directory mode
    tails them side by side).
    """
    from fedml_tpu.core.rng import seed_everything
    from fedml_tpu.distributed.fedavg_edge import (
        build_edge_rank,
        make_aggregator,
    )
    from fedml_tpu.models import create_model

    if not tenants:
        raise ValueError("run_gateway needs at least one tenant")
    first_cfg = tenants[0][2]
    if max_tenants is None:
        max_tenants = int(getattr(first_cfg, "gateway_max_tenants", 8) or 8)
    if tenant_workers is None:
        tenant_workers = int(
            getattr(first_cfg, "gateway_tenant_workers", 0) or 0)

    # -- admission (quota NACKs are typed, never silent) -------------------
    admitted: list = []
    results: Dict[str, dict] = {}
    rejected: Dict[str, str] = {}
    for tid, dataset, config, worker_num in tenants:
        tid = str(tid)
        if tid in results:
            raise ValueError(f"duplicate tenant id {tid!r}")
        reason = None
        if tenant_workers and int(worker_num) > tenant_workers:
            reason = (f"worker-quota: {worker_num} workers > "
                      f"gateway_tenant_workers {tenant_workers}")
        elif len(admitted) >= max_tenants:
            reason = (f"tenant-quota: gateway_max_tenants {max_tenants} "
                      "already admitted")
        results[tid] = {"tenant": tid, "admitted": reason is None,
                        "reject_reason": reason, "quarantined": False,
                        "error": None, "aggregator": None, "wire": {},
                        "pulse_path": None, "plane": None}
        if reason is None:
            admitted.append((tid, dataset, config, int(worker_num)))
        else:
            rejected[tid] = reason
            LOG.warning("gateway: rejected tenant %r (%s)", tid, reason)
    if not admitted:
        return results

    # -- shared transport + mux -------------------------------------------
    size = 1 + sum(w for _, _, _, w in admitted)
    if transport == "local":
        make_bare = _make_local_factory(size, wire_roundtrip)
    elif transport == "grpc":
        make_bare = _make_grpc_factory(size, grpc_base_port)
    else:
        raise ValueError(f"unsupported gateway transport {transport!r}")

    from fedml_tpu.obs import default_registry

    gw_comm = make_bare(0)
    mux = GatewayMux(gw_comm, default_registry())
    mux.rejected.update(rejected)
    gw_comm.add_observer(mux)
    gw_thread = threading.Thread(target=gw_comm.handle_receive_message,
                                 daemon=True, name="gateway-mux")

    # -- per-tenant lanes + workers ----------------------------------------
    lanes: Dict[str, TenantLane] = {}
    threads: list = []
    base = 1
    for tid, dataset, config, worker_num in admitted:
        cap = int(getattr(config, "wire_inbox_cap", 0) or 0)
        if inbox_cap is not None:
            cap = int(inbox_cap)
        if cap > 0 and not getattr(config, "wire_reliable", False):
            # WIRE_BUSY is consumed by the sender's reliable layer; a
            # capped lane without it would push back into a void and the
            # held uploads would simply be lost
            raise ValueError(
                f"tenant {tid!r}: wire_inbox_cap {cap} requires "
                "wire_reliable=True (WIRE_BUSY push-back needs the "
                "sender's reliable layer to hold and re-arm)")
        pulse_path = (os.path.join(pulse_dir, f"pulse-{tid}.jsonl")
                      if pulse_dir else None)
        lane = TenantLane(tid, config, worker_num, base - 1, cap, pulse_path)
        lanes[tid] = lane
        mux.lanes[tid] = lane
        results[tid]["pulse_path"] = pulse_path

        # deterministic per-tenant state, exactly the standalone launcher's
        # construction (run_fedavg_edge): model + root key + aggregator are
        # pure in config.seed, shared across the tenant's rank threads
        bundle = create_model(config.model, dataset.class_num,
                              input_shape=dataset.train_x.shape[2:] or None)
        root_key = seed_everything(config.seed)
        aggregator = make_aggregator(bundle.init(root_key), worker_num,
                                     config, dataset=dataset, bundle=bundle)
        lane.aggregator = aggregator
        results[tid]["aggregator"] = aggregator

        def lane_body(lane=lane, dataset=dataset, config=config,
                      worker_num=worker_num, bundle=bundle,
                      root_key=root_key, aggregator=aggregator):
            comm = None
            try:
                # EVERYTHING the lane constructs — the reliable layer's
                # wire group, the server's stale-upload lane, pulse
                # snapshots — attaches to THIS tenant's registry/plane
                with registry_scope(lane.registry), plane_scope(lane.plane):
                    link = TenantLink(gw_comm, lane.inbox, lane.tenant,
                                      lane.base_rank)
                    comm = link
                    if getattr(config, "wire_reliable", False):
                        b, c, m = retry_schedule(config)
                        comm = ReliableCommManager(
                            link, rank=0, retry_base_s=b, retry_cap_s=c,
                            retry_max=m,
                            drain_timeout_s=retry_budget_s(config) + 0.5)
                    lane.comm = comm
                    mgr = build_edge_rank(dataset, config, 0,
                                          worker_num + 1, comm,
                                          bundle=bundle, root_key=root_key,
                                          aggregator=aggregator)
                    mgr.tenant = lane.tenant
                    mgr.run()
            except FederationHealthError as e:
                # the lane's escalating plane already dumped the tenant-
                # scoped flight bundle (dump-before-raise, obs/live.py)
                lane.error = str(e)
                mux.quarantine(lane.tenant, str(e))
            except BaseException as e:
                lane.error = repr(e)
                # fedflight: a non-health crash skipped the plane's dump
                # hook — capture the tenant's window under the quarantine
                # trigger before the lane state is torn down
                try:
                    from fedml_tpu.obs import flight as _flight

                    _flight.trigger("lane_crash", 0, kind="quarantine",
                                    reason=repr(e), tenant=lane.tenant)
                except Exception:
                    pass
                mux.quarantine(lane.tenant, f"lane crashed: {e!r}")
            finally:
                if comm is not None:
                    try:
                        comm.stop_receive_message()
                    except Exception:
                        pass
                lane.plane.close()

        threads.append(threading.Thread(target=lane_body, daemon=True,
                                        name=f"lane-{tid}"))

        for local_r in range(1, worker_num + 1):
            global_r = lane.base_rank + local_r

            def worker_body(lane=lane, dataset=dataset, config=config,
                            worker_num=worker_num, bundle=bundle,
                            root_key=root_key, local_r=local_r,
                            global_r=global_r):
                try:
                    # worker wire counters (reliable retransmits, chaos
                    # fates) land in the tenant registry too — the
                    # cross-tenant leakage pin reads them there
                    with registry_scope(lane.registry):
                        bare = make_bare(global_r)
                        chan = TenantChannel(bare, lane.tenant, global_r)
                        stack = build_wire_stack(chan, config, local_r)
                        mgr = build_edge_rank(dataset, config, local_r,
                                              worker_num + 1, stack,
                                              bundle=bundle,
                                              root_key=root_key)
                        mgr.tenant = lane.tenant
                        mgr.run()
                except BaseException as e:
                    if lane.error is None and not lane.quarantined:
                        lane.error = f"worker {local_r}: {e!r}"

            threads.append(threading.Thread(
                target=worker_body, daemon=True,
                name=f"{tid}-rank{local_r}"))
        base += worker_num

    # -- run ---------------------------------------------------------------
    gw_thread.start()
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    hung = []
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            hung.append(t.name)
    if hung:
        for lane in lanes.values():
            if not lane.quarantined and lane.error is None:
                lane.error = f"timeout: threads still alive: {hung}"
            if lane.comm is not None:
                try:
                    lane.comm.stop_receive_message()
                except Exception:
                    pass
    gw_comm.stop_receive_message()
    gw_thread.join(timeout=5.0)

    for tid, lane in lanes.items():
        res = results[tid]
        res["quarantined"] = lane.quarantined
        res["error"] = lane.error
        res["wire"] = lane.registry.snapshot("wire")
        res["plane"] = lane.plane
    if hung:
        raise TimeoutError(
            f"gateway run exceeded {timeout}s; hung threads: {hung}")
    return results
