"""Minimal distributed-algorithm template (reference distributed/base_framework).

Reference: fedml_api/distributed/base_framework/algorithm_api.py:16-38 — the
smallest possible message-driven algorithm: the server broadcasts an init
signal, each client computes a numeric "local result", the server averages
and broadcasts the global result, for ``comm_round`` rounds. Exists as the
template every message-driven algorithm copies, and as the transport smoke
test (CI-script-framework.sh:16-24 launches exactly this).
"""

from __future__ import annotations

import logging
from typing import List

import numpy as np

from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks


def warn_strict_barrier(config, proto: str) -> None:
    """Log that ``straggler_deadline_sec`` has no effect for ``proto``:
    unlike fedavg_edge, this protocol keeps the strict all-participants
    barrier (docs/deploy.md 'Fault tolerance' explains per protocol why it
    cannot drop participants)."""
    if getattr(config, "straggler_deadline_sec", None) is not None:
        logging.getLogger(proto).warning(
            "straggler_deadline_sec ignored: %s keeps the strict all-"
            "participants barrier (see docs/deploy.md 'Fault tolerance' "
            "for why this protocol cannot drop participants)", proto)


# Shared straggler-deadline machinery (fedavg_edge + fedgkt_edge; one
# implementation so the two fault-tolerant protocols cannot drift).
# Control event injected into the server's OWN receive queue when the
# deadline fires — never crosses the wire; handling serializes with real
# message handling on the receive loop.
MSG_TYPE_LOCAL_ROUND_DEADLINE = 99
#: consecutive all-dead deadlines before the federation tears itself down
MAX_EMPTY_DEADLINES = 10


def broadcast_flight_dump(manager, size: int) -> None:
    """fedflight cross-rank capture (obs/flight.py, DESIGN.md §21): when a
    server-side trigger just dumped an incident bundle (the pulse plane
    dumps BEFORE the watchdog's escalate raise), tell every worker rank to
    flush its own full-rate flight ring into the SAME deterministic
    incident id. Fire-and-forget with a bounded flush deadline: each send
    is individually try/excepted and no acks are awaited, so a dead peer
    costs at most the transport's send timeout instead of hanging the
    dying server's teardown. No-op while the recorder is off or nothing
    has triggered."""
    from fedml_tpu.comm.message import (
        MSG_ARG_KEY_FLIGHT_ID,
        MSG_ARG_KEY_FLIGHT_ROUND,
        MSG_ARG_KEY_FLIGHT_RULE,
        MSG_TYPE_FLIGHT_DUMP,
    )
    from fedml_tpu.obs import flight as _flight

    info = _flight.last_incident()
    if info is None:
        return
    for rank in range(1, int(size)):
        try:
            m = Message(MSG_TYPE_FLIGHT_DUMP, manager.rank, rank)
            m.add_params(MSG_ARG_KEY_FLIGHT_ID, info["id"])
            m.add_params(MSG_ARG_KEY_FLIGHT_RULE, info["rule"])
            m.add_params(MSG_ARG_KEY_FLIGHT_ROUND, info["round"])
            manager.send_message(m)
        except Exception as e:
            logging.getLogger("fedflight").warning(
                "flight dump broadcast to rank %d failed (%s)", rank, e)


def require_injectable(comm, feature: str = "straggler_deadline_sec") -> None:
    # asks the manager itself (not its type): wire middleware wrappers
    # (reliable/chaos) delegate the answer to the transport they wrap
    if not comm.supports_local_injection():
        raise ValueError(
            f"{feature} needs a transport with local event injection "
            f"(local/grpc); {type(comm).__name__} has none")


class RoundDeadlineTimer:
    """Arms a daemon ``threading.Timer`` that injects a round-tagged
    LOCAL_ROUND_DEADLINE message into ``comm``'s own delivery queue."""

    def __init__(self, comm, deadline: float, rank: int, round_key: str):
        self.comm = comm
        self.deadline = float(deadline)
        self.rank = int(rank)
        self.round_key = round_key
        self._timer = None

    def arm(self, round_idx: int) -> None:
        import threading

        self.cancel()
        m = Message(MSG_TYPE_LOCAL_ROUND_DEADLINE, self.rank, self.rank)
        m.add_params(self.round_key, int(round_idx))

        def fire():
            try:
                self.comm.inject_local(m)
            except Exception as e:   # e.g. receive loop already torn down
                LOG.warning("deadline timer injection failed: %s", e)

        t = threading.Timer(self.deadline, fire)
        t.daemon = True
        t.start()
        self._timer = t

    def cancel(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

LOG = logging.getLogger(__name__)

MSG_TYPE_S2C_INIT = 1
MSG_TYPE_C2S_RESULT = 2
MSG_TYPE_S2C_SYNC = 3
MSG_TYPE_S2C_FINISH = 4

MSG_ARG_KEY_RESULT = "local_result"
MSG_ARG_KEY_GLOBAL = "global_result"


class BaseServerManager(ServerManager):
    def __init__(self, args, comm, rank, size):
        super().__init__(args, comm, rank, size)
        self.round_idx = 0
        self.comm_round = int(getattr(args, "comm_round", 1))
        self.results: dict[int, float] = {}
        self.global_history: List[float] = []

    def run(self):
        self.register_message_receive_handlers()
        for client in range(1, self.size):
            self.send_message(Message(MSG_TYPE_S2C_INIT, self.rank, client))
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_C2S_RESULT, self.handle_result)

    def handle_result(self, msg: Message):
        self.results[msg.get_sender_id()] = float(msg.get(MSG_ARG_KEY_RESULT))
        if len(self.results) == self.size - 1:  # barrier by message counting
            global_result = float(np.mean(list(self.results.values())))
            self.global_history.append(global_result)
            self.results.clear()
            self.round_idx += 1
            done = self.round_idx >= self.comm_round
            for client in range(1, self.size):
                m = Message(MSG_TYPE_S2C_FINISH if done else MSG_TYPE_S2C_SYNC, self.rank, client)
                m.add_params(MSG_ARG_KEY_GLOBAL, global_result)
                self.send_message(m)
            if done:
                self.finish()


class BaseClientManager(ClientManager):
    def __init__(self, args, comm, rank, size, local_fn=None):
        super().__init__(args, comm, rank, size)
        # local "training": any callable (round_idx, global_result) -> float
        self.local_fn = local_fn or (lambda r, g: float(self.rank) + (g or 0.0))
        self.round_idx = 0

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT, self.handle_init)
        self.register_message_receive_handler(MSG_TYPE_S2C_SYNC, self.handle_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH, self.handle_finish)

    def _train_and_send(self, global_result):
        result = self.local_fn(self.round_idx, global_result)
        m = Message(MSG_TYPE_C2S_RESULT, self.rank, 0)
        m.add_params(MSG_ARG_KEY_RESULT, float(result))
        self.send_message(m)
        self.round_idx += 1

    def handle_init(self, msg: Message):
        self._train_and_send(None)

    def handle_sync(self, msg: Message):
        self._train_and_send(msg.get(MSG_ARG_KEY_GLOBAL))

    def handle_finish(self, msg: Message):
        self.finish()


def run_base_framework(client_num: int, comm_round: int = 3, wire_roundtrip: bool = True,
                       config=None):
    """In-process launch of server + clients (reference's `mpirun -np N`).

    ``config`` (a FedConfig or anything with the wire/chaos fields) layers
    the reliable/chaos wire middleware over the transport exactly like the
    fedavg_edge launcher — without it ``--wire_reliable``/``--chaos_*``
    were silently ignored for this protocol (ROADMAP wire-reliability gap).
    """
    from fedml_tpu.comm.reliable import wire_wrap_factory
    from fedml_tpu.obs import configure_from

    class Args:
        pass

    args = Args()
    args.comm_round = comm_round
    size = client_num + 1
    if config is not None:
        configure_from(config)

    def make(rank, comm):
        if rank == 0:
            return BaseServerManager(args, comm, rank, size)
        return BaseClientManager(args, comm, rank, size)

    managers = run_ranks(make, size, wire_roundtrip=wire_roundtrip,
                         wrap=wire_wrap_factory(config) if config is not None
                         else None)
    return managers[0].global_history
