"""Message-driven vertical FL — the guest/host exchange over the edge
transport.

Counterpart of reference fedml_api/distributed/classical_vertical_fl/
(vfl_api.py:16-42 + guest_manager.py/host_manager.py): one process per party
over MPI, per batch the hosts send logit components, the guest returns the
common gradient. Here the SAME party objects as the host-simulated protocol
(algorithms/vfl.py VFLGuestParty/VFLHostParty — the executable spec) run
inside ClientManager/ServerManager runtimes over the framework transports
(comm/local.py threads, or gRPC via ``comm_factory``).

Privacy surface matches the reference: raw features never leave a party —
only row indices, [B,1] logit components, and the [B,1] common gradient
travel. Batch order is driven by the guest exactly like VFLAPI.fit
(epoch-wise permutation from numpy default_rng(seed)), so the wire run is
BYTE-EQUAL to the in-process protocol run on the same seed: the party
compute is the same jitted functions on the same inputs in the same order,
and the wire format round-trips arrays exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.vfl import (
    VFLGuestParty,
    VFLHostParty,
    bce_with_logits,
    init_party_params,
    party_component,
)
from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks

MSG_TYPE_G2H_BATCH = "vfl_batch"       # guest -> host: row indices
MSG_TYPE_H2G_COMPONENT = "vfl_comp"    # host -> guest: logit component
MSG_TYPE_G2H_GRAD = "vfl_grad"         # guest -> host: common gradient
MSG_TYPE_G2H_EVAL = "vfl_eval"         # guest -> host: test components request
MSG_TYPE_H2G_EVAL_COMP = "vfl_eval_comp"
MSG_TYPE_G2H_FINISH = "vfl_finish"
MSG_TYPE_G2H_CKPT = "vfl_ckpt"         # guest -> host: persist party state now

KEY_IDX = "idx"
KEY_U = "u"
KEY_STEP = "step"
KEY_EPOCH = "epoch"


class VFLHostManager(ClientManager):
    """Host party runtime (reference host_manager.py): holds its feature
    slice and a VFLHostParty; answers batches with components, learns from
    the common gradient."""

    def __init__(self, args, comm, rank, size, party: VFLHostParty, x_train,
                 x_test, state_path=None, resume=False):
        super().__init__(args, comm, rank, size)
        self.party = party
        self.x_train = np.asarray(x_train)
        self.x_test = np.asarray(x_test)
        # per-party state persistence: hosts OWN their feature-slice model
        # (raw params never travel), so resume must restore it locally —
        # the GKT-client pattern (fedgkt_edge.py)
        self._state_path = state_path
        # epoch this host's restored state belongs to; checked against the
        # guest's resumed epoch on the first batch (ADVICE r5 low: a crash
        # between the guest's save and a host's persist used to resume with
        # guest params at epoch e and host params at e-1, undetectably)
        self._resumed_epoch: "int | None" = None
        if resume and state_path is not None:
            import os

            if os.path.exists(state_path):
                from fedml_tpu.core.serialization import tree_from_bytes

                with open(state_path, "rb") as f:
                    st = tree_from_bytes(f.read())
                self.party.params = st["params"]
                self.party.opt_state = st["opt"]
                if "epoch" in st:
                    self._resumed_epoch = int(np.asarray(st["epoch"]).item())

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_G2H_BATCH, self._on_batch)
        self.register_message_receive_handler(MSG_TYPE_G2H_GRAD, self._on_grad)
        self.register_message_receive_handler(MSG_TYPE_G2H_EVAL, self._on_eval)
        self.register_message_receive_handler(MSG_TYPE_G2H_CKPT, self._on_ckpt)
        self.register_message_receive_handler(MSG_TYPE_G2H_FINISH,
                                              lambda m: self.finish())

    def _on_ckpt(self, msg: Message):
        if self._state_path is None:
            return
        from fedml_tpu.core.serialization import tree_to_bytes

        blob = tree_to_bytes({"params": self.party.params,
                              "opt": self.party.opt_state,
                              "epoch": np.int64(msg.get(KEY_EPOCH, -1))})
        tmp = self._state_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        import os

        os.replace(tmp, self._state_path)

    def _on_batch(self, msg: Message):
        if self._resumed_epoch is not None:
            guest_epoch = msg.get(KEY_EPOCH)
            if guest_epoch is not None and int(guest_epoch) != self._resumed_epoch:
                raise RuntimeError(
                    f"VFL resume inconsistency: host rank {self.rank} restored "
                    f"party state from epoch {self._resumed_epoch} but the "
                    f"guest resumed at epoch {int(guest_epoch)} — the parties' "
                    "checkpoints are from different training points (crash "
                    "between guest save and host persist?); restore a "
                    "matching set or restart from scratch"
                )
            self._resumed_epoch = None
        idx = np.asarray(msg.get(KEY_IDX), np.int64)
        self.party.set_batch(self.x_train[idx])
        out = Message(MSG_TYPE_H2G_COMPONENT, self.rank, 0)
        out.add_params(KEY_STEP, msg.get(KEY_STEP))
        out.add_params(KEY_U, np.asarray(self.party.send_components()))
        self.send_message(out)

    def _on_grad(self, msg: Message):
        self.party.receive_gradients(jnp.asarray(msg.get(KEY_U)))

    def _on_eval(self, msg: Message):
        out = Message(MSG_TYPE_H2G_EVAL_COMP, self.rank, 0)
        out.add_params(KEY_U, np.asarray(self.party.predict(self.x_test)))
        self.send_message(out)


class VFLGuestManager(ServerManager):
    """Guest party runtime + batch driver (reference guest_manager.py +
    vfl_api.py:16-42): owns the labels, fuses components, broadcasts the
    common gradient, drives the epoch/batch schedule of VFLAPI.fit."""

    def __init__(self, args, comm, rank, size, party: VFLGuestParty, dataset,
                 ckpt_path=None, resume_from=None):
        super().__init__(args, comm, rank, size)
        self.party = party
        self.dataset = dataset
        n = len(dataset.train_y)
        self.bs = min(int(args.batch_size), n)
        self.steps = n // self.bs
        self.epochs = int(args.epochs)
        self._order_rng = np.random.default_rng(args.seed)
        self.epoch = 0
        self.step = 0
        self._ckpt_path = ckpt_path
        if resume_from:
            from fedml_tpu.utils.checkpoint import load_checkpoint

            state = load_checkpoint(resume_from)
            self.party.params = state["variables"]["params"]
            self.party.opt_state = state["variables"]["opt"]
            self.epoch = int(state["round_idx"])
            self.losses_resumed = list(state["extra"].get("losses", []))
            # the epoch permutation stream is stateful: fast-forward past
            # the completed epochs so the resumed order matches the
            # uninterrupted run's
            for _ in range(self.epoch):
                self._order_rng.permutation(n)
        self._components: dict[int, np.ndarray] = {}
        self._eval_components: dict[int, np.ndarray] = {}
        self.losses: list[float] = list(getattr(self, "losses_resumed", []))
        self.history: list[dict] = []

    def run(self):
        self.register_message_receive_handlers()
        if self.epoch >= self.epochs:   # resumed a finished run: eval only
            for rank in range(1, self.size):
                self.send_message(Message(MSG_TYPE_G2H_EVAL, self.rank, rank))
            self.com_manager.handle_receive_message()
            return
        self._next_epoch_order()
        self._send_batch()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_H2G_COMPONENT, self._on_component)
        self.register_message_receive_handler(MSG_TYPE_H2G_EVAL_COMP, self._on_eval_component)

    def _next_epoch_order(self):
        n = len(self.dataset.train_y)
        self._order = self._order_rng.permutation(n)[: self.steps * self.bs] \
            .reshape(self.steps, self.bs)
        self._epoch_losses: list[float] = []

    def _batch_idx(self):
        return self._order[self.step]

    def _send_batch(self):
        idx = self._batch_idx()
        self.party.set_batch(self.dataset.train_parts[0][idx],
                             self.dataset.train_y[idx])
        for rank in range(1, self.size):
            m = Message(MSG_TYPE_G2H_BATCH, self.rank, rank)
            m.add_params(KEY_STEP, self.step)
            m.add_params(KEY_EPOCH, self.epoch)
            m.add_params(KEY_IDX, idx.astype(np.int64))
            self.send_message(m)

    def _on_component(self, msg: Message):
        assert int(msg.get(KEY_STEP)) == self.step
        self._components[msg.get_sender_id()] = np.asarray(msg.get(KEY_U))
        if len(self._components) < self.size - 1:
            return
        # fixed host-rank order => the same float sum as the in-process form
        self.party.receive_components(
            [jnp.asarray(self._components[r]) for r in range(1, self.size)])
        self._components.clear()
        self.party.fit()
        self._epoch_losses.append(self.party.loss)
        common = np.asarray(self.party.send_gradients())
        for rank in range(1, self.size):
            m = Message(MSG_TYPE_G2H_GRAD, self.rank, rank)
            m.add_params(KEY_U, common)
            self.send_message(m)
        self.step += 1
        if self.step < self.steps:
            self._send_batch()
            return
        # epoch done
        self.losses.append(float(np.mean(self._epoch_losses)))
        self.epoch += 1
        self.step = 0
        self._maybe_checkpoint()
        if self.epoch < self.epochs:
            self._next_epoch_order()
            self._send_batch()
            return
        # training done -> distributed eval
        for rank in range(1, self.size):
            self.send_message(Message(MSG_TYPE_G2H_EVAL, self.rank, rank))

    def _maybe_checkpoint(self):
        if self._ckpt_path is None:
            return
        from fedml_tpu.utils.checkpoint import save_checkpoint

        for rank in range(1, self.size):
            m = Message(MSG_TYPE_G2H_CKPT, self.rank, rank)
            # the epoch tag makes the cross-party checkpoint SET verifiable:
            # every host .state file records which guest epoch it pairs with
            m.add_params(KEY_EPOCH, self.epoch)
            self.send_message(m)
        save_checkpoint(self._ckpt_path,
                        {"params": self.party.params,
                         "opt": self.party.opt_state},
                        round_idx=self.epoch,
                        extra={"losses": list(self.losses)})

    def _on_eval_component(self, msg: Message):
        self._eval_components[msg.get_sender_id()] = np.asarray(msg.get(KEY_U))
        if len(self._eval_components) < self.size - 1:
            return
        d = self.dataset
        u = party_component(self.party.params, jnp.asarray(d.test_parts[0]))
        u = np.asarray(u) + sum(self._eval_components[r]
                                for r in range(1, self.size))
        pred = (u[:, 0] > 0).astype(np.float32)
        self.history.append({
            "Train/Loss": self.losses[-1],
            "Test/Acc": float((pred == d.test_y).mean()),
            "Test/Loss": float(bce_with_logits(jnp.asarray(u[:, 0]),
                                               jnp.asarray(d.test_y))),
        })
        for rank in range(1, self.size):
            self.send_message(Message(MSG_TYPE_G2H_FINISH, self.rank, rank))
        self.finish()


def run_vfl_edge(dataset, hidden_dim: int = 16, lr: float = 0.01,
                 batch_size: int = 64, epochs: int = 10, seed: int = 0,
                 wire_roundtrip: bool = True, comm_factory=None,
                 straggler_deadline_sec=None, checkpoint_dir=None,
                 resume: bool = False, config=None):
    """Launch guest (rank 0) + one host per remaining party over the local
    transport (or gRPC via ``comm_factory``). Same init derivation as
    build_protocol_vfl(seed) and same batch schedule as VFLAPI.fit(epochs,
    seed). Returns the guest manager (parties hold final params;
    ``history[-1]`` the final metrics).

    VFL is the ONE edge protocol that genuinely cannot drop a participant:
    each party owns a disjoint FEATURE slice, so the forward pass needs
    every party's embedding — losing one changes the model's input
    dimensionality mid-training (there is no 'train on fewer features'
    fallback that preserves the learned feature interactions). The strict
    barrier stays; ``straggler_deadline_sec`` is warned about and ignored
    (docs/deploy.md 'Fault tolerance')."""
    import types

    from fedml_tpu.distributed.base_framework import warn_strict_barrier

    warn_strict_barrier(types.SimpleNamespace(
        straggler_deadline_sec=straggler_deadline_sec), __name__)
    root = jax.random.PRNGKey(seed)
    keys = jax.random.split(root, dataset.num_parties)
    guest = VFLGuestParty(
        init_party_params(keys[0], dataset.party_dims[0], hidden_dim, guest=True), lr)
    hosts = {
        p: VFLHostParty(
            init_party_params(keys[p], dataset.party_dims[p], hidden_dim,
                              guest=False), lr)
        for p in range(1, dataset.num_parties)
    }
    size = dataset.num_parties

    class Args:
        pass

    args = Args()
    args.batch_size = batch_size
    args.epochs = epochs
    args.seed = seed

    holder = {}
    guest_ckpt = host_path = None
    if checkpoint_dir is not None:
        import os

        os.makedirs(checkpoint_dir, exist_ok=True)
        guest_ckpt = os.path.join(checkpoint_dir, "vfl_guest.ckpt")

        def host_path(rank):
            return os.path.join(checkpoint_dir, f"vfl_host_{rank}.state")

    def make(rank, comm):
        if rank == 0:
            holder["guest"] = VFLGuestManager(
                args, comm, rank, size, guest, dataset,
                ckpt_path=guest_ckpt,
                resume_from=guest_ckpt if (resume and guest_ckpt) else None)
            return holder["guest"]
        return VFLHostManager(args, comm, rank, size, hosts[rank],
                              dataset.train_parts[rank],
                              dataset.test_parts[rank],
                              state_path=host_path(rank) if host_path else None,
                              resume=resume)

    # ``config`` layers the reliable/chaos wire middleware over the
    # transport (ROADMAP wire-reliability gap): VFL's strict all-parties
    # barrier cannot drop a participant, so a lossy wire MUST be recovered
    # by retransmit — there is no deadline fallback for this protocol.
    from fedml_tpu.comm.reliable import wire_wrap_factory
    from fedml_tpu.obs import configure_from

    if config is not None:
        configure_from(config)
    run_ranks(make, size, wire_roundtrip=wire_roundtrip,
              comm_factory=comm_factory,
              wrap=wire_wrap_factory(config) if config is not None else None)
    return holder["guest"]
