"""Message-driven FedBuff: asynchronous buffered aggregation at the edge.

The synchronous edge protocol (distributed/fedavg_edge.py) broadcasts one
model per round and blocks on a barrier or a straggler deadline; a slow or
flaky client either gates the round or gets DROPPED at the deadline. This
module is the paradigm the sync stack can't reach (ROADMAP "asynchronous
buffered aggregation"): there are no rounds on the wire at all —

- the server answers every accepted upload IMMEDIATELY (arrival mode) with
  the current model version and the worker's next assignment, so a fast
  worker loops at its own pace while a slow one simply contributes later
  with a staleness-decayed weight (algorithms/fedbuff.py);
- a model version is emitted every ``--buffer_k`` folded contributions;
  per-version evaluation, pulse snapshots (version-lag in the ``staleness``
  sketch lane + ``server_version`` on the wire lane) and the health
  watchdog's ``version_lag`` rule hang off the emission boundary;
- crash-stopped workers are ejected by the reliable layer's gave-up path
  (``on_gave_up`` → a local PEER_GAVE_UP control event on the server's own
  receive loop), never by discarding their contributions; a revived worker
  (chaos ``crash_restart`` or a real process restart) re-enters via JOIN —
  or via its own retransmitted upload — and contributes with the staleness
  its lag earned;
- ``--buffer_mode deterministic`` folds through the canonical
  ``(train-tag, worker)`` frontier instead: replies are held until the
  frontier stalls, so the entire async schedule — fold order, version
  membership, staleness values, weights — is a pure function of
  ``(seed, chaos_seed)`` and replays bit-identically under drop/dup/delay/
  crash chaos (tests/test_fedbuff.py pins local + grpc). With
  ``buffer_k == worker count`` this degenerates to exactly synchronous
  FedAvg (the sync-equivalence pin). A stalled frontier re-sends the
  blocking worker's assignment on a probe timer, so a crash that left no
  unacked traffic still reaches the gave-up oracle (and a live worker
  starved by an abandoned message is un-wedged) — version emission never
  stalls on a corpse.

Assignments compose with the fedsched :class:`CohortScheduler`: the sweep
tag is the scheduler's round index, so ``--cohort_policy speed|fair``
shapes async cohorts exactly as it shapes sync ones (uniform stays
bit-identical to ``sample_clients`` by construction — the sync-equivalence
pin depends on it). Worker ``w`` takes the tag-``t`` cohort's slice
``cohort[w::workers]`` — a pure function of ``(seed, tag, w)``, never of
the alive set, so an ejection cannot reshuffle anyone else's data.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import jax
import numpy as np

from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
)
from fedml_tpu.algorithms.fedbuff import DeterministicFrontier, FedBuffBuffer
from fedml_tpu.core.tasks import get_task
from fedml_tpu.distributed.fedavg_edge import (
    MSG_ARG_KEY_MODEL_DELTA,
    FedAVGTrainer,
    _edge_args,
)
from fedml_tpu.models import create_model
from fedml_tpu.parallel.local import finalize_metrics, make_eval_fn

LOG = logging.getLogger(__name__)

# protocol (the fedavg_edge numbering, extended with the async additions)
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL = 2
MSG_TYPE_C2S_SEND_MODEL = 3
MSG_TYPE_S2C_FINISH = 4
MSG_TYPE_C2S_JOIN = 5
# local control events injected into the server's OWN receive queue (never
# cross the wire; handling serializes with real messages on the loop)
MSG_TYPE_LOCAL_PEER_GAVE_UP = 98
MSG_TYPE_LOCAL_STALL_PROBE = 97

#: the model version a sync message carries / an upload echoes as the
#: version it TRAINED from — ``server_version - trained_version`` is the
#: staleness the fold weight decays by
MSG_ARG_KEY_VERSION = "model_version"
#: the worker's per-assignment sweep tag: drives the client RNG stream and
#: the deterministic frontier's canonical order (and dedups uploads —
#: a retransmit of an already-folded tag can never fold twice)
MSG_ARG_KEY_TRAIN_TAG = "train_tag"
#: rank carried by the local control events
MSG_ARG_KEY_PEER = "peer_rank"

#: frontier-stall probe cadence when no --straggler_deadline_sec is set
#: (the deadline flag doubles as the probe interval when present: it is
#: the operator's statement of how long "suspiciously quiet" is). Either
#: way the effective cadence is floored just above the wire's retry
#: budget, so a probe never re-sends work the original could still
#: legitimately deliver.
DEFAULT_PROBE_SEC = 3.0


def _flat64(tree) -> np.ndarray:
    """Flatten a host tree to one f64 vector (fedlens norm/cosine basis;
    leaf order is the canonical jax.tree order, so two trees of the same
    structure flatten comparably)."""
    return np.concatenate([np.asarray(l, np.float64).ravel()
                           for l in jax.tree.leaves(tree)])


def _probe_interval(config) -> float:
    from fedml_tpu.comm.reliable import retry_budget_s

    base = float(getattr(config, "straggler_deadline_sec", None)
                 or DEFAULT_PROBE_SEC)
    if getattr(config, "wire_reliable", False):
        return max(base, 1.25 * retry_budget_s(config))
    return base


class FedBuffAggregator:
    """Server-side state: the versioned staleness-weighted buffer plus the
    eval surface (mirrors FedAVGAggregator so launchers/tests read the
    same attributes: ``variables``, ``test_history``, ``wire_stats``)."""

    def __init__(self, variables, worker_num: int, config, dataset=None,
                 bundle=None):
        self.variables = variables
        self.worker_num = worker_num
        self.config = config
        self.dataset = dataset
        self.buffer = FedBuffBuffer(
            int(getattr(config, "buffer_k", 4)),
            float(getattr(config, "buffer_staleness_alpha", 0.5)))
        self.mode = getattr(config, "buffer_mode", "arrival")
        self.test_history: list[dict] = []
        #: uploads dropped by the (worker, tag) exact-once guard — a
        #: retransmit that crossed a version boundary, a pre-rejoin copy —
        #: surfaced, never double-folded
        self.duplicate_uploads = 0
        #: ejected workers that re-entered (JOIN or upload)
        self.rejoins = 0
        self._eval = (make_eval_fn(bundle,
                                   get_task(dataset.task, dataset.class_num))
                      if bundle is not None and dataset is not None else None)

    @property
    def uploads_folded(self) -> int:
        # the dispatch thread is the buffer's only mutator (handlers
        # serialize on the manager loop; timers re-enter via inject_local,
        # see _arm_probe) — a lock-free stat read cannot tear
        # fedlint: disable=check-then-act
        return self.buffer.folds

    @property
    def versions_emitted(self) -> int:
        return self.buffer.versions_emitted

    def test_on_server(self, version_idx: int) -> Optional[dict]:
        if self._eval is None:
            return None
        sums = self._eval(self.variables, self.dataset.test_x,
                          self.dataset.test_y, self.dataset.test_mask)
        m = finalize_metrics(jax.tree.map(np.asarray, sums))
        m["round"] = version_idx
        self.test_history.append(m)
        return m


class FedBuffEdgeServerManager(ServerManager):
    """The async server (module docstring): no round barrier, a version
    every K folds, per-upload replies (arrival) or frontier-ordered
    replies (deterministic)."""

    def __init__(self, args, comm, rank, size,
                 aggregator: FedBuffAggregator):
        super().__init__(args, comm, rank, size)
        self.aggregator = aggregator
        self.buffer = aggregator.buffer
        self.versions_total = int(args.comm_round)
        self.workers = size - 1
        cfg = aggregator.config
        self.deterministic = aggregator.mode == "deterministic"
        from fedml_tpu.data.sched import CohortScheduler

        cohort = min(args.client_num_per_round, args.client_num_in_total)
        self.scheduler = CohortScheduler(
            getattr(cfg, "cohort_policy", "uniform"), cfg.seed,
            args.client_num_in_total, cohort)
        self._alive = {w: True for w in range(self.workers)}
        self._finished = False
        #: arrival mode: the upload tag expected next per worker (the
        #: exact-once guard); deterministic mode reads the frontier's
        self._expected = {w: 0 for w in range(self.workers)}
        self.frontier = (DeterministicFrontier(range(self.workers))
                         if self.deterministic else None)
        #: per-worker assignment send time + ids (pulse attribution)
        self._sent_at: dict[int, float] = {}
        self._assignment_map: dict[int, list[int]] = {}
        #: per-worker LAST SENT assignment content (tag, version, params
        #: REFERENCE — emissions build new trees, so this is aliasing,
        #: not copying): probe/JOIN resends must repeat the original
        #: bytes, or a resend racing its original would hand the worker
        #: a newer model and make the folded delta arrival-dependent
        self._last_sent: dict[int, tuple] = {}
        #: deterministic mode: workers whose fold joined the PENDING buffer
        #: — their replies flush at the buffer's emission (the only
        #: canonical point: per-fold or stall-time replies would hand a
        #: worker a model that depends on arrival timing). With
        #: buffer_k == workers this is exactly the synchronous broadcast.
        self._pending_replies: list[int] = []
        if self.deterministic and self.buffer.k > self.workers:
            raise ValueError(
                f"buffer_mode=deterministic needs buffer_k <= workers "
                f"({self.buffer.k} > {self.workers}): replies flush at "
                "emission, so a buffer needing more folds than there are "
                "workers can never fill (DESIGN.md §18)")
        self._probe_sec = _probe_interval(cfg)
        self._probe_timer: Optional[threading.Timer] = None
        self._emit_t0 = time.perf_counter()
        #: fedlens alignment basis: the LAST emitted server update
        #: (flattened f64) — an async fold has no same-round cohort to
        #: align against, so each upload's delta is scored against the
        #: server's most recent direction instead (None until the first
        #: emission: norms-only, like the streaming sync fold)
        self._last_emit_delta: Optional[np.ndarray] = None
        if self.deterministic:
            from fedml_tpu.distributed.base_framework import require_injectable

            require_injectable(comm, feature="buffer_mode=deterministic")
        # ejection oracle: the reliable layer reports the peer whose
        # retries exhausted; re-enter the event on the server's own loop
        from fedml_tpu.comm.base import find_layer
        from fedml_tpu.comm.reliable import ReliableCommManager

        reliable = find_layer(comm, ReliableCommManager)
        if reliable is not None:
            reliable.on_gave_up = self._on_gave_up

    # -- lifecycle ---------------------------------------------------------

    def run(self):
        self.register_message_receive_handlers()
        for w in range(self.workers):
            self._send_assignment(w, 0, msg_type=MSG_TYPE_S2C_INIT_CONFIG)
        self._arm_probe()
        try:
            self.com_manager.handle_receive_message()
        finally:
            # every exit path (teardown, escalation, error) must drop the
            # probe timer: a live timer closure would keep this manager —
            # and its comm stack's registry counter groups — alive past
            # the federation, leaking wire counters into later runs
            self._finished = True
            self._cancel_probe()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL, self.handle_upload)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_JOIN, self.handle_join)
        self.register_message_receive_handler(
            MSG_TYPE_LOCAL_PEER_GAVE_UP, self.handle_peer_gave_up)
        self.register_message_receive_handler(
            MSG_TYPE_LOCAL_STALL_PROBE, self.handle_stall_probe)

    def _teardown(self):
        self._finished = True
        self._cancel_probe()
        for rank in range(1, self.size):
            try:
                self.send_message(
                    Message(MSG_TYPE_S2C_FINISH, self.rank, rank))
            except Exception as e:   # a corpse must not block teardown
                LOG.warning("FINISH to worker %d failed (%s)", rank - 1, e)
        self.finish()

    # -- assignments -------------------------------------------------------

    def _assignment(self, worker: int, tag: int) -> list[int]:
        """Worker ``worker``'s slice of the sweep-``tag`` cohort — pure in
        (seed, tag, worker): the fixed ``[w::workers]`` deal ignores the
        alive set, so ejections never reshuffle survivors' data (and with
        every worker alive it matches fedavg_edge's round-robin deal, the
        sync-equivalence construction)."""
        cohort = self.scheduler.sample(int(tag))
        return [int(c) for c in cohort[worker::self.workers]]

    def _send_assignment(self, worker: int, tag: int,
                         msg_type: int = MSG_TYPE_S2C_SYNC_MODEL,
                         resend: bool = False) -> None:
        """Send worker its (model, version, tag, cohort-slice) assignment.
        ``resend=True`` (the stall probe, an alive-JOIN un-wedge) repeats
        the LAST SENT content for that tag verbatim: a resend built from
        the current state could carry a newer emitted model than the
        original, and which copy the worker trains from would then be
        arrival-dependent — the exact-once guard dedups the uploads, but
        their payloads must be identical for deterministic replay."""
        cached = self._last_sent.get(worker)
        if resend and cached is not None and cached[0] == int(tag):
            _tag, version, params = cached
        else:
            # version only moves in emit(), on this same dispatch thread
            # (handlers serialize on the manager loop; timers re-enter via
            # inject_local) — the pair read here cannot straddle an emit
            # fedlint: disable=check-then-act
            version, params = self.buffer.version, self.aggregator.variables
        ids = self._assignment(worker, tag)
        m = Message(msg_type, self.rank, worker + 1)
        m.add_params(MSG_ARG_KEY_MODEL_PARAMS, params)
        m.add_params(MSG_ARG_KEY_CLIENT_INDEX, ids)
        m.add_params(MSG_ARG_KEY_VERSION, version)
        m.add_params(MSG_ARG_KEY_TRAIN_TAG, int(tag))
        try:
            self.send_message(m)
        except Exception as e:
            # the transport itself declared the peer gone (dead gRPC
            # endpoint): eject — via the injected control event so the
            # ejection serializes AFTER the handler currently running
            # (mid-drain re-entry would corrupt the frontier walk)
            LOG.warning("assignment to worker %d failed (%s)", worker, e)
            self._on_gave_up(worker + 1, m)
            return
        self._last_sent[worker] = (int(tag), version, params)
        self._sent_at[worker] = time.perf_counter()
        self._assignment_map[worker] = ids

    # -- upload path -------------------------------------------------------

    def handle_upload(self, msg: Message) -> None:
        if self._finished:
            return
        w = msg.get_sender_id() - 1
        tag = int(msg.get(MSG_ARG_KEY_TRAIN_TAG))
        trained_v = int(msg.get(MSG_ARG_KEY_VERSION))
        item = (msg.get(MSG_ARG_KEY_MODEL_DELTA),
                float(msg.get(MSG_ARG_KEY_NUM_SAMPLES)), trained_v)
        if not self._alive.get(w, False):
            # an upload from a presumed-dead worker IS its rejoin — and
            # unlike the sync deadline path, the payload is USED: staleness
            # weighting exists exactly so late work still counts
            LOG.info("worker %d rejoined via upload (tag %d)", w, tag)
            self._alive[w] = True
            self.aggregator.rejoins += 1
            if self.deterministic and self.frontier.next_tag(w) is None:
                self.frontier.admit(w, tag)
        if self.deterministic:
            if not self.frontier.offer(w, tag, item):
                self.aggregator.duplicate_uploads += 1
                return
            self._advance()
        else:
            if tag != self._expected.get(w):
                self.aggregator.duplicate_uploads += 1
                return
            self._expected[w] = tag + 1
            self._fold(w, tag, item)
            if not self._finished:
                self._send_assignment(w, tag + 1)

    def _fold(self, worker: int, tag: int, item) -> None:
        delta, n, trained_v = item
        rec = self.buffer.fold(delta, n, trained_v)
        if self.deterministic:
            self._pending_replies.append(worker)
        from fedml_tpu.obs import pulse_if_enabled

        pulse = pulse_if_enabled()
        if pulse is not None:
            sent = self._sent_at.get(worker)
            pulse.observe_upload(
                self._assignment_map.get(worker) or [],
                # dispatch-thread-only read; emit() is the sole writer and
                # runs on this same thread (see _send_assignment above)
                # fedlint: disable=check-then-act
                self.buffer.version,
                train_ms=(None if sent is None
                          else (time.perf_counter() - sent) * 1e3),
                upload_bytes=float(sum(
                    getattr(leaf, "nbytes", 8)
                    for leaf in jax.tree.leaves(delta))),
                staleness=rec["staleness"])
            from fedml_tpu.obs.lens import lens_enabled

            if lens_enabled():
                # fedlens per-fold: the upload IS a raw update delta —
                # norm directly, cosine vs the last emitted server update
                u = _flat64(delta)
                nrm = float(np.linalg.norm(u))
                align = None
                m = self._last_emit_delta
                if m is not None and m.size == u.size:
                    align = float(u @ m) / max(
                        nrm * float(np.linalg.norm(m)), 1e-12)
                ids = self._assignment_map.get(worker) or []
                if ids:
                    # fedlint: disable=check-then-act
                    pulse.observe_lens(ids, self.buffer.version,
                                       update_norm=nrm, align=align)
        if self.buffer.ready:
            self._emit()

    def _advance(self) -> None:
        """Deterministic mode: drain the frontier in canonical order.
        Replies flush inside :meth:`_emit` — a worker folded into buffer
        ``b`` hears back exactly when ``b`` emits, carrying the version its
        own buffer produced. That is the ONE reply schedule that is both a
        pure function of the fold sequence (per-fold or stall-time replies
        would hand out a model that depends on arrival timing) and, at
        ``buffer_k == workers``, exactly the synchronous broadcast
        (sync-equivalence). Liveness needs ``buffer_k <= admitted``
        (enforced at init, re-checked at ejection): each emission releases
        the workers whose uploads the NEXT K folds require."""
        for w, tag, item in self.frontier.drain():
            self._fold(w, tag, item)
            if self._finished:
                return
        self._arm_probe()

    # -- version emission --------------------------------------------------

    def _emit(self) -> None:
        old = self.aggregator.variables
        params, rec = self.buffer.emit(old)
        self.aggregator.variables = params
        from fedml_tpu.obs.lens import lens_enabled

        if lens_enabled():
            self._last_emit_delta = _flat64(params) - _flat64(old)
        v_idx = self.buffer.versions_emitted - 1   # 0-based, like rounds
        metrics = None
        if (v_idx % self.args.frequency_of_the_test == 0
                or v_idx == self.versions_total - 1):
            metrics = self.aggregator.test_on_server(v_idx)
        self.scheduler.notify_round_done(v_idx)
        from fedml_tpu.obs import pulse_if_enabled

        pulse = pulse_if_enabled()
        if pulse is not None:
            # one pulse snapshot per EMITTED VERSION — the async round
            # boundary. server_version + the per-version fold count ride
            # the wire lane; version lag feeds the staleness sketch per
            # fold (observe_upload), so the watchdog's version_lag rule
            # reads this round's delta p99.
            try:
                pulse.on_round(
                    v_idx, source="fedbuff_server",
                    loss=(float(metrics["loss"]) if metrics
                          and metrics.get("loss") is not None else None),
                    round_ms=(time.perf_counter() - self._emit_t0) * 1e3,
                    # dispatch-thread-only read; emit() is the sole writer
                    # and runs on this same thread (_send_assignment above)
                    # fedlint: disable=check-then-act
                    extra={"server_version": self.buffer.version,
                           "uploads": rec["folds"],
                           "version_lag_max": rec["staleness_max"],
                           "workers_alive": sum(
                               1 for a in self._alive.values() if a)})
            except Exception:
                # fedflight: the escalating plane just dumped this rank's
                # incident bundle (dump-before-raise, obs/live.py) —
                # broadcast the dump so every worker flushes its flight
                # ring to the same incident id before the error unwinds
                from fedml_tpu.distributed.base_framework import (
                    broadcast_flight_dump,
                )

                broadcast_flight_dump(self, self.size)
                raise
        self._emit_t0 = time.perf_counter()
        if self.buffer.versions_emitted >= self.versions_total:
            self._teardown()
            return
        if self.deterministic:
            # release the emitted buffer's workers (module docstring: the
            # canonical reply point); an ejected corpse is skipped — it
            # would not read the reply anyway
            released, self._pending_replies = self._pending_replies, []
            for w in released:
                if self._alive.get(w, False):
                    self._send_assignment(w, self.frontier.next_tag(w))

    # -- ejection / liveness -----------------------------------------------

    def _on_gave_up(self, receiver: int, msg: Message) -> None:
        """Reliable-layer hook (retransmit thread): re-enter as a local
        control event so ejection serializes with message handling."""
        if self._finished or receiver == 0:
            return
        m = Message(MSG_TYPE_LOCAL_PEER_GAVE_UP, self.rank, self.rank)
        m.add_params(MSG_ARG_KEY_PEER, int(receiver))
        try:
            self.com_manager.inject_local(m)
        except Exception as e:   # loop already torn down
            LOG.debug("gave-up injection failed (%s)", e)

    def handle_peer_gave_up(self, msg: Message) -> None:
        if self._finished:
            return
        self._eject(int(msg.get(MSG_ARG_KEY_PEER)) - 1)

    def _eject(self, worker: int) -> None:
        if not self._alive.get(worker, False):
            return
        LOG.warning("worker %d ejected (gave-up/unreachable); its pending "
                    "slots stop gating version emission", worker)
        self._alive[worker] = False
        if self.deterministic:
            self.frontier.eject(worker)
            # drop any reply the pending buffer owes it: if a JOIN
            # re-admits this worker before the buffer emits, the JOIN's
            # fresh assignment must be the ONLY one for its tag — a stale
            # release at emission would send a second, payload-different
            # copy and make the folded delta arrival-dependent
            self._pending_replies = [w for w in self._pending_replies
                                     if w != worker]
        if not any(self._alive.values()):
            LOG.error("every worker is dead; tearing down with %d/%d "
                      "versions emitted", self.buffer.versions_emitted,
                      self.versions_total)
            self._teardown()
            return
        if self.deterministic:
            if len(self.frontier.admitted) < self.buffer.k:
                # fewer admitted workers than the buffer needs folds: the
                # pending buffer can never fill (DESIGN.md §18 degradation
                # table) — tear down instead of stalling forever, like the
                # sync path's all-dead deadline bound
                LOG.error(
                    "admitted workers (%d) dropped below buffer_k (%d); "
                    "tearing down with %d/%d versions emitted",
                    len(self.frontier.admitted), self.buffer.k,
                    self.buffer.versions_emitted, self.versions_total)
                self._teardown()
                return
            self._advance()   # the corpse may have been the frontier head

    def handle_join(self, msg: Message) -> None:
        """A (re)connecting worker announces itself. An ejected worker is
        re-admitted at the CURRENT sweep with a fresh assignment; its
        in-flight pre-crash upload, if it ever lands, is absorbed by the
        exact-once guard. A JOIN from a worker still marked ALIVE is a
        starvation signal, not noise: fedbuff clients only JOIN after
        prolonged silence (keepalive) or a crash_restart revival, so in
        arrival mode the server re-sends the pending assignment — the
        idempotent un-wedge for an upload/assignment lost during an
        outage the gave-up oracle never saw (the worker owed the server
        nothing unacked, so it was never ejected). Deterministic mode
        must NOT answer arrival-timed JOINs with a model (it would leave
        the canonical reply schedule); its frontier-stall probe already
        re-sends the head assignment instead."""
        w = msg.get_sender_id() - 1
        if self._finished:
            return
        if self._alive.get(w, False):
            if not self.deterministic:
                LOG.info("alive worker %d JOINed (starved/revived); "
                         "re-sending its pending assignment tag %d",
                         w, self._expected[w])
                self._send_assignment(w, self._expected[w], resend=True)
            return
        self._alive[w] = True
        self.aggregator.rejoins += 1
        if self.deterministic:
            tag = max([self.frontier.next_tag(x)
                       for x in self.frontier.admitted] or [0])
            self.frontier.admit(w, tag)
        else:
            tag = self._expected[w]
        LOG.info("worker %d rejoined via JOIN; re-admitted at tag %d", w, tag)
        self._send_assignment(w, tag)

    # -- frontier stall probe ----------------------------------------------

    def _arm_probe(self) -> None:
        """Deterministic mode: while the frontier waits on a slot, probe
        its owner on a timer by RE-SENDING its pending assignment. To a
        live worker the resend is idempotent — a duplicate upload is
        absorbed by the exact-once guard, and a worker starved by an
        abandoned (gave-up) assignment is un-wedged; to a corpse the
        resend's retries exhaust and the gave-up path ejects it — version
        emission never stalls forever either way. The probe cadence is
        floored above the wire retry budget (``_probe_interval``), so a
        resend never races an original that could still deliver."""
        if not self.deterministic or self._finished:
            return
        self._cancel_probe()
        head = self.frontier.head()
        if head is None:
            return
        m = Message(MSG_TYPE_LOCAL_STALL_PROBE, self.rank, self.rank)
        m.add_params(MSG_ARG_KEY_PEER, head[1] + 1)
        m.add_params(MSG_ARG_KEY_TRAIN_TAG, head[0])

        def fire():
            try:
                self.com_manager.inject_local(m)
            except Exception as e:
                LOG.debug("stall-probe injection failed (%s)", e)

        t = threading.Timer(self._probe_sec, fire)
        t.daemon = True
        t.start()
        self._probe_timer = t

    def _cancel_probe(self) -> None:
        if self._probe_timer is not None:
            self._probe_timer.cancel()
            self._probe_timer = None

    def handle_stall_probe(self, msg: Message) -> None:
        if self._finished or not self.deterministic:
            return
        head = self.frontier.head()
        probed = (int(msg.get(MSG_ARG_KEY_TRAIN_TAG)),
                  int(msg.get(MSG_ARG_KEY_PEER)) - 1)
        if head == probed and self._alive.get(probed[1], False):
            LOG.info("frontier stalled on worker %d (tag %d) for %.1fs; "
                     "re-sending its assignment", probed[1], probed[0],
                     self._probe_sec)
            self._send_assignment(probed[1], probed[0], resend=True)
        self._arm_probe()


class FedBuffEdgeClientManager(ClientManager):
    """The async worker: stateless train-on-assignment (reusing the sync
    path's FedAVGTrainer — the tag drives the same (seed, tag, client) RNG
    stream fedavg_edge uses, which is what makes sync-equivalence exact),
    uploading the update DELTA against the version it trained from. A
    keepalive timer JOINs after prolonged silence, and a chaos
    crash_restart revival JOINs immediately (``on_restart``) — the
    recovery paths the crash_restart fate exists to test."""

    def __init__(self, args, comm, rank, size, trainer: FedAVGTrainer,
                 root_key):
        super().__init__(args, comm, rank, size)
        self.trainer = trainer
        self.root_key = root_key
        #: silence threshold before a JOIN re-announce; generous multiple
        #: of the server's probe cadence so healthy waits don't JOIN-spam
        self._keepalive_s = max(2.0 * _probe_interval(trainer.config), 3.0)
        self._keepalive: Optional[threading.Timer] = None
        #: serializes arm/cancel between the receive loop and a firing
        #: timer's own re-arm — an unlocked overwrite would orphan a live
        #: timer chain that keeps JOINing untracked
        self._ka_lock = threading.Lock()
        self._done = False

    def run(self):
        self.register_message_receive_handlers()
        from fedml_tpu.comm.chaos import find_chaos

        chaos = find_chaos(self.com_manager)
        if chaos is not None:
            chaos.on_restart = self._send_join
        self._arm_keepalive()
        try:
            self.com_manager.handle_receive_message()
        finally:
            # the receive loop can exit WITHOUT a FINISH (permanent
            # crash-stop kills the loop directly, errors unwind): the
            # keepalive must die with it, or it re-arms forever — JOINing
            # a dead federation every cycle and keeping this worker's
            # whole comm stack (and its registry counters) alive
            self._done = True
            self._cancel_keepalive()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self.handle_assignment)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self.handle_assignment)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_FINISH, self.handle_finish)
        from fedml_tpu.comm.message import MSG_TYPE_FLIGHT_DUMP

        self.register_message_receive_handler(
            MSG_TYPE_FLIGHT_DUMP, self.handle_flight_dump)

    def handle_flight_dump(self, msg: Message) -> None:
        """Server-broadcast incident capture (obs/flight.py): flush this
        rank's flight ring into the broadcast incident id's bundle
        (idempotent; no-op while the recorder is off)."""
        from fedml_tpu.obs import flight as _flight

        _flight.handle_dump_message(msg.get_params(), rank=self.rank)

    def _send_join(self) -> None:
        if self._done:
            return
        try:
            self.send_message(Message(MSG_TYPE_C2S_JOIN, self.rank, 0))
        except Exception as e:   # best-effort: retried by the next timer
            LOG.debug("rank %d JOIN failed (%s)", self.rank, e)

    def _arm_keepalive(self) -> None:
        def fire():
            self._send_join()
            self._arm_keepalive()

        with self._ka_lock:
            if self._keepalive is not None:
                self._keepalive.cancel()
                self._keepalive = None
            if self._done:
                return
            t = threading.Timer(self._keepalive_s, fire)
            t.daemon = True
            t.start()
            self._keepalive = t

    def _cancel_keepalive(self) -> None:
        with self._ka_lock:
            if self._keepalive is not None:
                self._keepalive.cancel()
                self._keepalive = None

    def handle_finish(self, msg: Message) -> None:
        self._done = True
        self._cancel_keepalive()
        self.finish()

    def handle_assignment(self, msg: Message) -> None:
        # keepalive measures SERVER silence while this worker is idle —
        # not its own training time: cancel for the (synchronous,
        # receive-loop-thread) training below and re-arm once the upload
        # is away, or any assignment training longer than the interval
        # would JOIN mid-train and earn a duplicate retrain of every tag
        self._cancel_keepalive()
        tag = int(msg.get(MSG_ARG_KEY_TRAIN_TAG))
        version = int(msg.get(MSG_ARG_KEY_VERSION))
        variables = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        self.trainer.update_dataset(msg.get(MSG_ARG_KEY_CLIENT_INDEX))
        new_vars, n = self.trainer.train(variables, tag, self.root_key)
        from fedml_tpu.core.pytree import tree_sub

        delta = tree_sub(new_vars, jax.tree.map(np.asarray, variables))
        out = Message(MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        out.add_params(MSG_ARG_KEY_MODEL_DELTA, delta)
        out.add_params(MSG_ARG_KEY_NUM_SAMPLES, n)
        out.add_params(MSG_ARG_KEY_TRAIN_TAG, tag)
        out.add_params(MSG_ARG_KEY_VERSION, version)
        self.send_message(out)
        self._arm_keepalive()   # idle again: the silence clock starts now


def build_fedbuff_rank(dataset, config, rank: int, world_size: int, comm,
                       bundle=None, root_key=None, aggregator=None):
    """Build ONE rank's manager (mirrors fedavg_edge.build_edge_rank:
    model init + federation RNG derive from ``config.seed``, so separate
    processes construct identical initial state)."""
    from fedml_tpu.core.rng import seed_everything

    if bundle is None:
        bundle = create_model(
            config.model, dataset.class_num,
            input_shape=dataset.train_x.shape[2:] or None)
    if root_key is None:
        root_key = seed_everything(config.seed)
    args = _edge_args(config, dataset)
    if rank == 0:
        if aggregator is None:
            aggregator = FedBuffAggregator(
                bundle.init(root_key), world_size - 1, config,
                dataset=dataset, bundle=bundle)
        return FedBuffEdgeServerManager(args, comm, 0, world_size,
                                        aggregator)
    trainer = FedAVGTrainer(dataset, bundle, config)
    return FedBuffEdgeClientManager(args, comm, rank, world_size, trainer,
                                    root_key)


def run_fedbuff_edge(dataset, config, worker_num: int,
                     wire_roundtrip: bool = True, comm_factory=None,
                     timeout: float = 300.0, profile_snapshot=None):
    """In-process launch: 1 async server + ``worker_num`` workers over the
    local transport (or a real one via ``comm_factory`` — the chaos/grpc
    tests' path). ``config.comm_round`` is the number of model VERSIONS to
    emit. ``profile_snapshot`` freezes the fedsched scheduling signal
    (``set_static_profile``) for the speed/fair policies' deterministic
    mode. Returns the server's aggregator (final model + per-version test
    history + fold accounting + wire stats)."""
    from fedml_tpu.core.rng import seed_everything
    from fedml_tpu.obs import configure_from

    configure_from(config)
    bundle = create_model(config.model, dataset.class_num,
                          input_shape=dataset.train_x.shape[2:] or None)
    root_key = seed_everything(config.seed)
    size = worker_num + 1
    aggregator = FedBuffAggregator(bundle.init(root_key), worker_num,
                                   config, dataset=dataset, bundle=bundle)

    def make(rank, comm):
        mgr = build_fedbuff_rank(dataset, config, rank, size, comm,
                                 bundle=bundle, root_key=root_key,
                                 aggregator=aggregator)
        if rank == 0 and profile_snapshot is not None:
            mgr.scheduler.set_static_profile(profile_snapshot)
        return mgr

    from fedml_tpu.comm.reliable import wire_wrap_factory

    managers = run_ranks(make, size, wire_roundtrip=wire_roundtrip,
                         comm_factory=comm_factory, timeout=timeout,
                         codec=getattr(config, "wire_codec", "raw"),
                         wrap=wire_wrap_factory(config),
                         inbox_cap=int(getattr(config, "wire_inbox_cap", 0) or 0))
    # Release every rank's wire stack explicitly: a crash-stopped rank's
    # receive loop exits WITHOUT reaching finish(), and an un-stopped
    # reliable layer's retransmit thread is an immortal reference to its
    # registry counter groups — the crash's gave_up counts would haunt
    # every later federation's wire snapshots in this process. Idempotent
    # for the ranks that did finish.
    for m in managers:
        try:
            m.com_manager.stop_receive_message()
        except Exception:   # already torn down
            pass
    from fedml_tpu.utils.metrics import merge_wire_stats

    aggregator.wire_stats = merge_wire_stats(
        [m.com_manager for m in managers])
    anomalies = ("wire/retransmits", "wire/retransmit_errors",
                 "wire/gave_up", "wire/dup_dropped")
    if any(aggregator.wire_stats.get(k, 0) for k in anomalies) or any(
            k.startswith("chaos/") and v
            for k, v in aggregator.wire_stats.items()):
        LOG.info("wire stats: %s", aggregator.wire_stats)
    return aggregator
