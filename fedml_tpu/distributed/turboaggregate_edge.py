"""Message-driven TurboAggregate — the secure-aggregation protocol over the
edge transport.

Counterpart of reference fedml_api/distributed/turboaggregate/
(TA_decentralized_worker_manager.py + TA_fedavg.py): workers hold additive
shares of each group-mate's masked update, group leaders relay the running
field total along the group ring, and only the final unmasked total reaches
the server. The reference runs this over MPI with torch state dicts; here the
same group-relay topology runs over the framework's Message transports
(comm/local.py threads, or gRPC via ``comm_factory``), and the field math is
the vectorized int64 MPC kernel shared with the host-simulated form
(algorithms/turboaggregate.py) — so the recovered aggregate is BIT-EQUAL to
``secure_weighted_sum`` on the same inputs (additive masks cancel exactly in
the prime field, whatever RNG drew them).

Per round, with C clients in G = max(1, C // group_size) round-robin groups
(group g = clients {g, g+G, ...}, matching secure_weighted_sum's grouping):

  server --SYNC(model, weight)-->  every client
  client: local-train, q = quantize(flat_update * w), split q into
          |group| additive shares, one --SHARE--> per group-mate
  client: sum of received shares  --PARTIAL--> group leader
  leader: own partials + relay-in --RELAY-->   next group's leader
  last leader                     --TOTAL-->   server (dequantize, next round)

No hop ever sees a client's update in the clear: shares and partial sums are
field-uniform until the final total is unmasked at the server.
"""

from __future__ import annotations

import jax
import numpy as np

from fedml_tpu.algorithms.turboaggregate import (
    P_DEFAULT,
    additive_shares,
    dequantize,
    quantize,
)
from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.comm.message import MSG_ARG_KEY_MODEL_PARAMS
from fedml_tpu.core.rng import round_key, seed_everything
from fedml_tpu.core.tasks import get_task
from fedml_tpu.models import create_model
from fedml_tpu.parallel.local import finalize_metrics, make_eval_fn, make_local_train_fn

MSG_TYPE_S2C_SYNC = "ta_sync"        # server -> clients: model + round + weight
MSG_TYPE_C2C_SHARE = "ta_share"      # additive share to a group-mate
MSG_TYPE_C2L_PARTIAL = "ta_partial"  # masked partial sum to the group leader
MSG_TYPE_L2L_RELAY = "ta_relay"      # running field total along the group ring
MSG_TYPE_L2S_TOTAL = "ta_total"      # final field total to the server
MSG_TYPE_S2C_FINISH = "ta_finish"

KEY_ROUND = "round"
KEY_WEIGHT = "weight"
KEY_FIELD = "field"          # int64 field vector payload
KEY_LOSS_SUM = "loss_sum"    # non-secret metric riding the relay
KEY_COUNT_SUM = "count_sum"


def _unflatten_template(variables):
    """(treedef, shapes, dtypes) for field-vector <-> pytree mapping —
    shared by both server managers so the two protocol paths cannot
    drift."""
    import jax as _jax

    leaves, treedef = _jax.tree.flatten(_jax.tree.map(np.asarray, variables))
    return treedef, [l.shape for l in leaves], [l.dtype for l in leaves]


def _unflatten_flat(flat, treedef, shapes, dtypes):
    out, off = [], 0
    for shape, dtype in zip(shapes, dtypes):
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out.append(flat[off:off + n].reshape(shape).astype(dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


def _ckpt_setup(server, cfg, fname: str) -> None:
    """Checkpoint/resume wiring shared by both TA server managers
    (mirrors fedavg_edge): server state = variables + round + history —
    client mask RNGs need no persistence because the additive/BGW masks
    cancel exactly in the field, so a resumed run's aggregate is
    bit-identical whatever masks the restarted clients draw."""
    import os

    server._ckpt_path = None
    if getattr(cfg, "checkpoint_dir", None):
        os.makedirs(cfg.checkpoint_dir, exist_ok=True)
        server._ckpt_path = os.path.join(cfg.checkpoint_dir, fname)
    server._ckpt_freq = int(getattr(cfg, "checkpoint_frequency", 10) or 10)
    resume = getattr(cfg, "resume_from", None)
    if resume:
        from fedml_tpu.utils.checkpoint import load_checkpoint

        state = load_checkpoint(resume)
        server.variables = state["variables"]
        server.round_idx = int(state["round_idx"])
        for k, v in state["extra"].get("history", {}).items():
            server.history[k] = list(v)


def _ckpt_maybe(server) -> None:
    if server._ckpt_path is None:
        return
    if (server.round_idx % server._ckpt_freq == 0
            or server.round_idx >= server.round_num):
        from fedml_tpu.utils.checkpoint import save_checkpoint

        save_checkpoint(server._ckpt_path, server.variables,
                        round_idx=server.round_idx,
                        extra={"history": server.history})


def _groups(num_clients: int, group_size: int) -> list[list[int]]:
    """Round-robin grouping, identical to secure_weighted_sum's
    ``range(g, C, n_groups)`` (algorithms/turboaggregate.py:232)."""
    n_groups = max(1, num_clients // group_size)
    return [list(range(g, num_clients, n_groups)) for g in range(n_groups)]


class TAEdgeServerManager(ServerManager):
    """Round driver + unmasker (reference TA_fedavg aggregator role): sends
    the model out, receives ONE field total per round, dequantizes."""

    def __init__(self, args, comm, rank, size, variables, dataset, bundle,
                 frac_bits: int, p=P_DEFAULT):
        super().__init__(args, comm, rank, size)
        self.variables = variables
        self.dataset = dataset
        self.frac_bits = frac_bits
        self.p = p
        self.round_idx = 0
        self.round_num = int(args.comm_round)
        self.history: dict[str, list] = {"round": [], "Test/Acc": [],
                                         "Test/Loss": [], "Train/Loss": []}
        self._eval = make_eval_fn(bundle, get_task(dataset.task, dataset.class_num))
        # flatten template: leaf order/shape/dtype for field <-> pytree
        self._treedef, self._shapes, self._dtypes = _unflatten_template(variables)
        counts = np.asarray(dataset.train_counts, np.float64)[: size - 1]
        self._weights = counts / counts.sum()
        _ckpt_setup(self, args, "ta_server.ckpt")

    def run(self):
        self.register_message_receive_handlers()
        if self.round_idx >= self.round_num:   # resumed a finished run
            for rank in range(1, self.size):
                self.send_message(Message(MSG_TYPE_S2C_FINISH, self.rank, rank))
            self.finish()
            return
        self._send_sync()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_L2S_TOTAL, self._on_total)

    def _send_sync(self):
        for rank in range(1, self.size):
            m = Message(MSG_TYPE_S2C_SYNC, self.rank, rank)
            m.add_params(MSG_ARG_KEY_MODEL_PARAMS, self.variables)
            m.add_params(KEY_ROUND, self.round_idx)
            m.add_params(KEY_WEIGHT, float(self._weights[rank - 1]))
            self.send_message(m)

    def _on_total(self, msg: Message):
        # wire-protocol invariant: never an assert (stripped under -O, which
        # would turn a misrouted total into silent weight corruption)
        if int(msg.get(KEY_ROUND)) != self.round_idx:
            raise RuntimeError(
                f"TurboAggregate total for round {msg.get(KEY_ROUND)} arrived "
                f"at server in round {self.round_idx}")
        field_total = np.asarray(msg.get(KEY_FIELD), np.int64)
        flat = dequantize(field_total, self.frac_bits, self.p)
        self.variables = _unflatten_flat(flat, self._treedef, self._shapes,
                                         self._dtypes)
        train_loss = float(msg.get(KEY_LOSS_SUM)) / max(float(msg.get(KEY_COUNT_SUM)), 1e-12)
        if (self.round_idx % self.args.frequency_of_the_test == 0
                or self.round_idx == self.round_num - 1):
            sums = self._eval(self.variables, self.dataset.test_x,
                              self.dataset.test_y, self.dataset.test_mask)
            m = finalize_metrics(jax.tree.map(np.asarray, sums))
            self.history["round"].append(self.round_idx)
            self.history["Test/Acc"].append(m.get("acc"))
            self.history["Test/Loss"].append(m.get("loss"))
            self.history["Train/Loss"].append(train_loss)
        self.round_idx += 1
        _ckpt_maybe(self)
        if self.round_idx >= self.round_num:
            for rank in range(1, self.size):
                self.send_message(Message(MSG_TYPE_S2C_FINISH, self.rank, rank))
            self.finish()
            return
        self._send_sync()


class TAEdgeClientManager(ClientManager):
    """Worker: local training + the share/partial/relay legs (reference
    TA_decentralized_worker_manager.py roles, one rank per client)."""

    def __init__(self, args, comm, rank, size, dataset, bundle, config,
                 root_key, group_size: int, frac_bits: int, p=P_DEFAULT):
        super().__init__(args, comm, rank, size)
        self.dataset = dataset
        self.config = config
        self.root_key = root_key
        self.frac_bits = frac_bits
        self.p = p
        self.client_idx = rank - 1
        C = size - 1
        self.num_clients = C
        groups = _groups(C, group_size)
        self._groups_list = groups
        self.gid = self.client_idx % len(groups)
        self.members = groups[self.gid]
        self.my_slot = self.members.index(self.client_idx)
        self.leader = self.members[0]
        self.n_groups = len(groups)
        self.is_leader = self.client_idx == self.leader
        self.last_group = self.gid == self.n_groups - 1
        self._rng = np.random.default_rng([config.seed, 0x7A, self.client_idx])
        self.round_idx = -1
        # a fast group-mate may deliver round-r+1 legs before OUR SYNC(r+1)
        # lands (the server's per-rank sends race with peers' sends); such
        # messages are buffered and replayed right after the SYNC
        self._ahead: list[tuple] = []
        from fedml_tpu.parallel.local import local_train_kwargs

        self.local_train = jax.jit(make_local_train_fn(
            bundle, get_task(dataset.task, dataset.class_num),
            **local_train_kwargs(config),
        ))
        self._reset_round()

    def _reset_round(self):
        self._share_sum = None
        self._n_shares = 0
        self._partial_sum = None
        self._n_partials = 0
        self._relay_in = None
        self._loss_sum = 0.0
        self._count_sum = 0.0

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_SYNC, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_C2C_SHARE, self._on_share)
        self.register_message_receive_handler(MSG_TYPE_C2L_PARTIAL, self._on_partial)
        self.register_message_receive_handler(MSG_TYPE_L2L_RELAY, self._on_relay)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH,
                                              lambda m: self.finish())

    # -- protocol legs -----------------------------------------------------

    def _ahead_of_round(self, msg: Message, handler) -> bool:
        r = int(msg.get(KEY_ROUND))
        if r == self.round_idx:
            return False
        if r < self.round_idx:  # relay gating makes past rounds impossible
            raise RuntimeError(
                f"client {self.client_idx}: stale round {r} message "
                f"(at round {self.round_idx}): {msg}")
        self._ahead.append((handler, msg))
        return True

    def _on_sync(self, msg: Message):
        self._reset_round()
        self.round_idx = int(msg.get(KEY_ROUND))
        if self.gid == 0 and self.is_leader:
            self._relay_in = np.zeros(1, np.int64)  # ring head starts at 0
        variables = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        w = float(msg.get(KEY_WEIGHT))
        x, y, m, count = self.dataset.client_slice_cached(self.client_idx)
        rng = jax.random.split(round_key(self.root_key, self.round_idx),
                               self.num_clients)[self.client_idx]
        res = self.local_train(variables, x[0], y[0], m[0],
                               np.float32(count[0]), rng)
        self._loss_own = float(res.train_loss) * float(count[0])
        self._count_own = float(count[0])
        leaves = jax.tree.leaves(jax.tree.map(np.asarray, res.variables))
        flat = np.concatenate([np.ravel(l).astype(np.float64) for l in leaves])
        q = quantize(flat * w, self.frac_bits, self.p)
        shares = additive_shares(q, len(self.members), self.p, self._rng)
        for slot, member in enumerate(self.members):
            m_out = Message(MSG_TYPE_C2C_SHARE, self.rank, member + 1)
            m_out.add_params(KEY_ROUND, self.round_idx)
            m_out.add_params(KEY_FIELD, shares[slot])
            self.send_message(m_out)
        for handler, pending in self._ahead:
            handler(pending)
        self._ahead.clear()

    def _on_share(self, msg: Message):
        if self._ahead_of_round(msg, self._on_share):
            return
        share = np.asarray(msg.get(KEY_FIELD), np.int64)
        self._share_sum = (share if self._share_sum is None
                           else np.mod(self._share_sum + share, self.p))
        self._n_shares += 1
        if self._n_shares == len(self.members):
            out = Message(MSG_TYPE_C2L_PARTIAL, self.rank, self.leader + 1)
            out.add_params(KEY_ROUND, self.round_idx)
            out.add_params(KEY_FIELD, self._share_sum)
            out.add_params(KEY_LOSS_SUM, self._loss_own)
            out.add_params(KEY_COUNT_SUM, self._count_own)
            self.send_message(out)

    def _on_partial(self, msg: Message):
        if not self.is_leader:
            raise RuntimeError(
                f"rank {self.rank}: partial-sum message routed to a non-leader")
        if self._ahead_of_round(msg, self._on_partial):
            return
        part = np.asarray(msg.get(KEY_FIELD), np.int64)
        self._partial_sum = (part if self._partial_sum is None
                             else np.mod(self._partial_sum + part, self.p))
        self._n_partials += 1
        self._loss_sum += float(msg.get(KEY_LOSS_SUM))
        self._count_sum += float(msg.get(KEY_COUNT_SUM))
        self._maybe_relay()

    def _on_relay(self, msg: Message):
        if not self.is_leader:
            raise RuntimeError(
                f"rank {self.rank}: relay message routed to a non-leader")
        if self._ahead_of_round(msg, self._on_relay):
            return
        self._relay_in = np.asarray(msg.get(KEY_FIELD), np.int64)
        self._loss_sum += float(msg.get(KEY_LOSS_SUM))
        self._count_sum += float(msg.get(KEY_COUNT_SUM))
        self._maybe_relay()

    def _maybe_relay(self):
        if self._relay_in is None or self._n_partials != len(self.members):
            return
        total = np.mod(self._relay_in + self._partial_sum, self.p)
        if self.last_group:
            out = Message(MSG_TYPE_L2S_TOTAL, self.rank, 0)
        else:
            next_leader = self._groups_list[self.gid + 1][0]
            out = Message(MSG_TYPE_L2L_RELAY, self.rank, next_leader + 1)
        out.add_params(KEY_ROUND, self.round_idx)
        out.add_params(KEY_FIELD, total)
        out.add_params(KEY_LOSS_SUM, self._loss_sum)
        out.add_params(KEY_COUNT_SUM, self._count_sum)
        self.send_message(out)


# -------------------------------------------------- threshold (FT) protocol
#
# The ring/additive protocol above is the reference's strict-barrier shape:
# additive shares tolerate ZERO dropouts (every share is needed). But the
# coded machinery TurboAggregate exists for IS a threshold scheme — so when
# ``straggler_deadline_sec`` is set, the federation switches to BGW/Shamir
# threshold aggregation (algorithms/turboaggregate.py bgw_encode/decode;
# reference mpc_function.py:62-108 — the N-T reconstruction the r4 verdict
# named):
#
#   server --SYNC(model, w_j)--> live clients
#   client j: train; q_j = quantize(flat_j * w_j); deal BGW shares of q_j
#             (degree-T polynomial, evaluation alpha_i = i+1) one per peer,
#             THEN --DEALT(count, loss)--> server.  (Sends are synchronous:
#             a DEALT that arrived implies every share before it arrived.)
#   server:   on all-live DEALT or deadline -> D = dealers that reported;
#             --REVEAL(D)--> live clients
#   client i: S_i = sum_{j in D} share_{j->i} mod p   --EVAL(S_i)--> server
#   server:   S_i are evaluations of F = sum_{j in D} f_j at alpha_i, a
#             degree-T polynomial with F(0) = sum q_j — ANY T+1 surviving
#             evaluations reconstruct the aggregate (bgw_decode), so up to
#             live - (T+1) clients can die between phases and the round
#             still closes. Privacy: any <=T colluders see <=T evaluations
#             of a degree-T masked polynomial — nothing.

MSG_TYPE_C2C_TSHARE = "ta_tshare"    # dealer -> peer: BGW share
MSG_TYPE_C2S_DEALT = "ta_dealt"      # dealer -> server: shares all delivered
MSG_TYPE_S2C_REVEAL = "ta_reveal"    # server -> clients: dealer set D
MSG_TYPE_C2S_EVAL = "ta_eval"        # client -> server: S_i evaluation

KEY_DEALER = "dealer"
KEY_CLIENT = "client"
KEY_DEALERS = "dealers"
KEY_COUNT = "count"
KEY_LOSS = "loss"
KEY_GEN = "gen"   # attempt generation: a deadline re-run re-deals fresh
#                   polynomials, so stale phase messages must never mix in


class TAThresholdServerManager(ServerManager):
    """Fault-tolerant TurboAggregate server: two deadline-guarded phases
    (deal, eval) per round; reconstruction from any >= T+1 evaluations."""

    def __init__(self, args, comm, rank, size, variables, dataset, bundle,
                 frac_bits: int, threshold_t: int, deadline: float,
                 p=P_DEFAULT):
        super().__init__(args, comm, rank, size)
        from fedml_tpu.distributed.base_framework import (
            RoundDeadlineTimer, require_injectable)

        require_injectable(comm)
        self.variables = variables
        self.dataset = dataset
        self.frac_bits = frac_bits
        self.T = int(threshold_t)
        self.p = p
        self.round_idx = 0
        self.round_num = int(args.comm_round)
        self.num_clients = size - 1
        if self.num_clients < self.T + 1:
            raise ValueError(
                f"threshold T={self.T} needs at least T+1="
                f"{self.T + 1} clients; got {self.num_clients}")
        self.history: dict[str, list] = {"round": [], "Test/Acc": [],
                                         "Test/Loss": [], "Train/Loss": []}
        self._eval_fn = make_eval_fn(bundle,
                                     get_task(dataset.task, dataset.class_num))
        self._treedef, self._shapes, self._dtypes = _unflatten_template(variables)
        counts = np.asarray(dataset.train_counts,
                            np.float64)[: self.num_clients]
        self._weights = counts / counts.sum()
        self._alive = {i: True for i in range(self.num_clients)}
        self._phase = "deal"
        self._dealt: dict[int, tuple] = {}
        self._evals: dict[int, np.ndarray] = {}
        self._dealers: list[int] = []
        self._empty = 0
        self._gen = 0
        self._timer = RoundDeadlineTimer(comm, deadline, rank, KEY_ROUND)
        _ckpt_setup(self, args, "ta_server.ckpt")

    # -- lifecycle ---------------------------------------------------------
    def run(self):
        self.register_message_receive_handlers()
        if self.round_idx >= self.round_num:   # resumed a finished run
            self._teardown()
            return
        self._send_sync()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        from fedml_tpu.distributed.base_framework import (
            MSG_TYPE_LOCAL_ROUND_DEADLINE)

        self.register_message_receive_handler(MSG_TYPE_C2S_DEALT,
                                              self._on_dealt)
        self.register_message_receive_handler(MSG_TYPE_C2S_EVAL, self._on_eval)
        self.register_message_receive_handler(MSG_TYPE_LOCAL_ROUND_DEADLINE,
                                              self._on_deadline)

    def _live(self):
        return [i for i, a in self._alive.items() if a]

    def _mark_dead(self, cid: int):
        if self._alive.get(cid):
            import logging

            logging.getLogger(__name__).warning(
                "TA threshold: client %d marked dead (round %d, phase %s)",
                cid, self.round_idx, self._phase)
            self._alive[cid] = False

    def _send_sync(self):
        self._phase = "deal"
        self._dealt = {}
        self._evals = {}
        self._gen += 1
        for cid in self._live():
            m = Message(MSG_TYPE_S2C_SYNC, self.rank, cid + 1)
            m.add_params(MSG_ARG_KEY_MODEL_PARAMS, self.variables)
            m.add_params(KEY_ROUND, self.round_idx)
            m.add_params(KEY_GEN, self._gen)
            m.add_params(KEY_WEIGHT, float(self._weights[cid]))
            try:
                self.send_message(m)
            except Exception:
                self._mark_dead(cid)
        if not self._live():
            self._teardown()
            return
        # tag = gen*2 + phase: unique per (attempt, phase), so a timer that
        # fired into the queue just before cancel() is always recognisably
        # stale (a re-run round re-deals fresh polynomials under a new gen)
        self._timer.arm(self._gen * 2)

    # -- phase 1: dealing --------------------------------------------------
    def _on_dealt(self, msg: Message):
        if int(msg.get(KEY_GEN)) != self._gen or self._phase != "deal":
            return  # late report from a slow/dead-marked client or attempt
        cid = int(msg.get(KEY_CLIENT))
        self._dealt[cid] = (float(msg.get(KEY_COUNT)), float(msg.get(KEY_LOSS)))
        if set(self._dealt) >= set(self._live()):
            self._start_reveal()

    def _start_reveal(self):
        self._timer.cancel()
        self._empty = 0   # progress: the budget counts CONSECUTIVE stalls
        self._dealers = sorted(self._dealt)
        self._phase = "eval"
        for cid in self._live():
            m = Message(MSG_TYPE_S2C_REVEAL, self.rank, cid + 1)
            m.add_params(KEY_ROUND, self.round_idx)
            m.add_params(KEY_GEN, self._gen)
            m.add_params(KEY_DEALERS, np.asarray(self._dealers, np.int64))
            try:
                self.send_message(m)
            except Exception:
                self._mark_dead(cid)
        self._timer.arm(self._gen * 2 + 1)

    # -- phase 2: evaluations ---------------------------------------------
    def _on_eval(self, msg: Message):
        if int(msg.get(KEY_GEN)) != self._gen or self._phase != "eval":
            return  # stale attempt: its shares were re-dealt since
        cid = int(msg.get(KEY_CLIENT))
        self._evals[cid] = np.asarray(msg.get(KEY_FIELD), np.int64)
        if set(self._evals) >= set(self._live()):
            self._finish_round()

    def _on_deadline(self, msg: Message):
        tag = self._gen * 2 + (0 if self._phase == "deal" else 1)
        if int(msg.get(KEY_ROUND)) != tag:
            return  # stale timer from an already-closed phase/attempt
        from fedml_tpu.distributed.base_framework import MAX_EMPTY_DEADLINES

        if self._phase == "deal":
            if not self._dealt:
                # a FULLY empty window is indistinguishable from everyone
                # still compiling — leave liveness alone and retry, like
                # fedavg_edge, tearing down only after MAX_EMPTY_DEADLINES
                self._empty += 1
                if self._empty >= MAX_EMPTY_DEADLINES:
                    self._teardown()
                    return
                self._send_sync()
                return
            self._empty = 0
            # partial progress: the silent remainder really is dead
            for cid in self._live():
                if cid not in self._dealt:
                    self._mark_dead(cid)
            self._start_reveal()
            return
        # eval phase: the threshold property — any T+1 evaluations close
        # the round even though clients died after dealing
        if len(self._evals) >= self.T + 1:
            for cid in self._live():
                if cid not in self._evals:
                    self._mark_dead(cid)
            self._finish_round()
            return
        # below the threshold: do NOT condemn the silent clients (they may
        # all be slow) — retry the round, bounded by the same empty counter
        self._empty += 1
        if self._empty >= MAX_EMPTY_DEADLINES:
            import logging

            logging.getLogger(__name__).error(
                "TA threshold: %d evaluations < T+1=%d after %d windows — "
                "cannot reconstruct; tearing down",
                len(self._evals), self.T + 1, self._empty)
            self._teardown()
            return
        self._send_sync()  # re-run the round

    def _finish_round(self):
        self._timer.cancel()
        self._empty = 0
        ids = sorted(self._evals)
        shares = np.stack([self._evals[i] for i in ids])
        from fedml_tpu.algorithms.turboaggregate import bgw_decode

        field_sum = bgw_decode(shares, ids, self.p)
        w_d = float(sum(self._weights[d] for d in self._dealers))
        flat = dequantize(field_sum, self.frac_bits, self.p) / max(w_d, 1e-12)
        self.variables = _unflatten_flat(flat, self._treedef, self._shapes,
                                         self._dtypes)
        loss_sum = sum(l for _c, l in self._dealt.values())
        count_sum = sum(c for c, _l in self._dealt.values())
        train_loss = loss_sum / max(count_sum, 1e-12)
        if (self.round_idx % self.args.frequency_of_the_test == 0
                or self.round_idx == self.round_num - 1):
            sums = self._eval_fn(self.variables, self.dataset.test_x,
                                 self.dataset.test_y, self.dataset.test_mask)
            m = finalize_metrics(jax.tree.map(np.asarray, sums))
            self.history["round"].append(self.round_idx)
            self.history["Test/Acc"].append(m.get("acc"))
            self.history["Test/Loss"].append(m.get("loss"))
            self.history["Train/Loss"].append(train_loss)
        self.round_idx += 1
        _ckpt_maybe(self)
        if self.round_idx >= self.round_num:
            self._teardown()
            return
        self._send_sync()

    def _teardown(self):
        self._timer.cancel()
        # FINISH goes to EVERY rank, dead-marked included: over the local
        # transport a "dead" client is a live thread that must still exit
        for cid in range(self.num_clients):
            try:
                self.send_message(
                    Message(MSG_TYPE_S2C_FINISH, self.rank, cid + 1))
            except Exception:
                pass
        self.finish()


class TAThresholdClientManager(ClientManager):
    """Fault-tolerant TurboAggregate worker: deal BGW shares, then reveal
    the share-sum over the server's dealer set."""

    def __init__(self, args, comm, rank, size, dataset, bundle, config,
                 root_key, threshold_t: int, frac_bits: int, p=P_DEFAULT):
        super().__init__(args, comm, rank, size)
        self.dataset = dataset
        self.config = config
        self.root_key = root_key
        self.frac_bits = frac_bits
        self.T = int(threshold_t)
        self.p = p
        self.client_idx = rank - 1
        self.num_clients = size - 1
        self._rng = np.random.default_rng([config.seed, 0x7B, self.client_idx])
        self.round_idx = -1
        self._gen = 0
        self._shares: dict[int, np.ndarray] = {}
        self._ahead: list[tuple] = []
        from fedml_tpu.parallel.local import local_train_kwargs

        self.local_train = jax.jit(make_local_train_fn(
            bundle, get_task(dataset.task, dataset.class_num),
            **local_train_kwargs(config),
        ))

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_SYNC, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_C2C_TSHARE,
                                              self._on_tshare)
        self.register_message_receive_handler(MSG_TYPE_S2C_REVEAL,
                                              self._on_reveal)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH,
                                              lambda m: self.finish())

    def _ahead_of_round(self, msg: Message, handler) -> bool:
        r = int(msg.get(KEY_ROUND))
        if r == self.round_idx:
            return False
        if r < self.round_idx:
            return True  # stale leftovers of a re-run round: drop
        self._ahead.append((handler, msg))
        return True

    def _on_sync(self, msg: Message):
        self.round_idx = int(msg.get(KEY_ROUND))
        self._gen = int(msg.get(KEY_GEN))
        self._shares = {}
        variables = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        w = float(msg.get(KEY_WEIGHT))
        x, y, m, count = self.dataset.client_slice_cached(self.client_idx)
        rng = jax.random.split(round_key(self.root_key, self.round_idx),
                               self.num_clients)[self.client_idx]
        res = self.local_train(variables, x[0], y[0], m[0],
                               np.float32(count[0]), rng)
        leaves = jax.tree.leaves(jax.tree.map(np.asarray, res.variables))
        flat = np.concatenate([np.ravel(l).astype(np.float64)
                               for l in leaves])
        q = quantize(flat * w, self.frac_bits, self.p)
        from fedml_tpu.algorithms.turboaggregate import bgw_encode

        shares = bgw_encode(q, self.num_clients, self.T, self.p, self._rng)
        for peer in range(self.num_clients):
            if peer == self.client_idx:
                self._shares[self.client_idx] = shares[peer]
                continue
            out = Message(MSG_TYPE_C2C_TSHARE, self.rank, peer + 1)
            out.add_params(KEY_ROUND, self.round_idx)
            out.add_params(KEY_GEN, self._gen)
            out.add_params(KEY_DEALER, self.client_idx)
            out.add_params(KEY_FIELD, shares[peer])
            try:
                self.send_message(out)
            except Exception:
                continue  # dead peer: its share is simply lost
        done = Message(MSG_TYPE_C2S_DEALT, self.rank, 0)
        done.add_params(KEY_ROUND, self.round_idx)
        done.add_params(KEY_GEN, self._gen)
        done.add_params(KEY_CLIENT, self.client_idx)
        done.add_params(KEY_COUNT, float(count[0]))
        done.add_params(KEY_LOSS, float(res.train_loss) * float(count[0]))
        self.send_message(done)
        # snapshot-and-swap: replayed handlers may legitimately RE-buffer
        # messages that are still ahead (a gen+2 share during the gen+1
        # replay) — iterating the live list would chase its own appends
        pending, self._ahead = self._ahead, []
        for handler, msg_p in pending:
            handler(msg_p)

    def _on_tshare(self, msg: Message):
        if self._ahead_of_round(msg, self._on_tshare):
            return
        g = int(msg.get(KEY_GEN))
        if g > self._gen:
            # a faster peer already started the re-run attempt: buffer the
            # share and replay it after OUR re-SYNC lands
            self._ahead.append((self._on_tshare, msg))
            return
        if g < self._gen:
            return  # share from a superseded attempt
        self._shares[int(msg.get(KEY_DEALER))] = np.asarray(
            msg.get(KEY_FIELD), np.int64)

    def _on_reveal(self, msg: Message):
        if self._ahead_of_round(msg, self._on_reveal):
            return
        g = int(msg.get(KEY_GEN))
        if g > self._gen:
            self._ahead.append((self._on_reveal, msg))
            return
        if g < self._gen:
            return  # reveal of a superseded attempt: shares re-dealt since
        dealers = np.asarray(msg.get(KEY_DEALERS), np.int64)
        missing = [int(d) for d in dealers if int(d) not in self._shares]
        if missing:
            # protocol invariant (DEALT-after-shares ordering) violated
            raise RuntimeError(
                f"client {self.client_idx}: REVEAL names dealers {missing} "
                f"whose shares never arrived (round {self.round_idx})")
        s = np.zeros_like(self._shares[int(dealers[0])])
        for d in dealers:
            s = np.mod(s + self._shares[int(d)], self.p)
        out = Message(MSG_TYPE_C2S_EVAL, self.rank, 0)
        out.add_params(KEY_ROUND, self.round_idx)
        out.add_params(KEY_GEN, self._gen)
        out.add_params(KEY_CLIENT, self.client_idx)
        out.add_params(KEY_FIELD, s)
        self.send_message(out)


def run_turboaggregate_edge(dataset, config, group_size: int = 2,
                            frac_bits: int = 20, wire_roundtrip: bool = True,
                            comm_factory=None, threshold_t: int = 1):
    """Launch 1 server + num_clients workers over the local transport (or a
    real one via ``comm_factory``) and run the full secure-relay federation.
    Returns the server manager (final ``variables`` + ``history``).

    With ``config.straggler_deadline_sec`` set, runs the BGW threshold
    protocol instead of the strict additive ring: up to live-(T+1) clients
    may die mid-round and the server still reconstructs the aggregate."""
    from fedml_tpu.obs import configure_from

    configure_from(config)
    C = min(config.client_num_in_total, dataset.num_clients)
    bundle = create_model(config.model, dataset.class_num,
                          input_shape=dataset.train_x.shape[2:] or None)
    root_key = seed_everything(config.seed)
    variables0 = jax.tree.map(np.asarray, bundle.init(root_key))
    size = C + 1

    args = config  # carries comm_round / frequency_of_the_test / ckpt knobs

    holder = {}
    deadline = getattr(config, "straggler_deadline_sec", None)

    def make(rank, comm):
        if deadline is not None:
            if rank == 0:
                holder["server"] = TAThresholdServerManager(
                    args, comm, rank, size, variables0, dataset, bundle,
                    frac_bits, threshold_t, float(deadline))
                return holder["server"]
            return TAThresholdClientManager(
                args, comm, rank, size, dataset, bundle, config, root_key,
                threshold_t, frac_bits)
        if rank == 0:
            holder["server"] = TAEdgeServerManager(
                args, comm, rank, size, variables0, dataset, bundle, frac_bits)
            return holder["server"]
        return TAEdgeClientManager(args, comm, rank, size, dataset, bundle,
                                   config, root_key, group_size, frac_bits)

    from fedml_tpu.comm.reliable import wire_wrap_factory

    run_ranks(make, size, wire_roundtrip=wire_roundtrip,
              comm_factory=comm_factory, wrap=wire_wrap_factory(config))
    return holder["server"]
