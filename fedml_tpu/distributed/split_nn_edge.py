"""Message-driven SplitNN for genuinely remote clients.

Reference: fedml_api/distributed/split_nn/ — the full per-batch protocol:
client sends (acts, labels) [MSG 2], server replies with activation
gradients [MSG 1] during train; validation mode/over signals [MSG 3/4];
relay semaphore client->client [MSG 6]; protocol finished [MSG 5]
(message_define.py:1-25, client_manager.py:17-87, server_manager.py:16-46).

JAX twist: the reference keeps autograd state across the wire
(``acts.retain_grad()`` then ``acts.backward(grads)``). A functional
backward can't hold living graph state, so the client recomputes its stage
under ``jax.vjp`` when the gradient arrives — pure rematerialization, one
extra client-stage forward, no stateful tape. In-datacenter use
algorithms/split_nn.py instead, which fuses the whole exchange into one XLA
program per batch scan.
"""

from __future__ import annotations

import logging

import jax
import numpy as np
import optax

from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.core.tasks import get_task
from fedml_tpu.parallel.local import make_optimizer

log = logging.getLogger(__name__)

# message_define.py:1-25
MSG_TYPE_S2C_GRADS = 1
MSG_TYPE_C2S_SEND_ACTS = 2
MSG_TYPE_C2S_VALIDATION_MODE = 3
MSG_TYPE_C2S_VALIDATION_OVER = 4
MSG_TYPE_C2S_PROTOCOL_FINISHED = 5
MSG_TYPE_C2C_SEMAPHORE = 6
# managed-ring (fault-tolerant) mode additions — no reference counterpart:
# the reference's ring stalls forever on a dead client
MSG_TYPE_C2S_TURN_DONE = 7
MSG_TYPE_S2C_FINISHED = 8

MSG_ARG_KEY_ACTS = "activations"
MSG_ARG_KEY_LABELS = "labels"
MSG_ARG_KEY_MASK = "mask"
MSG_ARG_KEY_GRADS = "activation_grads"


class SplitNNClientTrainer:
    """Client-stage compute (reference split_nn/client.py:4-42)."""

    def __init__(self, client_bundle, config, x, y, mask, n_batches, test_x, test_y):
        self.bundle = client_bundle
        self.variables = None  # set by the API before run
        self.tx = make_optimizer(config.client_optimizer, config.lr, config.momentum, config.wd)
        self.opt_state = None
        self.x, self.y, self.mask = x, y, mask
        self.test_x, self.test_y = test_x, test_y
        self.n_batches = int(n_batches)
        self.batch_size = config.batch_size
        self.batch_idx = 0
        self.phase = "train"
        self._last_x = None

        # Both forward and the vjp recompute must trace the SAME function:
        # train=False in both, so d_acts from the server corresponds exactly
        # to the recomputed graph. Stochastic/stateful client stages
        # (dropout, BN) belong in the fused path (algorithms/split_nn.py),
        # where forward and backward live in one program by construction.
        @jax.jit
        def fwd(variables, bx):
            return self.bundle.module.apply(variables, bx, train=False)

        @jax.jit
        def bwd_step(variables, opt_state, bx, d_acts):
            def acts_fn(params):
                return self.bundle.module.apply({**variables, "params": params}, bx, train=False)

            _, vjp_fn = jax.vjp(acts_fn, variables["params"])
            (grads,) = vjp_fn(d_acts)
            updates, new_opt = self.tx.update(grads, opt_state, variables["params"])
            params = optax.apply_updates(variables["params"], updates)
            return {**variables, "params": params}, new_opt

        self._fwd = fwd
        self._bwd = bwd_step

    def init(self, variables):
        self.variables = variables
        self.opt_state = self.tx.init(variables["params"])

    def train_mode(self):
        self.phase = "train"
        self.batch_idx = 0

    def eval_mode(self):
        self.phase = "validation"
        self.batch_idx = 0

    @property
    def n_eval_batches(self) -> int:
        return self.test_x.shape[0] // self.batch_size

    def forward_pass(self):
        bs = self.batch_size
        if self.phase == "train":
            i = self.batch_idx % self.n_batches
            bx = self.x[i * bs : (i + 1) * bs]
            by = self.y[i * bs : (i + 1) * bs]
            bm = self.mask[i * bs : (i + 1) * bs]
        else:
            i = self.batch_idx % max(self.n_eval_batches, 1)
            bx = self.test_x[i * bs : (i + 1) * bs]
            by = self.test_y[i * bs : (i + 1) * bs]
            bm = np.ones((bx.shape[0],), np.float32)  # eval rows are pre-filtered real
        self._last_x = bx
        acts = self._fwd(self.variables, bx)
        self.batch_idx += 1
        return np.asarray(acts), np.asarray(by), np.asarray(bm, np.float32)

    def backward_pass(self, grads):
        self.variables, self.opt_state = self._bwd(
            self.variables, self.opt_state, self._last_x, grads
        )


class SplitNNServerTrainer:
    """Server-stage compute (reference split_nn/server.py:7-73)."""

    def __init__(self, server_bundle, config, task, max_rank: int):
        self.bundle = server_bundle
        self.task = task
        self.tx = make_optimizer(config.client_optimizer, config.lr, config.momentum, config.wd)
        self.variables = None
        self.opt_state = None
        self.MAX_RANK = max_rank
        self.active_node = 1
        self.phase = "train"
        self.epoch = 0
        self.total = 0.0
        self.correct = 0.0
        self.val_history: list[float] = []

        @jax.jit
        def train_step(variables, opt_state, acts, labels, mask):
            def loss_fn(params, acts_in):
                logits = self.bundle.module.apply({**variables, "params": params}, acts_in, train=True)
                return self.task.loss(logits, labels, mask), logits

            (loss, logits), (gp, g_acts) = jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)(
                variables["params"], acts
            )
            updates, new_opt = self.tx.update(gp, opt_state, variables["params"])
            params = optax.apply_updates(variables["params"], updates)
            correct = jax.numpy.sum((jax.numpy.argmax(logits, -1) == labels) * mask)
            return {**variables, "params": params}, new_opt, g_acts, loss, correct

        @jax.jit
        def eval_step(variables, acts, labels, mask):
            logits = self.bundle.apply_eval(variables, acts)
            correct = jax.numpy.sum((jax.numpy.argmax(logits, -1) == labels) * mask)
            return correct

        self._train_step = train_step
        self._eval_step = eval_step

    def init(self, variables):
        self.variables = variables
        self.opt_state = self.tx.init(variables["params"])

    def train_mode(self):
        self.phase = "train"
        self.total = self.correct = 0.0

    def eval_mode(self):
        self.phase = "validation"
        self.total = self.correct = 0.0

    def forward_backward(self, acts, labels, mask):
        if self.phase == "train":
            self.variables, self.opt_state, g_acts, loss, correct = self._train_step(
                self.variables, self.opt_state, acts, labels, mask
            )
            self.total += float(mask.sum())
            self.correct += float(correct)
            return np.asarray(g_acts)
        self.total += float(mask.sum())
        self.correct += float(self._eval_step(self.variables, acts, labels, mask))
        return None

    def validation_over(self):
        acc = self.correct / max(self.total, 1.0)
        self.val_history.append(acc)
        log.info("splitnn_edge epoch %d val_acc %.4f", self.epoch, acc)
        self.epoch += 1
        self.active_node = (self.active_node % self.MAX_RANK) + 1
        self.train_mode()


class SplitNNEdgeServerManager(ServerManager):
    """Strict mode: passive compute peer (the reference's shape). Managed
    mode (``deadline`` set): the server OWNS the relay ring — clients
    report TURN_DONE instead of passing the semaphore peer-to-peer, and a
    client that stops producing activations within the deadline is marked
    dead and the ring re-forms around it (the r4 verdict's SplitNN item)."""

    def __init__(self, args, comm, rank, size, trainer: SplitNNServerTrainer,
                 deadline: float | None = None, max_turns: int | None = None):
        super().__init__(args, comm, rank, size)
        self.trainer = trainer
        self.deadline = deadline
        self._alive = {r: True for r in range(1, size)}
        trainer.ring_alive = self._alive  # surfaced on the returned trainer
        self._ring = list(range(1, size))
        self._pos = -1
        self._activity = 0
        self._timer = None
        #: staged-rollout/ops control: stop (checkpointing) after k turns
        self._max_turns = max_turns
        self._turns_done = 0
        # checkpoint/resume (managed mode only — the server owns the ring
        # position there): server state = top-half weights + optimizer +
        # completed ring position + val history. Client bottom halves stay
        # with the clients (turns=1: a completed client's weights are not
        # needed by the remaining turns).
        cfg = args
        self._ckpt_path = None
        if getattr(cfg, "checkpoint_dir", None):
            import os

            os.makedirs(cfg.checkpoint_dir, exist_ok=True)
            self._ckpt_path = os.path.join(cfg.checkpoint_dir,
                                           "splitnn_server.ckpt")
        resume = getattr(cfg, "resume_from", None)
        if resume:
            from fedml_tpu.utils.checkpoint import load_checkpoint

            state = load_checkpoint(resume)
            trainer.variables = state["variables"]["vars"]
            trainer.opt_state = state["variables"]["opt"]
            self._pos = int(state["round_idx"])
            trainer.epoch = int(state["extra"]["epoch"])
            trainer.val_history.extend(state["extra"]["val_history"])
            log.info("splitnn ring resumed after position %d", self._pos)
        if deadline is not None:
            from fedml_tpu.distributed.base_framework import (
                RoundDeadlineTimer, require_injectable)

            require_injectable(comm)
            self._timer = RoundDeadlineTimer(comm, float(deadline),
                                             rank, "pos")

    def run(self):
        self.register_message_receive_handlers()
        if self.deadline is not None:
            self._advance()   # kick the first live client
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_C2S_SEND_ACTS, self.handle_message_acts)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_VALIDATION_MODE,
            lambda m: None if self._zombie(m) else self.trainer.eval_mode())
        self.register_message_receive_handler(
            MSG_TYPE_C2S_VALIDATION_OVER,
            lambda m: None if self._zombie(m) else self.trainer.validation_over())
        self.register_message_receive_handler(MSG_TYPE_C2S_PROTOCOL_FINISHED, self.handle_finish)
        if self.deadline is not None:
            from fedml_tpu.distributed.base_framework import (
                MSG_TYPE_LOCAL_ROUND_DEADLINE)

            self.register_message_receive_handler(MSG_TYPE_C2S_TURN_DONE,
                                                  self._on_turn_done)
            self.register_message_receive_handler(
                MSG_TYPE_LOCAL_ROUND_DEADLINE, self._on_deadline)

    # -- managed ring ------------------------------------------------------
    def _zombie(self, msg: Message) -> bool:
        """Managed mode: True for protocol messages from any rank other
        than the CURRENT live turn-holder — a skipped-then-woken client
        must not flip the shared trainer phase or feed batches into the
        healthy client's turn (review r5 #2)."""
        if self.deadline is None:
            return False
        s_ = msg.get_sender_id()
        return (self._pos >= len(self._ring)
                or self._ring[self._pos] != s_
                or not self._alive.get(s_, False))

    def _advance(self):
        """Hand the turn to the next live client, or finish the ring."""
        while True:
            self._pos += 1
            if self._pos >= len(self._ring):
                self._finish_all()
                return
            nxt = self._ring[self._pos]
            if not self._alive[nxt]:
                continue
            self._activity = 0
            try:
                self.send_message(
                    Message(MSG_TYPE_C2C_SEMAPHORE, self.rank, nxt))
            except Exception as e:
                log.warning("splitnn ring: kick of rank %d failed (%s)",
                            nxt, e)
                self._alive[nxt] = False
                continue
            self._timer.arm(self._pos)
            return

    def _maybe_checkpoint(self):
        if self._ckpt_path is None:
            return
        from fedml_tpu.utils.checkpoint import save_checkpoint

        save_checkpoint(
            self._ckpt_path,
            {"vars": self.trainer.variables, "opt": self.trainer.opt_state},
            round_idx=self._pos,
            extra={"epoch": int(self.trainer.epoch),
                   "val_history": [float(v)
                                   for v in self.trainer.val_history]})

    def _on_turn_done(self, msg: Message):
        if self._zombie(msg):
            return  # late report from an already-skipped client
        self._timer.cancel()
        self._turns_done += 1
        self._maybe_checkpoint()
        if self._max_turns is not None and self._turns_done >= self._max_turns:
            self._finish_all()
            return
        self._advance()

    def _on_deadline(self, msg: Message):
        if int(msg.get("pos")) != self._pos:
            return  # stale timer
        if self._activity > 0:
            # slow but alive: keep waiting another window
            self._activity = 0
            self._timer.arm(self._pos)
            return
        dead = self._ring[self._pos]
        log.warning("splitnn ring: rank %d silent past the %.1fs deadline — "
                    "skipping it and re-forming the ring", dead, self.deadline)
        self._alive[dead] = False
        # drop a half-finished validation phase cleanly
        self.trainer.train_mode()
        self._advance()

    def _finish_all(self):
        if self._timer is not None:
            self._timer.cancel()
        # FINISHED goes to every rank, dead-marked included: in-process
        # "dead" clients are live threads that must still exit
        for r in range(1, self.size):
            try:
                self.send_message(
                    Message(MSG_TYPE_S2C_FINISHED, self.rank, r))
            except Exception:
                pass
        self.finish()

    # -- compute peer ------------------------------------------------------
    def handle_message_acts(self, msg: Message):
        if self._zombie(msg):
            return  # late batch from a skipped client: no grads back — it
            #         parks in handle_gradients instead of corrupting state
        self._activity += 1
        acts = msg.get(MSG_ARG_KEY_ACTS)
        labels = msg.get(MSG_ARG_KEY_LABELS)
        mask = msg.get(MSG_ARG_KEY_MASK)
        grads = self.trainer.forward_backward(
            np.asarray(acts), np.asarray(labels), np.asarray(mask)
        )
        if self.trainer.phase == "train":
            out = Message(MSG_TYPE_S2C_GRADS, self.rank, msg.get_sender_id())
            out.add_params(MSG_ARG_KEY_GRADS, grads)
            try:
                self.send_message(out)
            except Exception as e:
                if self.deadline is None:
                    raise
                dead = msg.get_sender_id()
                log.warning("splitnn ring: grads to rank %d failed (%s)",
                            dead, e)
                self._alive[dead] = False
                if self._ring[self._pos] == dead:
                    self._timer.cancel()
                    self.trainer.train_mode()
                    self._advance()

    def handle_finish(self, msg: Message):
        self.finish()


class SplitNNEdgeClientManager(ClientManager):
    """Reference client_manager.py:8-87 — relay ring with per-batch exchange."""

    def __init__(self, args, comm, rank, size, trainer: SplitNNClientTrainer,
                 epochs_per_turn: int, turns: int, managed: bool = False):
        super().__init__(args, comm, rank, size)
        self.trainer = trainer
        self.epochs_per_turn = epochs_per_turn  # MAX_EPOCH_PER_NODE
        self.turns = turns
        self.turn_idx = 0
        self.epoch_in_turn = 0
        self.MAX_RANK = size - 1
        self.node_right = 1 if rank == self.MAX_RANK else rank + 1
        self.SERVER_RANK = 0
        #: managed mode: the SERVER owns the ring — wait for its semaphore,
        #: report TURN_DONE instead of passing peer-to-peer, finish on its
        #: FINISHED broadcast (fault-tolerant ring re-forming)
        self.managed = managed

    def run(self):
        self.register_message_receive_handlers()
        if self.rank == 1 and not self.managed:
            self.run_forward_pass()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_C2C_SEMAPHORE, self.handle_semaphore)
        self.register_message_receive_handler(MSG_TYPE_S2C_GRADS, self.handle_gradients)
        if self.managed:
            self.register_message_receive_handler(
                MSG_TYPE_S2C_FINISHED, lambda m: self.finish())

    def handle_semaphore(self, msg: Message):
        self.trainer.train_mode()
        self.run_forward_pass()

    def run_forward_pass(self):
        acts, labels, mask = self.trainer.forward_pass()
        m = Message(MSG_TYPE_C2S_SEND_ACTS, self.rank, self.SERVER_RANK)
        m.add_params(MSG_ARG_KEY_ACTS, acts)
        m.add_params(MSG_ARG_KEY_LABELS, labels)
        m.add_params(MSG_ARG_KEY_MASK, mask)
        self.send_message(m)

    def handle_gradients(self, msg: Message):
        self.trainer.backward_pass(np.asarray(msg.get(MSG_ARG_KEY_GRADS)))
        if self.trainer.batch_idx >= self.trainer.n_batches:
            self.epoch_in_turn += 1
            self.run_eval()
        else:
            self.run_forward_pass()

    def run_eval(self):
        self.send_message(Message(MSG_TYPE_C2S_VALIDATION_MODE, self.rank, self.SERVER_RANK))
        self.trainer.eval_mode()
        for _ in range(self.trainer.n_eval_batches):
            self.run_forward_pass()
        self.send_message(Message(MSG_TYPE_C2S_VALIDATION_OVER, self.rank, self.SERVER_RANK))

        if self.epoch_in_turn >= self.epochs_per_turn:
            self.epoch_in_turn = 0
            self.turn_idx += 1
            if self.managed:
                # hand the turn back to the ring owner and await the next
                # semaphore or the FINISHED broadcast
                self.send_message(Message(MSG_TYPE_C2S_TURN_DONE, self.rank,
                                          self.SERVER_RANK))
                return
            if self.turn_idx >= self.turns:
                if self.rank == self.MAX_RANK:
                    # last client of the last turn ends the whole protocol
                    self.send_message(Message(MSG_TYPE_C2S_PROTOCOL_FINISHED, self.rank, self.SERVER_RANK))
                else:
                    self.send_message(Message(MSG_TYPE_C2C_SEMAPHORE, self.rank, self.node_right))
                self.finish()
                return
            self.send_message(Message(MSG_TYPE_C2C_SEMAPHORE, self.rank, self.node_right))
        else:
            self.trainer.train_mode()
            self.run_forward_pass()


def run_splitnn_edge(dataset, config, client_bundle, server_bundle,
                     wire_roundtrip: bool = True, comm_factory=None,
                     max_turns: int | None = None):
    """In-process launch of server + one manager per client over the local
    transport (or a real one — e.g. gRPC loopback — via ``comm_factory``).
    Each client takes ``config.epochs`` epochs per turn and the ring runs
    one full cycle (turns=1), mirroring the reference defaults. Returns the
    server trainer (val_history, final variables).

    ``max_turns`` (managed mode) stops the federation after k completed
    turns, checkpointing — with ``config.checkpoint_dir`` /
    ``config.resume_from`` the ring resumes at the next position,
    reproducing the uninterrupted run's remaining turns exactly.

    With ``config.straggler_deadline_sec`` set the ring is server-managed:
    a client that stops producing activations within the deadline is marked
    dead, the ring re-forms around it, and the remaining clients' turns
    still run (its data is simply unseen — the same drop semantics as
    fedavg_edge's partial aggregation)."""
    from fedml_tpu.core.rng import seed_everything
    from fedml_tpu.obs import configure_from

    configure_from(config)
    deadline = getattr(config, "straggler_deadline_sec", None)

    task = get_task(dataset.task, dataset.class_num)
    n_clients = dataset.num_clients
    size = n_clients + 1
    root = seed_everything(config.seed)
    keys = jax.random.split(root, n_clients + 1)

    bs = config.batch_size
    # per-batch protocol has no mask channel: validate on the REAL test rows
    # only, truncated to a whole number of batches
    real = dataset.test_mask > 0
    test_x_real = dataset.test_x[real]
    test_y_real = dataset.test_y[real]
    n_test = (test_x_real.shape[0] // bs) * bs
    server_trainer = SplitNNServerTrainer(server_bundle, config, task, max_rank=n_clients)
    server_trainer.init(server_bundle.init(keys[-1]))

    class Args:
        pass

    def make(rank, comm):
        if rank == 0:
            return SplitNNEdgeServerManager(config, comm, rank, size,
                                            server_trainer, deadline=deadline,
                                            max_turns=max_turns)
        k = rank - 1
        x, y, m, count = dataset.client_slice_cached(k)
        n_real = int(count[0])
        # ceil: a trailing partial batch trains with its padding rows masked
        # out (padded rows sit at the END of each client's arrays)
        n_batches = min(max(-(-n_real // bs), 1), x.shape[1] // bs)
        trainer = SplitNNClientTrainer(
            client_bundle, config,
            x[0][: n_batches * bs], y[0][: n_batches * bs],
            m[0][: n_batches * bs].astype(np.float32), n_batches,
            test_x_real[:n_test], test_y_real[:n_test],
        )
        trainer.init(client_bundle.init(keys[k]))
        return SplitNNEdgeClientManager(Args(), comm, rank, size, trainer,
                                        epochs_per_turn=config.epochs, turns=1,
                                        managed=deadline is not None)

    from fedml_tpu.comm.reliable import wire_wrap_factory

    run_ranks(make, size, wire_roundtrip=wire_roundtrip,
              comm_factory=comm_factory, wrap=wire_wrap_factory(config))
    return server_trainer
