"""Message-driven FedAvg for edge/off-pod federation (reference distributed/fedavg).

Reference: fedml_api/distributed/fedavg/ — FedAvgServerManager.py:18-95,
FedAvgClientManager.py:18-75, FedAVGAggregator.py:13-163, message_define.py:
1-30. One process per participant, star topology, model weights in messages.

The TPU framework uses this paradigm ONLY at the true network edge (silos
behind gRPC, mobile clients); in-datacenter runs use the mesh-collective
path (parallel/crosssilo.py) which needs no messages at all. Per-worker
compute is the same jitted local-train scan used everywhere else — a worker
simulates `client_num_in_total / workers` logical clients by dataset
re-binding, exactly like the reference's client-sampling concurrency model
(FedAvgClientManager.handle_message_receive_model_from_server:50-61).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import numpy as np

from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
)
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.rng import round_key, sample_clients
from fedml_tpu.core.tasks import get_task
from fedml_tpu.models import create_model
from fedml_tpu.parallel.local import finalize_metrics, make_eval_fn, make_local_train_fn

LOG = logging.getLogger(__name__)

# message_define.py:1-30
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL = 2
MSG_TYPE_C2S_SEND_MODEL = 3
MSG_TYPE_S2C_FINISH = 4
# Beyond the reference protocol (its only failure story is MPI.Abort,
# client_manager.py:66-69): a worker announces itself so a restarted /
# reconnected process can re-enter a running federation.
MSG_TYPE_C2S_JOIN = 5
# Control event injected into the server's OWN queue when the straggler
# deadline fires — shared with fedgkt_edge (base_framework).
from fedml_tpu.distributed.base_framework import (  # noqa: E402
    MAX_EMPTY_DEADLINES,
    MSG_TYPE_LOCAL_ROUND_DEADLINE,
    RoundDeadlineTimer,
    broadcast_flight_dump,
    require_injectable,
)
from fedml_tpu.comm.message import MSG_TYPE_FLIGHT_DUMP  # noqa: E402
# Round tag: syncs carry the server's round index; uploads echo it so the
# server can drop stale uploads from workers that fell behind and rejoined.
MSG_ARG_KEY_ROUND = "round_idx"
# Broadcast generation: bumped on every model broadcast, echoed by uploads.
# Distinguishes pre- vs post-re-deal uploads of the SAME round (an all-fail
# round re-broadcasts round N with the lost clients re-dealt; a slow
# worker's original round-N upload must not be aggregated alongside the
# re-dealt copy of the same clients — the round tag alone can't tell).
MSG_ARG_KEY_GEN = "bcast_gen"

# Extension beyond the reference protocol: with config.wire_delta the client
# uploads (local mean - global) + error-feedback residual under this key
# instead of full weights, so a lossy wire codec (q8 / topk) compresses a
# small-magnitude tensor and the un-sent mass re-enters next round.
MSG_ARG_KEY_MODEL_DELTA = "model_delta"


class FedAVGAggregator:
    """Server-side state: collect worker results, weighted-average, sample.

    Reference FedAVGAggregator.py:13-163. add_local_trained_result /
    check_whether_all_receive / aggregate keep their names; aggregation math
    is the shared tree_weighted_mean primitive.
    """

    def __init__(self, variables, worker_num: int, config, dataset=None, bundle=None):
        self.variables = variables
        self.worker_num = worker_num
        self.config = config
        self.dataset = dataset
        self.model_dict: dict[int, dict] = {}
        self.sample_num_dict: dict[int, float] = {}
        self.flag_client_model_uploaded_dict = {i: False for i in range(worker_num)}
        self.test_history: list[dict] = []
        # exact-once accounting: every accepted upload increments this, so a
        # lossy-wire run can assert no upload was aggregated twice
        # (uploads_accepted == rounds x workers under full participation)
        self.uploads_accepted = 0
        #: fedlens per-round stats ({"workers", "update_norm", "align"}),
        #: set by aggregate() when the lens is armed; the server manager
        #: drains it into the pulse plane after each round closes
        self.lens_stats: Optional[dict] = None
        self._eval = make_eval_fn(bundle, get_task(dataset.task, dataset.class_num)) if bundle is not None and dataset is not None else None
        if getattr(config, "cohort_policy", "uniform") != "uniform":
            LOG.warning(
                "cohort_policy=%r ignored on the edge paradigm: the server "
                "samples uniformly (client_sampling/sample_clients); "
                "profiler-scheduled cohorts are a sim-path feature today",
                config.cohort_policy)

    def get_global_model_params(self):
        return self.variables

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True
        self.uploads_accepted += 1

    def check_whether_all_receive(self) -> bool:
        if not all(self.flag_client_model_uploaded_dict.values()):
            return False
        for i in self.flag_client_model_uploaded_dict:
            self.flag_client_model_uploaded_dict[i] = False
        return True

    def aggregate(self):
        order = sorted(self.model_dict)
        counts = np.asarray([self.sample_num_dict[i] for i in order], np.float32)
        if not order or float(counts.sum()) <= 0.0:
            # zero-weight round (e.g. only rejoin catch-up uploads after an
            # all-fail round): keep the model — the elastic no-op, matching
            # the mesh path's all-fail behavior (tests/test_failures.py)
            self.model_dict.clear()
            return self.variables
        old = self.variables
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *[self.model_dict[i] for i in order])
        self.variables = tree_weighted_mean(stacked, counts)
        from fedml_tpu.obs.lens import host_lens_stats, lens_enabled

        if lens_enabled():
            # the batch server still holds every member tree here, so the
            # full per-worker lens (norm + cosine vs the aggregate's raw
            # update) comes for free at round close
            self.lens_stats = dict(
                host_lens_stats(old, [self.model_dict[i] for i in order],
                                self.variables),
                workers=list(order))
        self.model_dict.clear()
        return self.variables

    def client_sampling(self, round_idx: int, client_num_in_total: int, client_num_per_round: int):
        return sample_clients(round_idx, client_num_in_total, client_num_per_round, seed=self.config.seed)

    def test_on_server_for_all_clients(self, round_idx: int) -> Optional[dict]:
        if self._eval is None:
            return None
        sums = self._eval(self.variables, self.dataset.test_x, self.dataset.test_y, self.dataset.test_mask)
        m = finalize_metrics(jax.tree.map(np.asarray, sums))
        m["round"] = round_idx
        self.test_history.append(m)
        return m


class StreamingFedAVGAggregator(FedAVGAggregator):
    """O(1)-memory server aggregation (core/streaming.StreamAccumulator):
    each accepted upload folds into ONE running weighted model sum the
    moment it arrives, instead of buffering every worker's tree in
    ``model_dict`` until the round closes — the memory bound a
    thousand-worker federation needs. ``model_dict`` keeps index->None
    markers so the deadline machinery's received-set logic (and the
    ``uploads`` count) is unchanged.

    Mode (``--stream_aggregate``): ``deterministic`` folds in worker-index
    order (out-of-order arrivals held until their predecessors land —
    empty in-order, bounded by the worker count worst-case), so the
    aggregate is independent of arrival timing, retransmit storms and
    chaos reordering; ``arrival`` folds immediately (strict O(1) held
    state) and matches batch within the fedseg tolerance. Stale uploads
    are dropped by the server manager BEFORE they reach this class, and a
    second same-round upload from one worker is dropped (first wins,
    counted) — nothing can fold twice."""

    def __init__(self, variables, worker_num: int, config, dataset=None,
                 bundle=None):
        super().__init__(variables, worker_num, config, dataset=dataset,
                         bundle=bundle)
        from fedml_tpu.core.streaming import StreamAccumulator

        mode = getattr(config, "stream_aggregate", "deterministic")
        self._stream_cls = lambda: StreamAccumulator(
            "arrival" if mode == "arrival" else "deterministic")
        self._stream = self._stream_cls()
        #: same-round duplicate uploads dropped (the batch path overwrote;
        #: a fold cannot be un-applied, so first wins — surfaced, never
        #: silently double-aggregated)
        self.duplicate_uploads = 0
        #: high-water mark of simultaneously held out-of-order uploads
        #: (deterministic mode) — the measured O(1) evidence
        self.stream_peak_held = 0
        #: fedlens fold-time accumulation (norm-only; module docstring)
        self._lens_acc: dict = {"workers": [], "update_norm": []}

    @property
    def stream_nbytes(self) -> int:
        return self._stream.nbytes

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        if index in self.model_dict:
            self.duplicate_uploads += 1
            return
        from fedml_tpu.obs.lens import host_lens_stats, lens_enabled

        if lens_enabled():
            # norm-only at fold time: the O(1) fold never buffers the
            # member trees an alignment basis needs (self.variables is
            # still the round's broadcast model until aggregate())
            st = host_lens_stats(self.variables, [model_params])
            acc = self._lens_acc
            acc["workers"].append(int(index))
            acc["update_norm"].append(float(st["update_norm"][0]))
        self._stream.add(index, model_params, float(sample_num))
        self.stream_peak_held = max(self.stream_peak_held,
                                    self._stream.peak_held)
        self.model_dict[index] = None
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True
        self.uploads_accepted += 1

    def aggregate(self):
        out = self._stream.finalize(self.variables)
        self._stream = self._stream_cls()
        self.model_dict.clear()
        if self._lens_acc["workers"]:
            import numpy as _np

            self.lens_stats = {
                "workers": self._lens_acc["workers"],
                "update_norm": _np.asarray(self._lens_acc["update_norm"]),
                "align": None}
            self._lens_acc = {"workers": [], "update_norm": []}
        if out is not None:
            self.variables = out
        # None = zero-weight round: the elastic no-op, like the batch path
        return self.variables


def make_aggregator(variables, worker_num: int, config, dataset=None,
                    bundle=None) -> FedAVGAggregator:
    """Batch or streaming server aggregation per ``config.stream_aggregate``
    — the one switch every edge launcher routes through."""
    cls = (StreamingFedAVGAggregator
           if getattr(config, "stream_aggregate", "off") != "off"
           else FedAVGAggregator)
    return cls(variables, worker_num, config, dataset=dataset, bundle=bundle)


class FedAvgEdgeServerManager(ServerManager):
    """Reference FedAvgServerManager.py:18-95 — plus fault-tolerant rounds
    the reference lacks (its only failure handling is MPI.COMM_WORLD.Abort,
    client_manager.py:66-69): with ``config.straggler_deadline_sec`` set,
    a round aggregates whichever uploads arrived by the deadline, missing
    workers are marked dead (their sends skipped so a dead peer can't stall
    the loop), their logical clients are re-dealt to survivors next round,
    and a worker that reconnects (JOIN message) re-enters the federation."""

    def __init__(self, args, comm, rank, size, aggregator: FedAVGAggregator):
        super().__init__(args, comm, rank, size)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.round_idx = 0
        # The image of the downlink the clients actually trained from this
        # round (decoded once at send time). Delta uploads reconstruct
        # against it; caching here keeps the sync path and the
        # reconstruction path one and the same code, and avoids O(workers)
        # redundant full-model re-encodes per round.
        self._downlink_image = None
        # fault tolerance (None = reference-strict: wait for all workers)
        self._deadline = getattr(aggregator.config, "straggler_deadline_sec", None)
        self._deadline_timer = None
        if self._deadline is not None:
            require_injectable(comm)
            self._deadline_timer = RoundDeadlineTimer(
                comm, self._deadline, rank, MSG_ARG_KEY_ROUND)
        self._alive = {w: True for w in range(size - 1)}
        # uploads dropped as stale (wrong round tag / pre-re-deal gen): a
        # RETRANSMITTED upload landing after its round was deadline-closed
        # counts here, never in the aggregate. A registry WIRE-lane counter
        # (not a plain attribute): pulse snapshots, the watchdog's
        # stale_spike delta rule and trace_report's registry section all
        # see it LIVE alongside the reliable layer's counters, instead of
        # a hand-stamped value at teardown.
        from fedml_tpu.obs import default_registry

        self._wire_lane = default_registry().group(
            "wire", rank=0, keys=("stale_uploads",))
        self._lost_clients: list[int] = []
        self._assignment_map: dict[int, list[int]] = {}
        self._expected: set[int] = set(range(size - 1))
        self._bcast_gen = 0
        # checkpoint/resume (reference: none at all, SURVEY.md §5.4; here
        # the long-running WAN federation — the case that most needs it —
        # persists global model + round + history every checkpoint_frequency
        # rounds and resumes bit-identically: sampling/RNG are stateless in
        # (seed, round), so the model+round+history ARE the whole server)
        cfg = aggregator.config
        self._ckpt_path = None
        if getattr(cfg, "checkpoint_dir", None):
            import os

            os.makedirs(cfg.checkpoint_dir, exist_ok=True)
            self._ckpt_path = os.path.join(cfg.checkpoint_dir, "edge_server.ckpt")
        self._ckpt_freq = int(getattr(cfg, "checkpoint_frequency", 10) or 10)
        resume = getattr(cfg, "resume_from", None)
        if resume:
            from fedml_tpu.utils.checkpoint import load_checkpoint

            state = load_checkpoint(resume)
            aggregator.variables = state["variables"]
            self.round_idx = int(state["round_idx"])
            aggregator.test_history.extend(state["extra"].get("test_history", []))
            LOG.info("resumed edge federation at round %d from %s",
                     self.round_idx, resume)
        # consecutive deadlines with zero uploads AND zero alive workers;
        # at _MAX_EMPTY_DEADLINES the federation tears down instead of
        # waiting forever for a rejoin that may never come
        self._empty_deadlines = 0
        # fedpulse round clock: broadcast -> aggregate wall, and the base
        # each accepted upload's arrival latency is measured against
        self._round_t0 = time.perf_counter()

    _MAX_EMPTY_DEADLINES = MAX_EMPTY_DEADLINES

    @property
    def stale_uploads(self) -> int:
        """The registry wire-lane counter (kept as an attribute-shaped read
        for the existing callers/tests)."""
        return self._wire_lane["stale_uploads"]

    def run(self):
        self.register_message_receive_handlers()
        if self.round_idx >= self.round_num:   # resumed a finished run
            self._teardown()
            return
        self.send_init_msg()
        self.com_manager.handle_receive_message()

    def _maybe_checkpoint(self):
        if self._ckpt_path is None:
            return
        if (self.round_idx % self._ckpt_freq == 0
                or self.round_idx >= self.round_num):
            from fedml_tpu.utils.checkpoint import save_checkpoint

            hist = [
                {k: (float(v) if hasattr(v, "item") else v) for k, v in h.items()}
                for h in self.aggregator.test_history
            ]
            save_checkpoint(self._ckpt_path,
                            self.aggregator.get_global_model_params(),
                            round_idx=self.round_idx,
                            extra={"test_history": hist})

    def _assignments(self, round_idx: int) -> dict[int, list[int]]:
        """Sample client_num_per_round logical clients and deal them to the
        alive workers round-robin — the reference's worker/logical-client
        re-binding (FedAvgClientManager.py:50-61) generalized to
        cohort != worker_num. Logical clients lost to a dead worker last
        round are dealt first, so no sampled client silently drops out."""
        cohort = min(self.args.client_num_per_round, self.args.client_num_in_total)
        sampled = [int(c) for c in self.aggregator.client_sampling(
            round_idx, self.args.client_num_in_total, cohort
        )]
        if self._lost_clients:
            sampled = [c for c in self._lost_clients if c not in sampled] + sampled
            self._lost_clients = []
        out: dict[int, list[int]] = {w: [] for w in range(self.size - 1)}
        targets = [w for w in out if self._alive[w]]
        if not targets:
            self._lost_clients = sampled   # nobody to run them; carry over
            return out
        for i, c in enumerate(sampled):
            out[targets[i % len(targets)]].append(c)
        return out

    # -- fault tolerance ---------------------------------------------------
    def _mark_dead(self, w: int) -> None:
        if self._alive.get(w, False):
            self._alive[w] = False
            lost = self._assignment_map.get(w, [])
            self._lost_clients.extend(c for c in lost if c not in self._lost_clients)
            LOG.warning("worker %d marked dead; re-dealing clients %s", w, lost)
        self._expected.discard(w)

    def _arm_timer(self) -> None:
        if self._deadline_timer is not None:
            self._deadline_timer.arm(self.round_idx)

    def _cancel_timer(self) -> None:
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()

    def handle_round_deadline(self, msg: Message) -> None:
        if self._deadline is None or int(msg.get(MSG_ARG_KEY_ROUND)) != self.round_idx:
            return   # stale timer from a round that completed in time
        received = set(self.aggregator.model_dict.keys())
        for w in sorted(self._expected - received):
            LOG.warning("round %d: worker %d missed the %.1fs deadline",
                        self.round_idx, w, self._deadline)
            self._mark_dead(w)
        if received:
            self._empty_deadlines = 0
            self._complete_round()
        elif any(self._alive.values()):
            # nobody reported but somebody is alive: re-deal and re-sync the
            # SAME round (model unchanged — an all-fail no-op, like the mesh
            # path's elastic all-fail round)
            self._empty_deadlines = 0
            self._assignment_map = self._assignments(self.round_idx)
            self._broadcast_model(MSG_TYPE_S2C_SYNC_MODEL,
                                  self.aggregator.get_global_model_params(),
                                  self._assignment_map)
        else:
            # every worker is dead: wait for a rejoin, bounded
            self._empty_deadlines += 1
            if self._empty_deadlines >= self._MAX_EMPTY_DEADLINES:
                LOG.error(
                    "round %d: all workers dead for %d consecutive deadlines; "
                    "tearing the federation down with %d/%d rounds done",
                    self.round_idx, self._empty_deadlines,
                    self.round_idx, self.round_num)
                self._teardown()
            else:
                self._arm_timer()

    def _downlink_codec(self):
        """topk is an UPLOAD (delta) compressor; sparsifying the full-weight
        downlink would destroy the model, so sync messages override it to
        raw. q8 downlinks are fine (and the delta reconstruction accounts
        for them)."""
        codec = getattr(self.aggregator.config, "wire_codec", "raw")
        return "raw" if codec.startswith("topk") else None

    def _broadcast_model(self, msg_type: int, global_params, assignments):
        """Send the model to every worker and cache the decoded image the
        workers will actually train from (delta uploads reconstruct against
        it — computing it once here keeps sync and reconstruction the same
        bytes by construction instead of re-encoding per upload)."""
        from fedml_tpu.obs import tracer_if_sampled

        # head sampling: broadcast and _complete_round derive the SAME
        # verdict for this round from the pure (seed, round) hash, so a
        # sampled round always closes the keyed span it opened
        tr = tracer_if_sampled(self.rank, self.round_idx)
        if tr is not None:
            # the server's round span opens at broadcast and closes in
            # _complete_round — a different handler invocation, so it is a
            # keyed cross-method span, not a context manager. An all-fail
            # re-broadcast of the same round re-opens the key: the span then
            # measures the LAST attempt, and the earlier one is dropped.
            tr.begin_span(("round", self.round_idx), "round", cat="round",
                          args={"round": self.round_idx, "role": "server"})
        # fedpulse round clock restarts at (re)broadcast — same last-attempt
        # semantics as the keyed span above
        self._round_t0 = time.perf_counter()
        override = self._downlink_codec()
        effective = override if override is not None else getattr(
            self.aggregator.config, "wire_codec", "raw")
        if effective != "raw":
            from fedml_tpu.core.compression import decode_tree, encode_tree

            self._downlink_image = decode_tree(encode_tree(global_params, effective))
        else:
            self._downlink_image = global_params
        self._expected = set()
        self._bcast_gen += 1
        msgs = []
        for w in sorted(assignments):
            if not self._alive[w]:
                continue
            m = Message(msg_type, self.rank, w + 1)
            m.codec = override
            m.add_params(MSG_ARG_KEY_MODEL_PARAMS, global_params)
            m.add_params(MSG_ARG_KEY_CLIENT_INDEX, assignments[w])
            m.add_params(MSG_ARG_KEY_ROUND, self.round_idx)
            m.add_params(MSG_ARG_KEY_GEN, self._bcast_gen)
            msgs.append((w, m))
        if self._deadline is not None and len(msgs) > 1:
            # Concurrent sends (advisor r4 #4): each gRPC send blocks up to
            # the straggler deadline on an unreachable-but-not-yet-dead
            # peer, so W stragglers would stall a sequential loop W*deadline
            # — overlapping them caps the broadcast at ~one deadline total.
            from concurrent.futures import ThreadPoolExecutor

            # one thread per send: each blocked send can hold its thread
            # for the full deadline, so any smaller pool re-serializes the
            # stall in waves (review r5 #2)
            with ThreadPoolExecutor(max_workers=len(msgs)) as ex:
                futs = [(w, ex.submit(self.send_message, m)) for w, m in msgs]
                results = [(w, f.exception()) for w, f in futs]
            for w, err in results:
                if err is None:
                    self._expected.add(w)
                else:
                    LOG.warning("send to worker %d failed (%s)", w, err)
                    self._mark_dead(w)
        else:
            for w, m in msgs:
                try:
                    self.send_message(m)
                except Exception as e:
                    if self._deadline is None:
                        raise
                    # dead peer: a blocked send must not stall the round
                    LOG.warning("send to worker %d failed (%s)", w, e)
                    self._mark_dead(w)
                    continue
                self._expected.add(w)
        self._arm_timer()

    def send_init_msg(self):
        # round_idx is 0 on a fresh start, R on a resume — the init message
        # carries the round tag, so workers pick up mid-federation cleanly
        self._assignment_map = self._assignments(self.round_idx)
        self._broadcast_model(MSG_TYPE_S2C_INIT_CONFIG,
                              self.aggregator.get_global_model_params(),
                              self._assignment_map)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL, self.handle_message_receive_model_from_client
        )
        self.register_message_receive_handler(
            MSG_TYPE_C2S_JOIN, self.handle_message_join
        )
        self.register_message_receive_handler(
            MSG_TYPE_LOCAL_ROUND_DEADLINE, self.handle_round_deadline
        )

    def handle_message_join(self, msg: Message) -> None:
        """A (re)connecting worker announces itself. Already-alive workers'
        JOINs (every worker sends one at startup in fault-tolerant mode) are
        ignored — replying would double-book them for the current round. A
        dead worker is revived and sent the current model with an empty
        assignment so it can catch up and take real work next round."""
        if self._deadline is None:
            return
        self._empty_deadlines = 0
        w = msg.get_sender_id() - 1
        if self._alive.get(w, False):
            return
        LOG.info("worker %d rejoined at round %d", w, self.round_idx)
        self._alive[w] = True
        m = Message(MSG_TYPE_S2C_SYNC_MODEL, self.rank, w + 1)
        m.codec = self._downlink_codec()
        m.add_params(MSG_ARG_KEY_MODEL_PARAMS,
                     self.aggregator.get_global_model_params())
        m.add_params(MSG_ARG_KEY_CLIENT_INDEX, [])
        m.add_params(MSG_ARG_KEY_ROUND, self.round_idx)
        # current generation, NOT a bump: the round's outstanding uploads
        # must stay valid
        m.add_params(MSG_ARG_KEY_GEN, self._bcast_gen)
        try:
            self.send_message(m)
        except Exception as e:
            LOG.warning("catch-up send to rejoined worker %d failed (%s)", w, e)
            self._alive[w] = False

    def _observe_stale(self, rounds_behind: int) -> None:
        """Feed one dropped contribution's rounds-behind to the pulse
        plane's staleness sketch (no-op while the plane is off)."""
        from fedml_tpu.obs import pulse_if_enabled

        pulse = pulse_if_enabled()
        if pulse is not None:
            pulse.observe_stale(rounds_behind)

    def handle_message_receive_model_from_client(self, msg: Message):
        sender = msg.get_sender_id()
        if self._deadline is not None:
            self._empty_deadlines = 0
            w = sender - 1
            if not self._alive.get(w, False):
                # an upload from a presumed-dead worker: it's back — count
                # it in from next round, but drop this (stale) payload
                LOG.info("worker %d rejoined via upload at round %d", w, self.round_idx)
                self._alive[w] = True
            tag = msg.get(MSG_ARG_KEY_ROUND)
            if tag is not None and int(tag) != self.round_idx:
                # late (possibly retransmitted) upload of a round that was
                # already deadline-closed: stale, never double-aggregated.
                # Its rounds-behind lag feeds the staleness sketch lane —
                # the same lane fedbuff's version lag writes.
                self._wire_lane["stale_uploads"] += 1
                self._observe_stale(self.round_idx - int(tag))
                return
            gen = msg.get(MSG_ARG_KEY_GEN)
            if gen is not None and int(gen) != self._bcast_gen:
                self._wire_lane["stale_uploads"] += 1
                # pre-re-deal upload of the CURRENT round: 0 rounds behind
                self._observe_stale(0)
                return
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        # what actually rode the wire: the sparse/small delta for delta
        # uploads, the full weights otherwise — the reconstructed tree
        # below would overstate a delta upload's bytes by the full-model
        # ratio in exactly the bandwidth-constrained deployments the
        # profiler's upload accounting is for
        wire_tree = (payload if payload is not None
                     else msg.get(MSG_ARG_KEY_MODEL_DELTA))
        if payload is None:
            # delta upload: reconstruct the worker model against the image
            # of the downlink the workers trained from this round, cached
            # at broadcast time (_broadcast_model). Under a lossy codec
            # that image carries the downlink compression error the client
            # saw — reconstructing against the raw globals instead would
            # bias every worker model by an error the client's
            # error-feedback residual never sees.
            from fedml_tpu.core.pytree import tree_add

            payload = jax.tree.map(
                np.asarray,
                tree_add(self._downlink_image, msg.get(MSG_ARG_KEY_MODEL_DELTA)))
        self.aggregator.add_local_trained_result(
            sender - 1, payload, msg.get(MSG_ARG_KEY_NUM_SAMPLES)
        )
        from fedml_tpu.obs import pulse_if_enabled

        pulse = pulse_if_enabled()
        if pulse is not None:
            # the broadcast->upload latency the server OBSERVED for this
            # worker (wire down + train + wire up — the signal the straggler
            # deadline acts on), attributed to its assigned logical clients;
            # bytes are the DECODED size of the tree the wire carried (delta
            # for delta uploads) — no re-serialization, so a lossy codec's
            # further ratio (q8/topk) is not modeled here
            pulse.observe_upload(
                self._assignment_map.get(sender - 1) or [],
                self.round_idx,
                train_ms=(time.perf_counter() - self._round_t0) * 1e3,
                upload_bytes=float(sum(
                    getattr(leaf, "nbytes", 8)
                    for leaf in jax.tree.leaves(wire_tree))))
        if self._deadline is not None:
            if not self._expected <= set(self.aggregator.model_dict.keys()):
                return
        elif not self.aggregator.check_whether_all_receive():
            return
        self._complete_round()

    def _complete_round(self):
        from fedml_tpu.obs import pulse_if_enabled, tracer_if_sampled

        self._cancel_timer()
        uploads = len(self.aggregator.model_dict)
        tr = tracer_if_sampled(self.rank, self.round_idx)
        if tr is None:
            global_params = self.aggregator.aggregate()
        else:
            with tr.span("aggregate", cat="round",
                         args={"round": self.round_idx,
                               "uploads": uploads}):
                global_params = self.aggregator.aggregate()
            tr.end_span(("round", self.round_idx))
        if self._deadline is not None:
            for i in self.aggregator.flag_client_model_uploaded_dict:
                self.aggregator.flag_client_model_uploaded_dict[i] = False
        metrics = None
        if (
            self.round_idx % self.args.frequency_of_the_test == 0
            or self.round_idx == self.round_num - 1
        ):
            metrics = self.aggregator.test_on_server_for_all_clients(self.round_idx)
        pulse = pulse_if_enabled()
        if pulse is not None:
            # fedlens drain: per-worker upload stats the aggregator computed
            # at round close, attributed to each worker's assigned logical
            # clients (the id space every lens consumer ranks in) — fed
            # BEFORE on_round so this round's snapshot folds them
            ls = getattr(self.aggregator, "lens_stats", None)
            self.aggregator.lens_stats = None
            if ls:
                al = ls.get("align")
                for j, w in enumerate(ls["workers"]):
                    ids = self._assignment_map.get(w) or []
                    if ids:
                        pulse.observe_lens(
                            ids, self.round_idx,
                            update_norm=float(ls["update_norm"][j]),
                            align=None if al is None else float(al[j]))
            # one pulse snapshot per completed round, from the server (the
            # only rank that sees the whole broadcast->aggregate path); its
            # stale-upload/liveness counters ride the wire lane so the
            # watchdog's spike rules see them. May raise (escalate mode) —
            # AFTER the snapshot is written, and the round is already
            # aggregated, so the stream records the dying state.
            # stale_uploads is NOT in extra: it rides the registry wire
            # lane live (the watchdog's stale_spike delta reads it there)
            try:
                pulse.on_round(
                    self.round_idx, source="edge_server",
                    loss=(float(metrics["loss"]) if metrics
                          and metrics.get("loss") is not None else None),
                    round_ms=(time.perf_counter() - self._round_t0) * 1e3,
                    extra={"uploads": uploads,
                           "workers_alive": sum(
                               1 for a in self._alive.values() if a)})
            except Exception:
                # fedflight cross-rank capture: the escalating plane just
                # dumped the server's incident bundle (dump-before-raise,
                # obs/live.py) — tell every worker to flush its own flight
                # ring to the same incident id BEFORE the error propagates
                # and tears the federation down
                broadcast_flight_dump(self, self.size)
                raise
        self.round_idx += 1
        self._maybe_checkpoint()
        if self.round_idx >= self.round_num:
            self._teardown()
            return
        self._assignment_map = self._assignments(self.round_idx)
        self._broadcast_model(MSG_TYPE_S2C_SYNC_MODEL, global_params,
                              self._assignment_map)

    def _teardown(self):
        """FINISH goes to EVERY worker, dead-marked ones included: a
        slow-but-alive worker that was dropped from the rounds must still
        tear down instead of blocking on its queue forever (a truly dead
        peer's send fails within the send timeout and is swallowed in
        fault-tolerant mode)."""
        self._cancel_timer()
        for rank in range(1, self.size):
            try:
                self.send_message(Message(MSG_TYPE_S2C_FINISH, self.rank, rank))
            except Exception as e:
                if self._deadline is None:
                    raise
                LOG.warning("FINISH to worker %d failed (%s)", rank - 1, e)
        self.finish()


class FedAVGTrainer:
    """Worker-side trainer wrapper (reference FedAVGTrainer.py:4-52): holds
    the jitted local-train fn and re-binds the logical client's data slice."""

    def __init__(self, dataset, bundle, config):
        self.dataset = dataset
        self.config = config
        from fedml_tpu.parallel.local import local_train_kwargs

        self.local_train = jax.jit(
            make_local_train_fn(
                bundle, get_task(dataset.task, dataset.class_num),
                **local_train_kwargs(config),
            )
        )
        self.client_indices: list[int] = []

    def update_dataset(self, client_indices) -> None:
        self.client_indices = [int(c) for c in client_indices]

    def train(self, variables, round_idx: int, root_key):
        """Train each assigned logical client from the same global weights and
        return the sample-weighted mean of the results + total count — the
        partial aggregate, so the server's weighted mean over workers equals
        the weighted mean over all sampled clients exactly."""
        if not self.client_indices:
            return jax.tree.map(np.asarray, variables), 0.0
        trees, counts = [], []
        for ci in self.client_indices:
            x, y, m, count = self.dataset.client_slice_cached(ci)
            rng = jax.random.fold_in(round_key(root_key, round_idx), ci)
            res = self.local_train(variables, x[0], y[0], m[0], np.float32(count[0]), rng)
            trees.append(res.variables)
            counts.append(float(count[0]))
        from fedml_tpu.core.pytree import tree_weighted_sum_list

        mean = jax.tree.map(np.asarray, tree_weighted_sum_list(trees, counts))
        return mean, float(sum(counts))


class FedAvgEdgeClientManager(ClientManager):
    """Reference FedAvgClientManager.py:18-75."""

    def __init__(self, args, comm, rank, size, trainer: FedAVGTrainer, root_key):
        super().__init__(args, comm, rank, size)
        self.trainer = trainer
        self.root_key = root_key
        self.round_idx = 0
        # error-feedback residual for delta uploads (per WORKER, like DGC:
        # the stream being compressed is this worker's upload sequence)
        self._residual = None
        self._residual_round = None
        # fault-tolerant mode: announce ourselves on startup so a restarted
        # worker process can re-enter a running federation
        self._ft = getattr(trainer.config, "straggler_deadline_sec", None) is not None
        self._bcast_gen = None
        # delta mode: the error-feedback residual is WORKER state the
        # protocol never ships — persist it beside the server checkpoint so
        # a resumed federation is bit-identical under a lossy codec
        cfg = trainer.config
        self._res_path = None
        if getattr(cfg, "checkpoint_dir", None) and getattr(cfg, "wire_delta", False):
            import os

            os.makedirs(cfg.checkpoint_dir, exist_ok=True)
            self._res_path = os.path.join(
                cfg.checkpoint_dir, f"edge_worker_{rank}.residual")
            if getattr(cfg, "resume_from", None) and os.path.exists(self._res_path):
                from fedml_tpu.core.serialization import tree_from_bytes

                with open(self._res_path, "rb") as f:
                    state = tree_from_bytes(f.read())
                self._residual = state["residual"]
                # the round this residual feeds into; if the server resumed
                # from an older checkpoint the tag won't match and the
                # residual is discarded at first sync (clean restart beats a
                # residual from the future)
                self._residual_round = int(np.asarray(state["round"]).item())
                LOG.info("rank %d resumed error-feedback residual for round %d",
                         rank, self._residual_round)

    def run(self):
        self.register_message_receive_handlers()
        if self._ft:
            # best-effort: a JOIN lost to startup ordering is harmless (the
            # server ignores JOINs from alive workers and its INIT broadcast
            # waits for our bind) — it must never kill the worker
            try:
                self.send_message(Message(MSG_TYPE_C2S_JOIN, self.rank, 0))
            except Exception as e:
                LOG.warning("startup JOIN failed (%s); waiting for init", e)
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self.handle_message_receive_model_from_server
        )
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH, self.handle_message_finish)
        self.register_message_receive_handler(MSG_TYPE_FLIGHT_DUMP, self.handle_message_flight_dump)

    def handle_message_flight_dump(self, msg: Message) -> None:
        """Server-broadcast incident capture: flush this rank's flight ring
        into the broadcast incident id's bundle (idempotent; no-op while
        the recorder is off)."""
        from fedml_tpu.obs import flight as _flight

        _flight.handle_dump_message(msg.get_params(), rank=self.rank)

    def handle_message_init(self, msg: Message):
        self.round_idx = 0
        self._train_and_send(msg)

    def handle_message_receive_model_from_server(self, msg: Message):
        self.round_idx += 1
        self._train_and_send(msg)

    def _train_and_send(self, msg: Message):
        # the server's round tag drives the RNG stream (identical to the
        # local counter in a healthy run; after a missed round / rejoin the
        # tag is the correct one)
        tag = msg.get(MSG_ARG_KEY_ROUND)
        if tag is not None:
            self.round_idx = int(tag)
        self._bcast_gen = msg.get(MSG_ARG_KEY_GEN)
        from fedml_tpu.obs import tracer_if_sampled

        # the worker derives the same (seed, round) head-sampling verdict
        # as the server: a sampled round's trace carries EVERY rank's spans
        tr = tracer_if_sampled(self.rank, self.round_idx)
        if tr is None:
            self._do_train_and_send(msg)
        else:
            with tr.span("round", cat="round",
                         args={"round": self.round_idx, "role": "worker"}):
                self._do_train_and_send(msg)

    def handle_message_finish(self, msg: Message):
        self.finish()

    def _do_train_and_send(self, msg: Message):
        self.trainer.update_dataset(msg.get(MSG_ARG_KEY_CLIENT_INDEX))
        variables = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        new_vars, n = self.trainer.train(variables, self.round_idx, self.root_key)
        out = Message(MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        out.add_params(MSG_ARG_KEY_ROUND, self.round_idx)
        if self._bcast_gen is not None:
            out.add_params(MSG_ARG_KEY_GEN, self._bcast_gen)
        cfg = self.trainer.config
        if getattr(cfg, "wire_delta", False) and n <= 0:
            # zero-weight upload (rejoin catch-up / empty assignment): the
            # server discards its mass, so folding the error-feedback
            # residual into it would destroy the residual's compensation —
            # keep the residual for the next REAL round and ship raw
            out.add_params(MSG_ARG_KEY_MODEL_PARAMS, new_vars)
        elif getattr(cfg, "wire_delta", False):
            from fedml_tpu.core.compression import decode_tree, encode_tree
            from fedml_tpu.core.pytree import tree_add, tree_sub

            d = tree_sub(new_vars, jax.tree.map(np.asarray, variables))
            if self._residual_round is not None:
                # discard only a FUTURE-tagged residual (server resumed from
                # an older checkpoint than the residual's round). A PAST tag
                # is normal: zero-weight uploads (rejoin catch-up / empty
                # assignment) deliberately hold the residual for the next
                # real round, so the tag may trail round_idx.
                if self._residual_round > self.round_idx:
                    LOG.warning(
                        "rank %d: resumed residual targets future round %d "
                        "but federation is at round %d; discarding it",
                        self.rank, self._residual_round, self.round_idx)
                    self._residual = None
                self._residual_round = None
            if self._residual is not None:
                d = tree_add(d, self._residual)
            # simulate the transport's (deterministic) codec so the residual
            # accounts for exactly what the server will receive; with a raw
            # codec the residual stays zero and the protocol is lossless
            codec = getattr(cfg, "wire_codec", "raw")
            if codec != "raw":
                received = decode_tree(encode_tree(d, codec))
                self._residual = tree_sub(d, received)
                if self._res_path is not None:
                    import os

                    from fedml_tpu.core.serialization import tree_to_bytes

                    tmp = self._res_path + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(tree_to_bytes({
                            "residual": self._residual,
                            "round": np.int64(self.round_idx + 1)}))
                    os.replace(tmp, self._res_path)
            out.add_params(MSG_ARG_KEY_MODEL_DELTA, d)
        else:
            out.add_params(MSG_ARG_KEY_MODEL_PARAMS, new_vars)
        out.add_params(MSG_ARG_KEY_NUM_SAMPLES, n)
        self.send_message(out)


def _edge_args(config, dataset):
    """The small mutable arg bag the managers read (reference passes the raw
    argparse namespace; here it is derived from FedConfig + dataset)."""

    class Args:
        pass

    args = Args()
    args.comm_round = config.comm_round
    args.client_num_in_total = min(config.client_num_in_total, dataset.num_clients)
    args.client_num_per_round = min(config.client_num_per_round, args.client_num_in_total)
    args.frequency_of_the_test = config.frequency_of_the_test
    return args


def build_edge_rank(dataset, config, rank: int, world_size: int, comm,
                    bundle=None, root_key=None, aggregator=None):
    """Build ONE rank's manager. Model init and the federation RNG derive
    deterministically from ``config.seed``, so separate OS processes each
    construct identical initial state — the reference's "every rank loads
    the full dataset / builds the full model" pattern
    (main_fedavg.py:323, FedAvgAPI.py:20-28) without any weight broadcast
    beyond the protocol's own init message.

    ``bundle``/``root_key``/``aggregator`` let the in-process launcher share
    one instance across rank threads; per-process callers omit them."""
    from fedml_tpu.core.rng import seed_everything

    if bundle is None:
        bundle = create_model(
            config.model, dataset.class_num,
            input_shape=dataset.train_x.shape[2:] or None,
        )
    if root_key is None:
        root_key = seed_everything(config.seed)
    args = _edge_args(config, dataset)
    if rank == 0:
        if aggregator is None:
            aggregator = make_aggregator(
                bundle.init(root_key), world_size - 1, config,
                dataset=dataset, bundle=bundle,
            )
        return FedAvgEdgeServerManager(args, comm, 0, world_size, aggregator)
    trainer = FedAVGTrainer(dataset, bundle, config)
    return FedAvgEdgeClientManager(args, comm, rank, world_size, trainer, root_key)


def run_fedavg_edge(dataset, config, worker_num: int, wire_roundtrip: bool = True,
                    comm_factory=None, timeout: float = 300.0):
    """In-process launch: 1 server + worker_num clients over the local
    transport (the reference's mpirun path, FedAvgAPI.py:20-28) or a real
    transport via ``comm_factory`` (e.g. gRPC loopback). Returns the
    server's aggregator (holding the final global model + test history)."""
    from fedml_tpu.core.rng import seed_everything
    from fedml_tpu.obs import configure_from

    configure_from(config)
    bundle = create_model(config.model, dataset.class_num, input_shape=dataset.train_x.shape[2:] or None)
    root_key = seed_everything(config.seed)
    size = worker_num + 1
    aggregator = make_aggregator(
        bundle.init(root_key), worker_num, config, dataset=dataset,
        bundle=bundle
    )

    def make(rank, comm):
        return build_edge_rank(dataset, config, rank, size, comm,
                               bundle=bundle, root_key=root_key,
                               aggregator=aggregator)

    from fedml_tpu.comm.reliable import wire_wrap_factory

    managers = run_ranks(make, size, wire_roundtrip=wire_roundtrip,
                         comm_factory=comm_factory, timeout=timeout,
                         codec=getattr(config, "wire_codec", "raw"),
                         wrap=wire_wrap_factory(config),
                         inbox_cap=int(getattr(config, "wire_inbox_cap", 0) or 0))
    from fedml_tpu.utils.metrics import merge_wire_stats

    aggregator.wire_stats = merge_wire_stats(
        [m.com_manager for m in managers])
    # the server's own wire-lane counters (stale_uploads) live in the
    # registry — pulse/watchdog/trace_report read them live; this only
    # folds the same group into the end-of-run summary view
    for k, v in managers[0]._wire_lane.items():
        key = f"wire/{k}"
        aggregator.wire_stats[key] = aggregator.wire_stats.get(key, 0) + v
    anomalies = ("wire/retransmits", "wire/retransmit_errors", "wire/gave_up",
                 "wire/dup_dropped", "wire/stale_uploads")
    if any(aggregator.wire_stats.get(k, 0) for k in anomalies) or any(
            k.startswith("chaos/") and v
            for k, v in aggregator.wire_stats.items()):
        LOG.info("wire stats: %s", aggregator.wire_stats)
    return aggregator


def run_fedavg_edge_rank(dataset, config):
    """Run THIS process as one rank of a multi-process gRPC federation.

    The deployable counterpart of the reference's per-process launch
    (``mpirun -np N python main_fedavg.py`` →
    run_fedavg_distributed_pytorch.sh:21-23, rank branch FedAvgAPI.py:20-28),
    with rank→IP resolved from ``config.grpc_ipconfig_path`` exactly like
    the reference's grpc_ipconfig.csv (grpc_comm_manager.py:59-60). Blocks
    until the federation finishes; returns the aggregator on rank 0 (final
    global model + test history), None on workers."""
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    if config.rank is None or config.world_size is None:
        raise ValueError("per-rank deployment needs config.rank and config.world_size")
    if config.backend.lower() not in ("grpc", "mesh"):
        raise ValueError(
            f"per-rank deployment runs over gRPC; got backend={config.backend!r}"
        )
    deadline = getattr(config, "straggler_deadline_sec", None)
    comm = GRPCCommManager(
        config.rank, config.world_size,
        ip_config_path=config.grpc_ipconfig_path,
        base_port=config.grpc_base_port,
        codec=getattr(config, "wire_codec", "raw"),
        # Server in fault-tolerant mode: a send that can't reach its peer
        # within the straggler deadline is as good as failed — fail it so
        # the round marks the worker dead instead of stalling. Workers keep
        # the generous default: their sends target the server, and start
        # order must not matter (docs/deploy.md).
        send_timeout=deadline if deadline is not None and config.rank == 0
        else 120.0,
    )
    from fedml_tpu.comm.reliable import wire_wrap_factory
    from fedml_tpu.obs import configure_from, flush_all, tracing_enabled

    configure_from(config)
    wrap = wire_wrap_factory(config)
    if wrap is not None:
        comm = wrap(config.rank, comm)
    manager = build_edge_rank(dataset, config, config.rank, config.world_size, comm)
    LOG.info("rank %d/%d entering run loop (grpc base port %d)",
             config.rank, config.world_size, config.grpc_base_port)
    try:
        manager.run()
    finally:
        # per-rank deployment: THIS process owns only its own rank's trace
        if tracing_enabled():
            flush_all()
    from fedml_tpu.utils.metrics import wire_stats

    stats = wire_stats(comm)
    if stats:
        # per-rank deployment: each process only sees its OWN comm stack, so
        # every rank reports its counters — uplink loss shows up in worker
        # logs, not in the server's (rank-0-only) wire_stats
        LOG.info("rank %d wire stats: %s", config.rank, stats)
    if config.rank != 0:
        return None
    manager.aggregator.wire_stats = stats
    # registry wire-lane counters (stale_uploads): live during the run,
    # folded into the summary view here
    for k, v in manager._wire_lane.items():
        key = f"wire/{k}"
        stats[key] = stats.get(key, 0) + v
    return manager.aggregator
