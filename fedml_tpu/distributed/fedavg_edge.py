"""Message-driven FedAvg for edge/off-pod federation (reference distributed/fedavg).

Reference: fedml_api/distributed/fedavg/ — FedAvgServerManager.py:18-95,
FedAvgClientManager.py:18-75, FedAVGAggregator.py:13-163, message_define.py:
1-30. One process per participant, star topology, model weights in messages.

The TPU framework uses this paradigm ONLY at the true network edge (silos
behind gRPC, mobile clients); in-datacenter runs use the mesh-collective
path (parallel/crosssilo.py) which needs no messages at all. Per-worker
compute is the same jitted local-train scan used everywhere else — a worker
simulates `client_num_in_total / workers` logical clients by dataset
re-binding, exactly like the reference's client-sampling concurrency model
(FedAvgClientManager.handle_message_receive_model_from_server:50-61).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np

from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
)
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.rng import round_key, sample_clients
from fedml_tpu.core.tasks import get_task
from fedml_tpu.models import create_model
from fedml_tpu.parallel.local import finalize_metrics, make_eval_fn, make_local_train_fn

LOG = logging.getLogger(__name__)

# message_define.py:1-30
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_MODEL = 2
MSG_TYPE_C2S_SEND_MODEL = 3
MSG_TYPE_S2C_FINISH = 4

# Extension beyond the reference protocol: with config.wire_delta the client
# uploads (local mean - global) + error-feedback residual under this key
# instead of full weights, so a lossy wire codec (q8 / topk) compresses a
# small-magnitude tensor and the un-sent mass re-enters next round.
MSG_ARG_KEY_MODEL_DELTA = "model_delta"


class FedAVGAggregator:
    """Server-side state: collect worker results, weighted-average, sample.

    Reference FedAVGAggregator.py:13-163. add_local_trained_result /
    check_whether_all_receive / aggregate keep their names; aggregation math
    is the shared tree_weighted_mean primitive.
    """

    def __init__(self, variables, worker_num: int, config, dataset=None, bundle=None):
        self.variables = variables
        self.worker_num = worker_num
        self.config = config
        self.dataset = dataset
        self.model_dict: dict[int, dict] = {}
        self.sample_num_dict: dict[int, float] = {}
        self.flag_client_model_uploaded_dict = {i: False for i in range(worker_num)}
        self.test_history: list[dict] = []
        self._eval = make_eval_fn(bundle, get_task(dataset.task, dataset.class_num)) if bundle is not None and dataset is not None else None

    def get_global_model_params(self):
        return self.variables

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True

    def check_whether_all_receive(self) -> bool:
        if not all(self.flag_client_model_uploaded_dict.values()):
            return False
        for i in self.flag_client_model_uploaded_dict:
            self.flag_client_model_uploaded_dict[i] = False
        return True

    def aggregate(self):
        order = sorted(self.model_dict)
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *[self.model_dict[i] for i in order])
        counts = np.asarray([self.sample_num_dict[i] for i in order], np.float32)
        self.variables = tree_weighted_mean(stacked, counts)
        self.model_dict.clear()
        return self.variables

    def client_sampling(self, round_idx: int, client_num_in_total: int, client_num_per_round: int):
        return sample_clients(round_idx, client_num_in_total, client_num_per_round, seed=self.config.seed)

    def test_on_server_for_all_clients(self, round_idx: int) -> Optional[dict]:
        if self._eval is None:
            return None
        sums = self._eval(self.variables, self.dataset.test_x, self.dataset.test_y, self.dataset.test_mask)
        m = finalize_metrics(jax.tree.map(np.asarray, sums))
        m["round"] = round_idx
        self.test_history.append(m)
        return m


class FedAvgEdgeServerManager(ServerManager):
    """Reference FedAvgServerManager.py:18-95."""

    def __init__(self, args, comm, rank, size, aggregator: FedAVGAggregator):
        super().__init__(args, comm, rank, size)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.round_idx = 0

    def run(self):
        self.register_message_receive_handlers()
        self.send_init_msg()
        self.com_manager.handle_receive_message()

    def _assignments(self, round_idx: int) -> list[list[int]]:
        """Sample client_num_per_round logical clients and deal them to the
        size-1 workers round-robin — the reference's worker/logical-client
        re-binding (FedAvgClientManager.py:50-61) generalized to
        cohort != worker_num."""
        cohort = min(self.args.client_num_per_round, self.args.client_num_in_total)
        sampled = self.aggregator.client_sampling(
            round_idx, self.args.client_num_in_total, cohort
        )
        workers = self.size - 1
        return [[int(c) for c in sampled[w::workers]] for w in range(workers)]

    def _downlink_codec(self):
        """topk is an UPLOAD (delta) compressor; sparsifying the full-weight
        downlink would destroy the model, so sync messages override it to
        raw. q8 downlinks are fine (and the delta reconstruction accounts
        for them)."""
        codec = getattr(self.aggregator.config, "wire_codec", "raw")
        return "raw" if codec.startswith("topk") else None

    def send_init_msg(self):
        assignments = self._assignments(0)
        global_params = self.aggregator.get_global_model_params()
        for rank in range(1, self.size):
            m = Message(MSG_TYPE_S2C_INIT_CONFIG, self.rank, rank)
            m.codec = self._downlink_codec()
            m.add_params(MSG_ARG_KEY_MODEL_PARAMS, global_params)
            m.add_params(MSG_ARG_KEY_CLIENT_INDEX, assignments[rank - 1])
            self.send_message(m)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL, self.handle_message_receive_model_from_client
        )

    def handle_message_receive_model_from_client(self, msg: Message):
        sender = msg.get_sender_id()
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        if payload is None:
            # delta upload: reconstruct the worker model against the global
            # weights this round was trained from (aggregate() has not run
            # yet, so aggregator.variables still holds them). Under a lossy
            # codec the client trained from the DECODED downlink, so
            # reconstruct against that same lossy image — otherwise every
            # worker model would be off by the downlink compression error,
            # a bias the client's error-feedback residual never sees.
            from fedml_tpu.core.compression import decode_tree, encode_tree
            from fedml_tpu.core.pytree import tree_add

            base = self.aggregator.get_global_model_params()
            # mirror the DOWNLINK codec (sync messages override topk to raw,
            # see _downlink_codec — so under topk the client trained from the
            # exact global weights)
            codec = getattr(self.aggregator.config, "wire_codec", "raw")
            if codec != "raw" and not codec.startswith("topk"):
                base = decode_tree(encode_tree(base, codec))
            payload = jax.tree.map(
                np.asarray,
                tree_add(base, msg.get(MSG_ARG_KEY_MODEL_DELTA)))
        self.aggregator.add_local_trained_result(
            sender - 1, payload, msg.get(MSG_ARG_KEY_NUM_SAMPLES)
        )
        if not self.aggregator.check_whether_all_receive():
            return
        global_params = self.aggregator.aggregate()
        if (
            self.round_idx % self.args.frequency_of_the_test == 0
            or self.round_idx == self.round_num - 1
        ):
            self.aggregator.test_on_server_for_all_clients(self.round_idx)
        self.round_idx += 1
        if self.round_idx >= self.round_num:
            for rank in range(1, self.size):
                self.send_message(Message(MSG_TYPE_S2C_FINISH, self.rank, rank))
            self.finish()
            return
        assignments = self._assignments(self.round_idx)
        for rank in range(1, self.size):
            m = Message(MSG_TYPE_S2C_SYNC_MODEL, self.rank, rank)
            m.codec = self._downlink_codec()
            m.add_params(MSG_ARG_KEY_MODEL_PARAMS, global_params)
            m.add_params(MSG_ARG_KEY_CLIENT_INDEX, assignments[rank - 1])
            self.send_message(m)


class FedAVGTrainer:
    """Worker-side trainer wrapper (reference FedAVGTrainer.py:4-52): holds
    the jitted local-train fn and re-binds the logical client's data slice."""

    def __init__(self, dataset, bundle, config):
        self.dataset = dataset
        self.config = config
        from fedml_tpu.parallel.local import local_train_kwargs

        self.local_train = jax.jit(
            make_local_train_fn(
                bundle, get_task(dataset.task, dataset.class_num),
                **local_train_kwargs(config),
            )
        )
        self.client_indices: list[int] = []

    def update_dataset(self, client_indices) -> None:
        self.client_indices = [int(c) for c in client_indices]

    def train(self, variables, round_idx: int, root_key):
        """Train each assigned logical client from the same global weights and
        return the sample-weighted mean of the results + total count — the
        partial aggregate, so the server's weighted mean over workers equals
        the weighted mean over all sampled clients exactly."""
        if not self.client_indices:
            return jax.tree.map(np.asarray, variables), 0.0
        trees, counts = [], []
        for ci in self.client_indices:
            x, y, m, count = self.dataset.client_slice(np.asarray([ci]))
            rng = jax.random.fold_in(round_key(root_key, round_idx), ci)
            res = self.local_train(variables, x[0], y[0], m[0], np.float32(count[0]), rng)
            trees.append(res.variables)
            counts.append(float(count[0]))
        from fedml_tpu.core.pytree import tree_weighted_sum_list

        mean = jax.tree.map(np.asarray, tree_weighted_sum_list(trees, counts))
        return mean, float(sum(counts))


class FedAvgEdgeClientManager(ClientManager):
    """Reference FedAvgClientManager.py:18-75."""

    def __init__(self, args, comm, rank, size, trainer: FedAVGTrainer, root_key):
        super().__init__(args, comm, rank, size)
        self.trainer = trainer
        self.root_key = root_key
        self.round_idx = 0
        # error-feedback residual for delta uploads (per WORKER, like DGC:
        # the stream being compressed is this worker's upload sequence)
        self._residual = None

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self.handle_message_receive_model_from_server
        )
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def handle_message_init(self, msg: Message):
        self.round_idx = 0
        self._train_and_send(msg)

    def handle_message_receive_model_from_server(self, msg: Message):
        self.round_idx += 1
        self._train_and_send(msg)

    def handle_message_finish(self, msg: Message):
        self.finish()

    def _train_and_send(self, msg: Message):
        self.trainer.update_dataset(msg.get(MSG_ARG_KEY_CLIENT_INDEX))
        variables = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        new_vars, n = self.trainer.train(variables, self.round_idx, self.root_key)
        out = Message(MSG_TYPE_C2S_SEND_MODEL, self.rank, 0)
        cfg = self.trainer.config
        if getattr(cfg, "wire_delta", False):
            from fedml_tpu.core.compression import decode_tree, encode_tree
            from fedml_tpu.core.pytree import tree_add, tree_sub

            d = tree_sub(new_vars, jax.tree.map(np.asarray, variables))
            if self._residual is not None:
                d = tree_add(d, self._residual)
            # simulate the transport's (deterministic) codec so the residual
            # accounts for exactly what the server will receive; with a raw
            # codec the residual stays zero and the protocol is lossless
            codec = getattr(cfg, "wire_codec", "raw")
            if codec != "raw":
                received = decode_tree(encode_tree(d, codec))
                self._residual = tree_sub(d, received)
            out.add_params(MSG_ARG_KEY_MODEL_DELTA, d)
        else:
            out.add_params(MSG_ARG_KEY_MODEL_PARAMS, new_vars)
        out.add_params(MSG_ARG_KEY_NUM_SAMPLES, n)
        self.send_message(out)


def run_fedavg_edge(dataset, config, worker_num: int, wire_roundtrip: bool = True,
                    comm_factory=None):
    """In-process launch: 1 server + worker_num clients over the local
    transport (the reference's mpirun path, FedAvgAPI.py:20-28) or a real
    transport via ``comm_factory`` (e.g. gRPC loopback). Returns the
    server's aggregator (holding the final global model + test history)."""
    from fedml_tpu.core.rng import seed_everything

    bundle = create_model(config.model, dataset.class_num, input_shape=dataset.train_x.shape[2:] or None)
    root_key = seed_everything(config.seed)
    variables0 = bundle.init(root_key)
    size = worker_num + 1

    class Args:
        pass

    args = Args()
    args.comm_round = config.comm_round
    args.client_num_in_total = min(config.client_num_in_total, dataset.num_clients)
    args.client_num_per_round = min(config.client_num_per_round, args.client_num_in_total)
    args.frequency_of_the_test = config.frequency_of_the_test

    aggregator = FedAVGAggregator(variables0, worker_num, config, dataset=dataset, bundle=bundle)

    def make(rank, comm):
        if rank == 0:
            return FedAvgEdgeServerManager(args, comm, rank, size, aggregator)
        trainer = FedAVGTrainer(dataset, bundle, config)
        return FedAvgEdgeClientManager(args, comm, rank, size, trainer, root_key)

    run_ranks(make, size, wire_roundtrip=wire_roundtrip,
              comm_factory=comm_factory,
              codec=getattr(config, "wire_codec", "raw"))
    return aggregator
