"""Message-driven FedGKT for genuinely remote (weak edge) clients.

Reference: fedml_api/distributed/fedgkt/ — the algorithm's actual use case
is edge devices that can only train the small client net: each client sends
extracted train/test feature maps + soft logits to the server
(message_def.py MSG_ARG_KEY_FEATURE/LOGITS/LABELS/FEATURE_TEST/LABELS_TEST),
the server trains the big net on the union and returns per-client global
logits (MSG_ARG_KEY_GLOBAL_LOGITS) for the next round's distillation
(GKTClientMananger / GKTServerMananger message loop).

TPU twist: the compute stays the SAME jitted programs the simulation uses —
the client runs FedGKTAPI's per-client ``train_one`` (distillation scan +
extraction pass) standalone instead of under the cohort ``vmap``, and the
server stacks the received features in rank order and runs the identical
``server_phase`` program — so the wire form matches ``FedGKTAPI`` up to the
vmap-vs-single-client numerics (see tests). Transport is pluggable: the
in-process router or gRPC loopback via ``comm_factory``.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.core.rng import round_key
from fedml_tpu.core.tasks import int_cross_entropy

log = logging.getLogger(__name__)

# reference message_def.py:1-24
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_TO_CLIENT = 2
MSG_TYPE_C2S_SEND_FEATURE_AND_LOGITS = 3
MSG_TYPE_S2C_FINISH = 4

KEY_FEATURE = "feature"
KEY_LOGITS = "logits"
KEY_LABELS = "labels"
KEY_MASK = "mask"
KEY_COUNT = "count"
KEY_FEATURE_TEST = "feature_test"
KEY_LABELS_TEST = "labels_test"
KEY_MASK_TEST = "mask_test"
KEY_GLOBAL_LOGITS = "global_logits"
KEY_ROUND = "round"


class GKTEdgeServerManager(ServerManager):
    """Collects per-client features/logits, trains the server net on the
    union, returns fresh global logits (reference GKTServerMananger)."""

    def __init__(self, args, comm, rank, size, api):
        super().__init__(args, comm, rank, size)
        self.api = api                      # FedGKTAPI: programs + state host
        self.C = size - 1
        self.round_idx = 0
        self.round_num = int(args.comm_round)
        self._feat = {}
        self._test = {}
        self.history: list[dict] = []
        pair = api.pair

        @jax.jit
        def evaluate_feats(svars, tfeats, ty, tm):
            # the server half of FedGKTAPI._eval_fn — the client half (feature
            # extraction) already ran on the clients
            logits = jax.vmap(lambda f: pair.server.apply_eval(svars, f))(tfeats)
            pred = jnp.argmax(logits, axis=-1)
            m = tm.astype(jnp.float32)
            per = int_cross_entropy(logits, ty)
            return {
                "correct": jnp.sum((pred == ty).astype(jnp.float32) * m),
                "loss_sum": jnp.sum(per * m),
                "count": jnp.sum(m),
            }

        self._evaluate_feats = evaluate_feats

    def run(self):
        self.register_message_receive_handlers()
        self._send_logits(MSG_TYPE_S2C_INIT_CONFIG)
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_FEATURE_AND_LOGITS, self._on_features)

    def _send_logits(self, msg_type: int):
        slogits = np.asarray(self.api.server_logits)
        for rank in range(1, self.size):
            m = Message(msg_type, self.rank, rank)
            m.add_params(KEY_GLOBAL_LOGITS, slogits[rank - 1])
            m.add_params(KEY_ROUND, self.round_idx)
            self.send_message(m)

    def _on_features(self, msg: Message):
        if int(msg.get(KEY_ROUND)) != self.round_idx:
            raise RuntimeError(
                f"GKT features for round {msg.get(KEY_ROUND)} arrived at "
                f"server in round {self.round_idx}")
        k = msg.get_sender_id() - 1
        self._feat[k] = tuple(np.asarray(msg.get(key)) for key in
                              (KEY_FEATURE, KEY_LOGITS, KEY_LABELS, KEY_MASK))
        self._test[k] = tuple(np.asarray(msg.get(key)) for key in
                              (KEY_FEATURE_TEST, KEY_LABELS_TEST,
                               KEY_MASK_TEST))
        if len(self._feat) < self.C:
            return
        api = self.api
        order = sorted(self._feat)
        feats, clogits, ys, masks = (
            np.stack([self._feat[i][j] for i in order]) for j in range(4))
        rkey = round_key(api.root_key, self.round_idx)
        (api.server_vars, api.server_opt, api.server_logits, sloss) = (
            api._server_phase(
                api.server_vars, api.server_opt, jnp.asarray(feats),
                jnp.asarray(ys), jnp.asarray(masks), jnp.asarray(clogits),
                jax.random.fold_in(rkey, 2),
            )
        )
        cfg = api.config
        if (self.round_idx % cfg.frequency_of_the_test == 0
                or self.round_idx == self.round_num - 1):
            tfeats, tys, tms = (
                jnp.asarray(np.stack([self._test[i][j] for i in order]))
                for j in range(3))
            sums = jax.device_get(
                self._evaluate_feats(api.server_vars, tfeats, tys, tms))
            acc = float(sums["correct"]) / max(float(sums["count"]), 1.0)
            self.history.append({
                "round": self.round_idx, "Test/Acc": acc,
                "Test/Loss": float(sums["loss_sum"]) / max(float(sums["count"]), 1.0),
                "Train/ServerLoss": float(sloss),
            })
            log.info("GKT-edge round %d: test acc %.4f", self.round_idx, acc)
        self._feat.clear()
        self._test.clear()
        self.round_idx += 1
        if self.round_idx >= self.round_num:
            for rank in range(1, self.size):
                self.send_message(Message(MSG_TYPE_S2C_FINISH, self.rank, rank))
            self.finish()
        else:
            self._send_logits(MSG_TYPE_S2C_SYNC_TO_CLIENT)


class GKTEdgeClientManager(ClientManager):
    """Trains the small edge net with distillation, extracts and uploads
    features/logits (reference GKTClientMananger)."""

    def __init__(self, args, comm, rank, size, *, train_one, extract_test,
                 root_key, cvars, copt, x, y, mask, count, test_x, test_y,
                 test_mask, alpha_distill):
        super().__init__(args, comm, rank, size)
        # train_one/extract arrive ALREADY jitted and shared across the C
        # managers (jitted functions are thread-safe): one compile serves
        # every client instead of C identical compiles
        self._train_one = train_one
        self._extract_test = extract_test
        self.root_key = root_key
        self.cvars, self.copt = cvars, copt
        self.x, self.y, self.mask, self.count = x, y, mask, count
        self.test_x, self.test_y, self.test_mask = test_x, test_y, test_mask
        self.alpha_distill = alpha_distill
        self.C = size - 1

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_SYNC_TO_CLIENT, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH,
                                              lambda m: self.finish())

    def _on_sync(self, msg: Message):
        rnd = int(msg.get(KEY_ROUND))
        slogits = jnp.asarray(np.asarray(msg.get(KEY_GLOBAL_LOGITS)))
        # same derivations as the simulation's client phase: kl_w gates the
        # distillation term off in round 0, and client k consumes key
        # split(fold_in(round_key, 1), C)[k]
        kl_w = jnp.float32(0.0 if rnd == 0 else self.alpha_distill)
        rkey = round_key(self.root_key, rnd)
        key = jax.random.split(jax.random.fold_in(rkey, 1), self.C)[self.rank - 1]
        (self.cvars, self.copt, feats, logits, _loss) = self._train_one(
            self.cvars, self.copt, self.x, self.y, self.mask, self.count,
            slogits, kl_w, key)
        tfeats = self._extract_test(self.cvars, self.test_x)
        out = Message(MSG_TYPE_C2S_SEND_FEATURE_AND_LOGITS, self.rank, 0)
        out.add_params(KEY_FEATURE, np.asarray(feats))
        out.add_params(KEY_LOGITS, np.asarray(logits))
        out.add_params(KEY_LABELS, np.asarray(self.y))
        out.add_params(KEY_MASK, np.asarray(self.mask))
        out.add_params(KEY_FEATURE_TEST, np.asarray(tfeats))
        out.add_params(KEY_LABELS_TEST, np.asarray(self.test_y))
        out.add_params(KEY_MASK_TEST, np.asarray(self.test_mask))
        out.add_params(KEY_ROUND, rnd)
        self.send_message(out)


def run_fedgkt_edge(dataset, config, pair=None, client_blocks: int = 3,
                    server_blocks_per_stage: int = 9,
                    wire_roundtrip: bool = True, comm_factory=None):
    """Launch server + one manager per client over the local transport (or
    gRPC loopback via ``comm_factory``) and run the full feature/logit
    federation. Returns the server manager (history + trained server net via
    ``.api``). Reuses a FedGKTAPI instance as the program/state host so the
    wire run shares init and jitted compute with the simulation."""
    from fedml_tpu.distributed.base_framework import warn_strict_barrier

    warn_strict_barrier(config, __name__)
    from fedml_tpu.algorithms.fedgkt import FedGKTAPI

    codec = getattr(config, "wire_codec", "raw")
    if codec.startswith("topk"):
        # topk is a DELTA compressor (error feedback absorbs the unsent
        # mass, fedavg_edge only). GKT payloads are full per-sample
        # features/logits with no residual stream — sparsifying them is
        # silent corruption, so refuse rather than degrade.
        raise ValueError(
            "wire_codec='topk:..' is only valid for delta uploads "
            "(fedavg_edge with wire_delta); fedgkt_edge exchanges full "
            "feature/logit payloads — use 'q8' or 'raw'"
        )
    api = FedGKTAPI(dataset, config, pair=pair, client_blocks=client_blocks,
                    server_blocks_per_stage=server_blocks_per_stage)
    train_one = jax.jit(api._build_client_train_one())
    extract_test = jax.jit(
        lambda cv, tx: api.pair.client.apply_eval(cv, tx)[1])
    tx_, ty_, tm_ = api._test_shards
    size = api.C + 1

    class Args:
        pass

    args = Args()
    args.comm_round = config.comm_round

    def make(rank, comm):
        if rank == 0:
            return GKTEdgeServerManager(args, comm, rank, size, api)
        k = rank - 1
        return GKTEdgeClientManager(
            args, comm, rank, size,
            train_one=train_one, extract_test=extract_test,
            root_key=api.root_key,
            cvars=jax.tree.map(lambda v: v[k], api.client_vars),
            copt=jax.tree.map(lambda v: v[k], api.client_opt),
            x=jnp.asarray(dataset.train_x[k]), y=jnp.asarray(dataset.train_y[k]),
            mask=jnp.asarray(dataset.train_mask[k]),
            count=jnp.asarray(dataset.train_counts[k], jnp.float32),
            test_x=jnp.asarray(tx_[k]), test_y=np.asarray(ty_[k]),
            test_mask=np.asarray(tm_[k]),
            alpha_distill=config.alpha_distill,
        )

    # GKT's payloads are the framework's biggest (per-sample feature maps +
    # logits both ways); the wire codec compresses them — q8 suits the
    # distillation exchange, whose targets are soft logits anyway. Labels/
    # masks and any integer arrays ride raw inside lossy frames.
    managers = run_ranks(make, size, wire_roundtrip=wire_roundtrip,
                         comm_factory=comm_factory,
                         codec=getattr(config, "wire_codec", "raw"))
    return managers[0]
