"""Message-driven FedGKT for genuinely remote (weak edge) clients.

Reference: fedml_api/distributed/fedgkt/ — the algorithm's actual use case
is edge devices that can only train the small client net: each client sends
extracted train/test feature maps + soft logits to the server
(message_def.py MSG_ARG_KEY_FEATURE/LOGITS/LABELS/FEATURE_TEST/LABELS_TEST),
the server trains the big net on the union and returns per-client global
logits (MSG_ARG_KEY_GLOBAL_LOGITS) for the next round's distillation
(GKTClientMananger / GKTServerMananger message loop).

TPU twist: the compute stays the SAME jitted programs the simulation uses —
the client runs FedGKTAPI's per-client ``train_one`` (distillation scan +
extraction pass) standalone instead of under the cohort ``vmap``, and the
server stacks the received features in rank order and runs the identical
``server_phase`` program — so the wire form matches ``FedGKTAPI`` up to the
vmap-vs-single-client numerics (see tests). Transport is pluggable: the
in-process router or gRPC loopback via ``comm_factory``.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.comm import ClientManager, Message, ServerManager
from fedml_tpu.comm.local import run_ranks
from fedml_tpu.core.rng import round_key
from fedml_tpu.core.tasks import int_cross_entropy

log = logging.getLogger(__name__)

# reference message_def.py:1-24
MSG_TYPE_S2C_INIT_CONFIG = 1
MSG_TYPE_S2C_SYNC_TO_CLIENT = 2
MSG_TYPE_C2S_SEND_FEATURE_AND_LOGITS = 3
MSG_TYPE_S2C_FINISH = 4
# straggler-deadline machinery shared with fedavg_edge
from fedml_tpu.distributed.base_framework import (  # noqa: E402
    MAX_EMPTY_DEADLINES,
    MSG_TYPE_LOCAL_ROUND_DEADLINE,
    RoundDeadlineTimer,
    require_injectable,
)

KEY_FEATURE = "feature"
KEY_LOGITS = "logits"
KEY_LABELS = "labels"
KEY_MASK = "mask"
KEY_COUNT = "count"
KEY_FEATURE_TEST = "feature_test"
KEY_LABELS_TEST = "labels_test"
KEY_MASK_TEST = "mask_test"
KEY_GLOBAL_LOGITS = "global_logits"
KEY_ROUND = "round"


class GKTEdgeServerManager(ServerManager):
    """Collects per-client features/logits, trains the server net on the
    union, returns fresh global logits (reference GKTServerMananger)."""

    def __init__(self, args, comm, rank, size, api):
        super().__init__(args, comm, rank, size)
        self.api = api                      # FedGKTAPI: programs + state host
        self.C = size - 1
        self.round_idx = 0
        self.round_num = int(args.comm_round)
        self._feat = {}
        self._test = {}
        self.history: list[dict] = []
        # Fault tolerance (config.straggler_deadline_sec; None = strict
        # barrier). GKT drops a straggler cleanly because ALL of its
        # per-client state lives server-side: a missing client's slot is
        # filled with its LAST-RECEIVED features under a ZERO mask (no
        # training contribution this round) and its server logits are
        # carried over, so the server phase shape stays static and a
        # rejoining client picks up meaningful logits.
        cfg = api.config
        self._deadline = getattr(cfg, "straggler_deadline_sec", None)
        self._deadline_timer = None
        if self._deadline is not None:
            require_injectable(comm)
            self._deadline_timer = RoundDeadlineTimer(
                comm, self._deadline, rank, KEY_ROUND)
        self._alive = {k: True for k in range(self.C)}
        self._last_feat: dict[int, tuple] = {}
        self._last_test: dict[int, tuple] = {}
        self._empty_deadlines = 0
        # checkpoint/resume (mirrors fedavg_edge): server-side GKT state is
        # server_vars/opt/logits + round + history; client state persists
        # per client next to it (run_fedgkt_edge plumbs the paths)
        self._ckpt_path = None
        if getattr(cfg, "checkpoint_dir", None):
            import os

            os.makedirs(cfg.checkpoint_dir, exist_ok=True)
            self._ckpt_path = os.path.join(cfg.checkpoint_dir, "gkt_server.ckpt")
        self._ckpt_freq = int(getattr(cfg, "checkpoint_frequency", 10) or 10)
        resume = getattr(cfg, "resume_from", None)
        if resume:
            from fedml_tpu.utils.checkpoint import load_checkpoint

            state = load_checkpoint(resume)
            t = state["variables"]
            api.server_vars = t["server_vars"]
            api.server_opt = t["server_opt"]
            api.server_logits = jnp.asarray(t["server_logits"])
            self.round_idx = int(state["round_idx"])
            self.history.extend(state["extra"].get("history", []))
            log.info("resumed GKT federation at round %d from %s",
                     self.round_idx, resume)
        pair = api.pair

        @jax.jit
        def evaluate_feats(svars, tfeats, ty, tm):
            # the server half of FedGKTAPI._eval_fn — the client half (feature
            # extraction) already ran on the clients
            logits = jax.vmap(lambda f: pair.server.apply_eval(svars, f))(tfeats)
            pred = jnp.argmax(logits, axis=-1)
            m = tm.astype(jnp.float32)
            per = int_cross_entropy(logits, ty)
            return {
                "correct": jnp.sum((pred == ty).astype(jnp.float32) * m),
                "loss_sum": jnp.sum(per * m),
                "count": jnp.sum(m),
            }

        self._evaluate_feats = evaluate_feats

    def run(self):
        self.register_message_receive_handlers()
        if self.round_idx >= self.round_num:   # resumed a finished run
            self._teardown()
            return
        self._send_logits(MSG_TYPE_S2C_INIT_CONFIG)
        self.com_manager.handle_receive_message()

    def _maybe_checkpoint(self):
        if self._ckpt_path is None:
            return
        if (self.round_idx % self._ckpt_freq == 0
                or self.round_idx >= self.round_num):
            from fedml_tpu.utils.checkpoint import save_checkpoint

            # history entries are already plain floats/ints (built via
            # float() in _complete_round) — JSON-safe as-is
            save_checkpoint(
                self._ckpt_path,
                {"server_vars": self.api.server_vars,
                 "server_opt": self.api.server_opt,
                 "server_logits": self.api.server_logits},
                round_idx=self.round_idx,
                extra={"history": list(self.history)})

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_FEATURE_AND_LOGITS, self._on_features)
        self.register_message_receive_handler(
            MSG_TYPE_LOCAL_ROUND_DEADLINE, self._on_deadline)

    def _send_logits(self, msg_type: int):
        slogits = np.asarray(self.api.server_logits)
        for rank in range(1, self.size):
            if self._deadline is not None and not self._alive[rank - 1]:
                continue
            m = Message(msg_type, self.rank, rank)
            m.add_params(KEY_GLOBAL_LOGITS, slogits[rank - 1])
            m.add_params(KEY_ROUND, self.round_idx)
            try:
                self.send_message(m)
            except Exception as e:
                if self._deadline is None:
                    raise
                log.warning("GKT sync to client %d failed (%s); marking dead",
                            rank - 1, e)
                self._alive[rank - 1] = False
        if self._deadline_timer is not None:
            self._deadline_timer.arm(self.round_idx)

    def _on_deadline(self, msg: Message):
        if self._deadline is None or int(msg.get(KEY_ROUND)) != self.round_idx:
            return
        missing = [k for k in range(self.C)
                   if self._alive[k] and k not in self._feat]
        for k in missing:
            log.warning("GKT round %d: client %d missed the %.1fs deadline; "
                        "marking dead", self.round_idx, k, self._deadline)
            self._alive[k] = False
        if self._feat:
            self._empty_deadlines = 0
            self._complete_round()
        else:
            # nothing arrived, so the missing-loop above just marked every
            # alive client dead (GKT has no JOIN side-channel that could
            # revive one without populating _feat): wait for a late upload
            # to rejoin someone, bounded by the shared cap
            self._empty_deadlines += 1
            if self._empty_deadlines >= MAX_EMPTY_DEADLINES:
                log.error("GKT: all clients dead for %d deadlines; tearing "
                          "down with %d/%d rounds done", self._empty_deadlines,
                          self.round_idx, self.round_num)
                self._teardown()
            elif self._deadline_timer is not None:
                self._deadline_timer.arm(self.round_idx)

    def _teardown(self):
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        for rank in range(1, self.size):
            try:
                self.send_message(Message(MSG_TYPE_S2C_FINISH, self.rank, rank))
            except Exception as e:
                if self._deadline is None:
                    raise
                log.warning("FINISH to client %d failed (%s)", rank - 1, e)
        self.finish()

    def _on_features(self, msg: Message):
        k = msg.get_sender_id() - 1
        if self._deadline is not None:
            self._empty_deadlines = 0
            if not self._alive.get(k, False):
                log.info("GKT client %d rejoined at round %d", k, self.round_idx)
                self._alive[k] = True
                if int(msg.get(KEY_ROUND)) != self.round_idx:
                    # stale upload: catch the client up with the CURRENT
                    # round's logits so it can take part right away
                    m = Message(MSG_TYPE_S2C_SYNC_TO_CLIENT, self.rank, k + 1)
                    m.add_params(KEY_GLOBAL_LOGITS,
                                 np.asarray(self.api.server_logits)[k])
                    m.add_params(KEY_ROUND, self.round_idx)
                    try:
                        self.send_message(m)
                    except Exception as e:
                        log.warning("GKT catch-up to client %d failed (%s)",
                                    k, e)
                        self._alive[k] = False
                    return
            if int(msg.get(KEY_ROUND)) != self.round_idx:
                return   # stale upload from a round that already closed
        elif int(msg.get(KEY_ROUND)) != self.round_idx:
            raise RuntimeError(
                f"GKT features for round {msg.get(KEY_ROUND)} arrived at "
                f"server in round {self.round_idx}")
        self._feat[k] = tuple(np.asarray(msg.get(key)) for key in
                              (KEY_FEATURE, KEY_LOGITS, KEY_LABELS, KEY_MASK))
        self._test[k] = tuple(np.asarray(msg.get(key)) for key in
                              (KEY_FEATURE_TEST, KEY_LABELS_TEST,
                               KEY_MASK_TEST))
        expected = ({k for k in range(self.C) if self._alive[k]}
                    if self._deadline is not None else set(range(self.C)))
        if not expected <= set(self._feat):
            return
        self._complete_round()

    def _complete_round(self):
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        api = self.api
        received = sorted(self._feat)
        for k in received:
            self._last_feat[k] = self._feat[k]
            self._last_test[k] = self._test[k]
        template = self._feat[received[0]]

        def slot(k):
            """A missing client's slot: its LAST-RECEIVED features under a
            ZERO mask (no training contribution), or an all-zero slot if it
            died before ever uploading — the stack shape stays the static
            [C, ...] the server program was compiled for either way."""
            if k in self._feat:
                return self._feat[k]
            if k in self._last_feat:
                f, l, y, m = self._last_feat[k]
                return f, l, y, np.zeros_like(m)
            return tuple(np.zeros_like(t) for t in template)

        order = list(range(self.C))
        feats, clogits, ys, masks = (
            np.stack([slot(i)[j] for i in order]) for j in range(4))
        rkey = round_key(api.root_key, self.round_idx)
        (api.server_vars, api.server_opt, new_logits, sloss) = (
            api._server_phase(
                api.server_vars, api.server_opt, jnp.asarray(feats),
                jnp.asarray(ys), jnp.asarray(masks), jnp.asarray(clogits),
                jax.random.fold_in(rkey, 2),
            )
        )
        if len(received) == self.C:
            # healthy path (and the whole strict mode): every slot is
            # fresh — assign the jit output directly, no host round-trip
            api.server_logits = new_logits
        else:
            # scatter fresh logits back by client id; a missing client
            # keeps its previous logits (its slot's output came from stale
            # or zero inputs)
            merged = np.asarray(api.server_logits).copy()
            fresh = np.asarray(new_logits)
            for k in received:
                merged[k] = fresh[k]
            api.server_logits = jnp.asarray(merged)
        cfg = api.config
        if (self.round_idx % cfg.frequency_of_the_test == 0
                or self.round_idx == self.round_num - 1):
            torder = [k for k in order if k in self._last_test or k in self._test]
            tfeats, tys, tms = (
                jnp.asarray(np.stack([
                    (self._test.get(i) or self._last_test[i])[j]
                    for i in torder]))
                for j in range(3))
            sums = jax.device_get(
                self._evaluate_feats(api.server_vars, tfeats, tys, tms))
            acc = float(sums["correct"]) / max(float(sums["count"]), 1.0)
            self.history.append({
                "round": self.round_idx, "Test/Acc": acc,
                "Test/Loss": float(sums["loss_sum"]) / max(float(sums["count"]), 1.0),
                "Train/ServerLoss": float(sloss),
            })
            log.info("GKT-edge round %d: test acc %.4f", self.round_idx, acc)
        self._feat.clear()
        self._test.clear()
        self.round_idx += 1
        self._maybe_checkpoint()
        if self.round_idx >= self.round_num:
            self._teardown()
        else:
            self._send_logits(MSG_TYPE_S2C_SYNC_TO_CLIENT)


class GKTEdgeClientManager(ClientManager):
    """Trains the small edge net with distillation, extracts and uploads
    features/logits (reference GKTClientMananger)."""

    def __init__(self, args, comm, rank, size, *, train_one, extract_test,
                 root_key, cvars, copt, x, y, mask, count, test_x, test_y,
                 test_mask, alpha_distill, state_path=None, resume=False,
                 state_every=10):
        super().__init__(args, comm, rank, size)
        # train_one/extract arrive ALREADY jitted and shared across the C
        # managers (jitted functions are thread-safe): one compile serves
        # every client instead of C identical compiles
        self._train_one = train_one
        self._extract_test = extract_test
        self.root_key = root_key
        self.cvars, self.copt = cvars, copt
        self.x, self.y, self.mask, self.count = x, y, mask, count
        self.test_x, self.test_y, self.test_mask = test_x, test_y, test_mask
        self.alpha_distill = alpha_distill
        self.C = size - 1
        # per-client state persistence: unlike FedAvg (whose workers get the
        # model in every sync), GKT clients OWN their small-net weights —
        # resume must restore them or the federation restarts distillation
        # from scratch
        self._state_path = state_path
        self._state_every = max(int(state_every), 1)
        self._state_round = None
        self._init_state = (cvars, copt)
        if resume and state_path is not None:
            import os

            if os.path.exists(state_path):
                from fedml_tpu.core.serialization import tree_from_bytes

                with open(state_path, "rb") as f:
                    st = tree_from_bytes(f.read())
                self.cvars = st["cvars"]
                self.copt = st["copt"]
                self._state_round = int(np.asarray(st["round"]).item())
                log.info("GKT client %d resumed local state for round %d",
                         rank - 1, self._state_round)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_SYNC_TO_CLIENT, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH,
                                              lambda m: self.finish())

    def _on_sync(self, msg: Message):
        rnd = int(msg.get(KEY_ROUND))
        if self._state_round is not None:
            # discard only FUTURE-tagged state (server resumed from an
            # older checkpoint than this client's save). A PAST tag is the
            # normal straggler/dead-client case: the uninterrupted run
            # would have it rejoin with exactly those weights (the server
            # carries its old logits for the same reason), so keep them.
            if self._state_round > rnd:
                log.warning(
                    "GKT client %d: resumed state targets future round %d "
                    "but federation is at round %d; discarding it",
                    self.rank - 1, self._state_round, rnd)
                self.cvars, self.copt = self._init_state
            self._state_round = None
        slogits = jnp.asarray(np.asarray(msg.get(KEY_GLOBAL_LOGITS)))
        # same derivations as the simulation's client phase: kl_w gates the
        # distillation term off in round 0, and client k consumes key
        # split(fold_in(round_key, 1), C)[k]
        kl_w = jnp.float32(0.0 if rnd == 0 else self.alpha_distill)
        rkey = round_key(self.root_key, rnd)
        key = jax.random.split(jax.random.fold_in(rkey, 1), self.C)[self.rank - 1]
        (self.cvars, self.copt, feats, logits, _loss) = self._train_one(
            self.cvars, self.copt, self.x, self.y, self.mask, self.count,
            slogits, kl_w, key)
        tfeats = self._extract_test(self.cvars, self.test_x)
        out = Message(MSG_TYPE_C2S_SEND_FEATURE_AND_LOGITS, self.rank, 0)
        out.add_params(KEY_FEATURE, np.asarray(feats))
        out.add_params(KEY_LOGITS, np.asarray(logits))
        out.add_params(KEY_LABELS, np.asarray(self.y))
        out.add_params(KEY_MASK, np.asarray(self.mask))
        out.add_params(KEY_FEATURE_TEST, np.asarray(tfeats))
        out.add_params(KEY_LABELS_TEST, np.asarray(self.test_y))
        out.add_params(KEY_MASK_TEST, np.asarray(self.test_mask))
        out.add_params(KEY_ROUND, rnd)
        self.send_message(out)
        # persist ONLY at the server's checkpoint boundaries, so the
        # on-disk client state always matches a server checkpoint — a
        # kill between boundaries then resumes both sides consistently
        # from the same round instead of pairing a boundary server with
        # newer client nets (which resume would have to discard)
        if self._state_path is not None and (
                (rnd + 1) % self._state_every == 0
                or rnd + 1 >= int(self.args.comm_round)):
            import os

            from fedml_tpu.core.serialization import tree_to_bytes

            tmp = self._state_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(tree_to_bytes({
                    "cvars": self.cvars, "copt": self.copt,
                    "round": np.int64(rnd + 1)}))
            os.replace(tmp, self._state_path)


def run_fedgkt_edge(dataset, config, pair=None, client_blocks=None,
                    server_blocks_per_stage=None,
                    wire_roundtrip: bool = True, comm_factory=None):
    """Launch server + one manager per client over the local transport (or
    gRPC loopback via ``comm_factory``) and run the full feature/logit
    federation. Returns the server manager (history + trained server net via
    ``.api``). Reuses a FedGKTAPI instance as the program/state host so the
    wire run shares init and jitted compute with the simulation."""
    from fedml_tpu.algorithms.fedgkt import FedGKTAPI
    from fedml_tpu.obs import configure_from

    configure_from(config)
    codec = getattr(config, "wire_codec", "raw")
    if codec.startswith("topk"):
        # topk is a DELTA compressor (error feedback absorbs the unsent
        # mass, fedavg_edge only). GKT payloads are full per-sample
        # features/logits with no residual stream — sparsifying them is
        # silent corruption, so refuse rather than degrade.
        raise ValueError(
            "wire_codec='topk:..' is only valid for delta uploads "
            "(fedavg_edge with wire_delta); fedgkt_edge exchanges full "
            "feature/logit payloads — use 'q8' or 'raw'"
        )
    api = FedGKTAPI(dataset, config, pair=pair, client_blocks=client_blocks,
                    server_blocks_per_stage=server_blocks_per_stage)
    train_one = jax.jit(api._build_client_train_one())
    extract_test = jax.jit(
        lambda cv, tx: api.pair.client.apply_eval(cv, tx)[1])
    tx_, ty_, tm_ = api._test_shards
    size = api.C + 1

    if getattr(config, "straggler_deadline_sec", None) is not None:
        # Fault-tolerant mode: absorb the jit compiles BEFORE the deadline
        # clock can run — a first round slowed by compilation must not get
        # healthy clients marked dead. All three programs are functional;
        # the warmup outputs are discarded.
        cv0 = jax.tree.map(lambda v: v[0], api.client_vars)
        co0 = jax.tree.map(lambda v: v[0], api.client_opt)
        res = train_one(
            cv0, co0, jnp.asarray(dataset.train_x[0]),
            jnp.asarray(dataset.train_y[0]), jnp.asarray(dataset.train_mask[0]),
            jnp.asarray(dataset.train_counts[0], jnp.float32),
            api.server_logits[0], jnp.float32(0.0),
            jax.random.fold_in(api.root_key, 0))
        feats0 = jax.block_until_ready(res[2])
        jax.block_until_ready(extract_test(cv0, jnp.asarray(tx_[0])))
        C = api.C
        jax.block_until_ready(api._server_phase(
            api.server_vars, api.server_opt,
            jnp.broadcast_to(feats0, (C,) + feats0.shape),
            jnp.asarray(dataset.train_y), jnp.asarray(dataset.train_mask),
            jnp.broadcast_to(res[3], (C,) + res[3].shape),
            jax.random.fold_in(api.root_key, 1))[3])

    class Args:
        pass

    args = Args()
    args.comm_round = config.comm_round

    import os as _os

    resume_from = getattr(config, "resume_from", None)
    ckpt_dir = getattr(config, "checkpoint_dir", None)
    if ckpt_dir is None and resume_from:
        # resuming without writing new checkpoints: the per-client state
        # lives next to the server checkpoint being resumed
        ckpt_dir = _os.path.dirname(_os.path.abspath(resume_from))
    resume = bool(resume_from)
    ckpt_freq = int(getattr(config, "checkpoint_frequency", 10) or 10)

    def make(rank, comm):
        import os

        if rank == 0:
            return GKTEdgeServerManager(args, comm, rank, size, api)
        k = rank - 1
        return GKTEdgeClientManager(
            args, comm, rank, size,
            train_one=train_one, extract_test=extract_test,
            root_key=api.root_key,
            cvars=jax.tree.map(lambda v: v[k], api.client_vars),
            copt=jax.tree.map(lambda v: v[k], api.client_opt),
            x=jnp.asarray(dataset.train_x[k]), y=jnp.asarray(dataset.train_y[k]),
            mask=jnp.asarray(dataset.train_mask[k]),
            count=jnp.asarray(dataset.train_counts[k], jnp.float32),
            test_x=jnp.asarray(tx_[k]), test_y=np.asarray(ty_[k]),
            test_mask=np.asarray(tm_[k]),
            alpha_distill=config.alpha_distill,
            state_path=(os.path.join(ckpt_dir, f"gkt_client_{k}.state")
                        if ckpt_dir else None),
            resume=resume, state_every=ckpt_freq,
        )

    # GKT's payloads are the framework's biggest (per-sample feature maps +
    # logits both ways); the wire codec compresses them — q8 suits the
    # distillation exchange, whose targets are soft logits anyway. Labels/
    # masks and any integer arrays ride raw inside lossy frames.
    from fedml_tpu.comm.reliable import wire_wrap_factory

    managers = run_ranks(make, size, wire_roundtrip=wire_roundtrip,
                         comm_factory=comm_factory,
                         codec=getattr(config, "wire_codec", "raw"),
                         wrap=wire_wrap_factory(config))
    return managers[0]
