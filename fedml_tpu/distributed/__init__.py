"""Node runtimes, topologies, and edge federation (counterpart of
fedml_core/distributed): the Message/Observer/handler-registry machinery kept
for genuinely off-pod clients, plus graph topologies for decentralized FL.

In-pod communication does NOT live here — it is XLA collectives
(fedml_tpu.parallel.crosssilo); this package is the true network edge.
"""

from fedml_tpu.distributed.topology import (
    AsymmetricTopologyManager,
    BaseTopologyManager,
    SymmetricTopologyManager,
)

__all__ = [
    "BaseTopologyManager",
    "SymmetricTopologyManager",
    "AsymmetricTopologyManager",
]
