"""gRPC edge transport for off-pod/external federation.

Reference: fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:
20-106 — a grpc server per node on port base+rank, send = open a channel to
the receiver's IP (from a rank→IP csv table) and make a unary call, receive
= servicer enqueues and a handler loop drains.

Differences by design:
- generic bytes RPC (``grpc.unary_unary_rpc_method_handler`` with identity
  serializers) instead of protoc-generated stubs — nothing to regenerate;
- channels are cached per receiver instead of opened/closed per send
  (reference grpc_comm_manager.py:62-74 reconnects every message);
- payload is the flat-buffer Message wire format, not pickled state dicts;
- receive dispatch is a blocking queue, not a poll loop.
"""

from __future__ import annotations

import csv
import logging
import queue
import threading
from concurrent import futures
from typing import Dict, Optional

import grpc

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message

LOG = logging.getLogger(__name__)

_SERVICE = "fedml_tpu.Comm"
_METHOD = "SendMessage"
_FULL_METHOD = f"/{_SERVICE}/{_METHOD}"
# Reference caps messages at 100 MB (grpc_comm_manager.py:35-36); modern
# models are bigger — allow 2 GB minus slack.
_MAX_MSG = 2 * 1024 * 1024 * 1024 - 1024

_STOP = object()


def build_ip_table(path: str) -> Dict[int, str]:
    """rank→IP table from csv (reference ip_config_utils.build_ip_table).

    csv format: ``receiver_id,ip`` with a header row.
    """
    table: Dict[int, str] = {}
    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    for row in rows[1:]:
        if len(row) >= 2 and row[0].strip():
            table[int(row[0])] = row[1].strip()
    return table


class GRPCCommManager(BaseCommunicationManager):
    BASE_PORT = 50000  # reference: port 50000 + rank (grpc_comm_manager.py:27)

    def __init__(
        self,
        rank: int,
        size: int,
        ip_table: Optional[Dict[int, str]] = None,
        ip_config_path: Optional[str] = None,
        base_port: int = BASE_PORT,
        host: str = "0.0.0.0",
        codec: str = "raw",
        send_timeout: float = 120.0,
        inbox_cap: int = 0,
    ):
        super().__init__(codec=codec)
        self.rank = int(rank)
        self.size = int(size)
        self.base_port = int(base_port)
        # In multi-process deployments ranks start in arbitrary order, so a
        # send may race the receiver's bind; wait_for_ready blocks the call
        # until the peer's server is up, bounded by this timeout.
        self.send_timeout = float(send_timeout)
        if ip_table is None:
            ip_table = build_ip_table(ip_config_path) if ip_config_path else {r: "127.0.0.1" for r in range(size)}
        self.ip_table = ip_table
        # inbox_cap > 0 bounds the inbox (--wire_inbox_cap): a full inbox
        # blocks the servicer thread, which parks the SENDER's unary call —
        # gRPC's own flow control becomes the backpressure path. 0 keeps
        # the historical unbounded queue.
        self._inbox: "queue.Queue" = queue.Queue(maxsize=int(inbox_cap))
        self._channels: Dict[int, grpc.Channel] = {}
        self._stubs: Dict[int, grpc.UnaryUnaryMultiCallable] = {}
        self._lock = threading.Lock()
        self._running = False

        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                _METHOD: grpc.unary_unary_rpc_method_handler(
                    self._servicer,
                    request_deserializer=None,  # raw bytes through
                    response_serializer=None,
                )
            },
        )
        opts = [
            ("grpc.max_send_message_length", _MAX_MSG),
            ("grpc.max_receive_message_length", _MAX_MSG),
        ]
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8), options=opts)
        self._server.add_generic_rpc_handlers((handler,))
        self._port = self._server.add_insecure_port(f"{host}:{self.base_port + self.rank}")
        if self._port == 0:
            raise OSError(
                f"grpc comm manager rank {self.rank}: failed to bind "
                f"{host}:{self.base_port + self.rank} (port in use?)"
            )
        self._server.start()
        LOG.info("grpc comm manager rank %d listening on :%d", self.rank, self._port)

    # -- servicer side (reference grpc_server.py:9-40) ---------------------
    def _servicer(self, request: bytes, context) -> bytes:
        self._inbox.put(Message.from_bytes(request))
        return b"ok"

    # -- send side (reference grpc_comm_manager.py:56-74) ------------------
    def _stub_for(self, receiver: int):
        with self._lock:
            if receiver not in self._stubs:
                ip = self.ip_table[receiver]
                chan = grpc.insecure_channel(
                    f"{ip}:{self.base_port + receiver}",
                    options=[
                        ("grpc.max_send_message_length", _MAX_MSG),
                        ("grpc.max_receive_message_length", _MAX_MSG),
                    ],
                )
                self._channels[receiver] = chan
                self._stubs[receiver] = chan.unary_unary(
                    _FULL_METHOD, request_serializer=None, response_deserializer=None
                )
            return self._stubs[receiver]

    def send_message(self, msg: Message) -> None:
        self._stub_for(int(msg.get_receiver_id()))(
            msg.to_bytes(msg.codec or self.codec),
            wait_for_ready=True,
            timeout=self.send_timeout,
        )

    def inject_local(self, msg: Message) -> None:
        self._inbox.put(msg)

    # -- receive loop ------------------------------------------------------
    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(item)
        self._shutdown()

    def stop_receive_message(self) -> None:
        self._running = False
        # teardown must not deadlock on a full bounded inbox: make room by
        # dropping the oldest queued item (unacked under the reliable layer,
        # so it is retransmitted — and the loop is exiting regardless)
        while True:
            try:
                self._inbox.put(_STOP, timeout=0.05)
                return
            except queue.Full:
                try:
                    self._inbox.get_nowait()
                except queue.Empty:
                    pass

    def _shutdown(self) -> None:
        with self._lock:
            for chan in self._channels.values():
                chan.close()
            self._channels.clear()
            self._stubs.clear()
        self._server.stop(grace=1.0)
