"""In-process multi-rank transport.

Plays the role of the reference MPI backend
(fedml_core/distributed/communication/mpi/) for single-host runs and tests:
N logical ranks exchanging Messages. The reference implementation uses two
daemon threads + two queues per process with a 0.3 s receive poll
(com_manager.py:71-79) and kills threads via
ctypes PyThreadState_SetAsyncExc (mpi_send_thread.py:47-53) — both
explicitly NOT replicated (SURVEY.md §5.2): here delivery is a single
blocking ``queue.Queue`` per rank and shutdown is a sentinel message.

Real multi-host TPU runs don't use this either — they use jax.distributed +
mesh collectives (fedml_tpu/parallel/). This backend exists so the
message-driven algorithm managers (SplitNN, FedGKT, base_framework, edge
federation) can run all ranks in one process, each rank on its own thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message

_STOP = object()


class LocalRouter:
    """Shared mailbox set for a group of ranks (one per launch).

    ``cap`` bounds every mailbox (0 = unbounded, the historical default):
    a sender to a full mailbox BLOCKS until the receiver drains — the
    in-process analogue of TCP flow control, and what ``--wire_inbox_cap``
    means on this transport. The gateway's per-tenant lanes add the
    WIRE_BUSY reply protocol on top (comm/flow.py); the transport itself
    only ever holds, never drops.
    """

    def __init__(self, size: int, cap: int = 0):
        self.size = size
        self.cap = int(cap)
        self._queues: Dict[int, "queue.Queue"] = {
            r: queue.Queue(maxsize=self.cap) for r in range(size)}

    def post(self, rank: int, item) -> None:
        self._queues[int(rank)].put(item)

    def post_control(self, rank: int, item) -> None:
        """Teardown-priority post: never blocks forever on a full mailbox —
        drops the oldest queued item to make room (the receiver is being
        stopped; under the reliable layer an unacked drop is retransmitted,
        and at teardown the peer's retries are bounded anyway)."""
        q = self._queues[int(rank)]
        while True:
            try:
                q.put(item, timeout=0.05)
                return
            except queue.Full:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass

    def take(self, rank: int, timeout: Optional[float] = None):
        return self._queues[int(rank)].get(timeout=timeout)


class LocalCommunicationManager(BaseCommunicationManager):
    def __init__(self, router: LocalRouter, rank: int, wire_roundtrip: bool = False,
                 codec: str = "raw"):
        super().__init__(codec=codec)
        self.router = router
        self.rank = int(rank)
        self._running = False
        # When set, every message is serialized+deserialized in transit —
        # tests use this to exercise the exact bytes a gRPC hop would carry.
        # A non-raw codec forces the roundtrip (compression must actually
        # apply in-process exactly as it would on a real wire).
        self.wire_roundtrip = wire_roundtrip or codec != "raw"

    def send_message(self, msg: Message) -> None:
        payload = (Message.from_bytes(msg.to_bytes(msg.codec or self.codec))
                   if self.wire_roundtrip else msg)
        self.router.post(msg.get_receiver_id(), payload)

    def inject_local(self, msg: Message) -> None:
        self.router.post(self.rank, msg)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self.router.take(self.rank)
            if item is _STOP:
                break
            self._notify(item)

    def stop_receive_message(self) -> None:
        self._running = False
        self.router.post_control(self.rank, _STOP)


def run_ranks(make_manager, size: int, wire_roundtrip: bool = False,
              timeout: float = 300.0, comm_factory=None, codec: str = "raw",
              wrap=None, inbox_cap: int = 0):
    """Launch ``size`` ranks on threads; rank r runs make_manager(r, comm).

    ``make_manager`` returns an object with ``.run()`` (typically a
    ClientManager/ServerManager subclass). Returns the per-rank manager
    objects after all threads join. Mirrors the reference's
    ``mpirun -np N`` + rank branch (FedAvgAPI.py:20-28) for in-process use.

    ``comm_factory(rank) -> BaseCommunicationManager`` substitutes a real
    transport (e.g. gRPC loopback) for the in-process router; the default
    builds LocalCommunicationManagers over one shared LocalRouter.
    ``codec`` sets the default transport's wire codec (compression); a
    comm_factory configures its own backends.
    ``wrap(rank, comm) -> comm`` layers wire middleware (reliable delivery,
    chaos injection — comm/reliable.py wire_wrap_factory) over whichever
    transport was built, so every protocol gets it without code changes.
    ``inbox_cap`` bounds the default router's per-rank mailboxes
    (``--wire_inbox_cap``; 0 = unbounded); a comm_factory configures its
    own backend's cap.
    """
    router = None if comm_factory else LocalRouter(size, cap=inbox_cap)
    comms: list[BaseCommunicationManager] = []
    try:
        for r in range(size):
            c = (comm_factory(r) if comm_factory
                 else LocalCommunicationManager(router, r,
                                                wire_roundtrip=wire_roundtrip,
                                                codec=codec))
            comms.append(wrap(r, c) if wrap is not None else c)
        managers = [make_manager(r, comms[r]) for r in range(size)]
    except BaseException:
        # partial setup (e.g. a gRPC port already bound): release what was
        # created so a retry in-process doesn't inherit bound ports
        for c in comms:
            c.stop_receive_message()
        raise

    errors: Dict[int, BaseException] = {}

    def _run(rank: int, m) -> None:
        from fedml_tpu.obs import tracer_if_enabled

        tr = tracer_if_enabled(rank)
        try:
            if tr is None:
                m.run()
            else:
                # rank lifecycle span: everything the rank does (handler
                # recv spans included) nests under it in the timeline
                with tr.span("rank_run", cat="lifecycle",
                             args={"rank": rank}):
                    m.run()
        except BaseException as e:  # propagate to the caller, unblock peers
            errors[rank] = e
            for c in comms:
                c.stop_receive_message()

    threads = [
        threading.Thread(target=_run, args=(r, m), daemon=True, name=f"rank{r}")
        for r, m in enumerate(managers)
    ]
    from fedml_tpu.obs import flush_all, tracing_enabled

    for t in threads:
        t.start()
    try:
        for t in threads:
            t.join(timeout=timeout)
            if t.is_alive() and not errors:
                raise TimeoutError(f"rank thread {t.name} did not finish within {timeout}s")
    finally:
        if tracing_enabled():
            # flush per-rank trace files even on timeout/failure: a
            # federation that hung or crashed is exactly the one whose
            # timeline is needed
            flush_all()
    if errors:
        rank, err = sorted(errors.items())[0]
        raise RuntimeError(f"rank {rank} raised during run_ranks") from err
    return managers
