"""Node runtime: handler-registry managers (reference L2).

Reference: fedml_core/distributed/client/client_manager.py:13-73 and
server/server_manager.py:13-68 — both are Observers; ``run()`` registers
message handlers then blocks in ``com_manager.handle_receive_message()``;
dispatch is ``message_handler_dict[msg_type]`` (client_manager.py:43-47).

Kept: the exact registry/run/dispatch surface, so every message-driven
algorithm (SplitNN, FedGKT, edge FedAvg…) is a thin subclass, as in the
reference. Changed: ``finish()`` performs a graceful stop of the receive
loop instead of ``MPI.COMM_WORLD.Abort()`` (client_manager.py:66-69) — a
hard abort with no drain, flagged in SURVEY.md §5.3 as a defect.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_TENANT,
    MSG_ARG_KEY_TRACE_CTX,
    Message,
)
from fedml_tpu.obs import tracer_if_enabled

LOG = logging.getLogger(__name__)


class _ManagerBase(Observer):
    #: tenant id under a federation gateway (distributed/gateway.py): when
    #: set, every outgoing envelope is stamped with ``__tenant__`` so the
    #: gateway can route it into this tenant's lane — exactly the trace-ctx
    #: pattern below. None (the default) stamps nothing: a standalone
    #: federation's wire bytes are unchanged.
    tenant: "str | None" = None

    def __init__(self, args, comm: BaseCommunicationManager, rank: int = 0, size: int = 0):
        self.args = args
        self.com_manager = comm
        self.rank = int(rank)
        self.size = int(size)
        self.com_manager.add_observer(self)
        self.message_handler_dict: Dict[object, Callable[[Message], None]] = {}

    def register_comm_manager(self, comm: BaseCommunicationManager) -> None:
        self.com_manager = comm

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()
        LOG.debug("rank %d run loop exited", self.rank)

    def register_message_receive_handlers(self) -> None:
        raise NotImplementedError

    def register_message_receive_handler(self, msg_type, handler: Callable[[Message], None]) -> None:
        self.message_handler_dict[msg_type] = handler

    def receive_message(self, msg_type, msg_params: Message) -> None:
        handler = self.message_handler_dict.get(msg_type)
        if handler is None:
            LOG.warning("rank %d: no handler for msg_type=%r", self.rank, msg_type)
            return
        tr = tracer_if_enabled(self.rank)
        if tr is None:
            handler(msg_params)
            return
        # recv span: linked to the sender's send span by the message uid in
        # the envelope's trace context; the parent id makes the causal chain
        # explicit even before the analyzer joins the per-rank files
        ctx = msg_params.get(MSG_ARG_KEY_TRACE_CTX)
        args = {"msg_type": str(msg_type),
                "peer": int(msg_params.get_sender_id())}
        if ctx:
            args["mid"] = ctx[2]
            args["send_sid"] = ctx[1]
            args["send_trace"] = ctx[0]
        with tr.span("recv", cat="comm", args=args):
            handler(msg_params)

    def send_message(self, message: Message) -> None:
        if self.tenant is not None:
            message.add_params(MSG_ARG_KEY_TENANT, self.tenant)
        tr = tracer_if_enabled(self.rank)
        if tr is None:
            self.com_manager.send_message(message)
            return
        with tr.span("send", cat="comm") as sp:
            ctx = tr.make_ctx(sp.span_id)
            message.add_params(MSG_ARG_KEY_TRACE_CTX, ctx)
            sp.set("msg_type", str(message.get_type()))
            sp.set("peer", int(message.get_receiver_id()))
            sp.set("mid", ctx[2])
            self.com_manager.send_message(message)

    def finish(self) -> None:
        """Graceful drain-and-stop (NOT the reference's COMM_WORLD.Abort)."""
        self.com_manager.stop_receive_message()


class ClientManager(_ManagerBase):
    """Per-client runtime (reference client/client_manager.py:13-73)."""


class ServerManager(_ManagerBase):
    """Rank-0 runtime (reference server/server_manager.py:13-68)."""
