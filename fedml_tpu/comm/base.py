"""Abstract comm-manager + observer interfaces.

Reference: fedml_core/distributed/communication/base_com_manager.py:7-27 and
observer.py:4-7. The surface is kept so algorithm managers written against
the reference port over directly; semantics differ in one way: backends here
deliver messages via blocking queues (no polling latency) and support
graceful shutdown (no MPI.COMM_WORLD.Abort()).
"""

from __future__ import annotations

import abc
from typing import List

from fedml_tpu.comm.message import Message


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type, msg_params: Message) -> None:
        ...


class BaseCommunicationManager(abc.ABC):
    #: wire codec applied to pytree payloads on SEND (core/compression.py:
    #: raw | q8 | topk:<ratio>). Receivers decode any codec — frames are
    #: self-describing — so the two sides of a link may differ.
    codec: str = "raw"

    def __init__(self, codec: str = "raw") -> None:
        self._observers: List[Observer] = []
        self.codec = codec

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching incoming messages to observers, until stopped."""

    @abc.abstractmethod
    def stop_receive_message(self) -> None:
        ...

    def inject_local(self, msg: Message) -> None:
        """Enqueue a message into THIS node's own delivery queue (it never
        touches the wire). Control events — e.g. a straggler-deadline timer
        firing — use this so they serialize with real message handling on
        the receive loop instead of racing it from another thread."""
        raise NotImplementedError(f"{type(self).__name__} has no local injection")

    def supports_local_injection(self) -> bool:
        """Whether inject_local reaches a real delivery queue. Wrapper
        transports (reliable/chaos) override this to ask the transport they
        wrap — merely defining a delegating inject_local must not make a
        non-injectable backend look injectable."""
        return type(self).inject_local is not BaseCommunicationManager.inject_local

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)


def find_layer(comm, cls):
    """Walk a wire middleware stack (the ``.inner`` chain: reliable over
    chaos over a bare transport) down to the first layer of ``cls`` —
    None when that middleware isn't stacked. The one walk protocol code
    uses to reach a specific layer's hooks (fedbuff's gave-up ejection
    oracle, the chaos ``on_restart`` re-announce)."""
    node = comm
    while node is not None:
        if isinstance(node, cls):
            return node
        node = getattr(node, "inner", None)
    return None
