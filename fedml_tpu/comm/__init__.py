"""Edge-transport communication layer (reference L0/L1).

In-mesh federation (simulation / cross-silo on one pod) never touches this
package — aggregation is a weighted ``psum`` over the device mesh
(fedml_tpu/parallel/crosssilo.py). This package exists for *genuinely
external* participants: off-pod silos, mobile clients, cross-datacenter
federation — the role the reference's MPI/gRPC/MQTT backends play
(fedml_core/distributed/communication/, SURVEY.md §2.7).

Surface mirrors the reference: ``Message`` envelope (message.py:5-74),
``Observer`` callback (observer.py:4-7), ``BaseCommunicationManager``
(base_com_manager.py:7-27), concrete backends selected by name via
``create_comm_manager``. Differences by design:

- payloads are flat-buffer pytrees (core/serialization.py), not pickled
  torch state_dicts or JSON nested lists;
- the local backend uses blocking queues, not the reference MPI backend's
  0.3 s receive poll (com_manager.py:78) or ctypes thread kill
  (mpi_send_thread.py:47-53);
- gRPC uses a generic bytes RPC (no generated stubs to drift out of sync
  with a .proto).
"""

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.local import LocalCommunicationManager, LocalRouter
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.reliable import (
    ReliableCommManager,
    build_wire_stack,
    wire_wrap_factory,
)


def create_comm_manager(backend: str, **kwargs):
    """Backend factory (reference client_manager.py:20-32 backend switch)."""
    if backend in ("LOCAL", "local", "MPI"):
        # MPI's role (single-datacenter multi-process ranks) is played by the
        # in-process router for simulation and by jax.distributed + mesh
        # collectives for real multi-host — there is no mpi4py path.
        return LocalCommunicationManager(**kwargs)
    if backend in ("GRPC", "grpc"):
        from fedml_tpu.comm.grpc_backend import GRPCCommManager

        return GRPCCommManager(**kwargs)
    if backend in ("MQTT", "mqtt"):
        from fedml_tpu.comm.mqtt_backend import MqttCommManager

        return MqttCommManager(**kwargs)
    raise ValueError(f"unknown comm backend: {backend!r}")


__all__ = [
    "Message",
    "Observer",
    "BaseCommunicationManager",
    "LocalCommunicationManager",
    "LocalRouter",
    "ClientManager",
    "ServerManager",
    "ReliableCommManager",
    "build_wire_stack",
    "wire_wrap_factory",
    "create_comm_manager",
]
