"""Minimal MQTT 3.1.1 client over plain TCP, paho-surface-compatible.

Implements exactly the client surface ``MqttCommManager`` uses —
``Client(client_id, protocol)``, ``connect``, ``loop_start``,
``subscribe``, ``publish``, ``loop_stop``, ``disconnect``, plus the
``on_connect``/``on_message`` callbacks — so the backend runs over a REAL
socket (against ``mqtt_broker.MqttBroker`` or any standard broker) when
paho-mqtt is absent from the image.

Auto-reconnect: if the socket drops while the loop is running, the reader
reconnects with a short backoff and refires ``on_connect`` — the backend's
subscriptions are re-established there, so a broker restart loses at most
in-flight QoS-0 messages (the reference's paho configuration has the same
QoS-0 semantics)."""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time

from fedml_tpu.comm.mqtt_broker import (CONNACK, CONNECT, DISCONNECT, PINGRESP,
                                        PUBLISH, SUBACK, SUBSCRIBE,
                                        encode_varlen, mqtt_str,
                                        publish_packet, read_varlen)

log = logging.getLogger(__name__)

MQTTv311 = 4


class _Msg:
    def __init__(self, topic: str, payload: bytes):
        self.topic = topic
        self.payload = payload


class Client:
    def __init__(self, client_id: str = "", protocol: int = MQTTv311,
                 reconnect_backoff: float = 0.2):
        self._id = client_id or f"fedml-{id(self)}"
        self.on_connect = None
        self.on_message = None
        self._host = self._port = None
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()
        self._running = False
        self._thread = None
        self._pid = 0
        self._backoff = reconnect_backoff

    # -- paho surface ------------------------------------------------------
    def connect(self, host: str, port: int = 1883, keepalive: int = 60):
        self._host, self._port = host, int(port)
        self._dial()

    def loop_start(self):
        self._running = True
        self._thread = threading.Thread(target=self._reader, daemon=True,
                                        name=f"mqtt-client-{self._id}")
        self._thread.start()

    def subscribe(self, topic: str, qos: int = 0):
        self._pid = (self._pid % 0xFFFF) + 1
        body = struct.pack(">H", self._pid) + mqtt_str(topic) + bytes([0])
        # SUBSCRIBE fixed-header flags are mandatory 0b0010 (§3.8.1)
        self._send(bytes([(SUBSCRIBE << 4) | 0x2])
                   + encode_varlen(len(body)) + body)

    def publish(self, topic: str, payload: bytes = b"", qos: int = 0):
        self._send(publish_packet(topic, bytes(payload)))

    def loop_stop(self):
        self._running = False

    def disconnect(self):
        self._running = False
        try:
            self._send(bytes([DISCONNECT << 4, 0]))
        except OSError:
            pass
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    # -- wire --------------------------------------------------------------
    def _dial(self):
        sock = socket.create_connection((self._host, self._port), timeout=10)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        body = (mqtt_str("MQTT") + bytes([MQTTv311])
                + bytes([0x02])            # clean session
                + struct.pack(">H", 60)    # keepalive
                + mqtt_str(self._id))
        sock.sendall(bytes([CONNECT << 4]) + encode_varlen(len(body)) + body)
        self._sock = sock

    def _send(self, pkt: bytes):
        with self._wlock:
            if self._sock is None:
                raise OSError("not connected")
            self._sock.sendall(pkt)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed")
            buf += chunk
        return buf

    def _reader(self):
        while self._running:
            try:
                hdr = self._recv_exact(1)[0]
                ptype = hdr >> 4
                length = read_varlen(self._recv_exact)
                body = self._recv_exact(length) if length else b""
                if ptype == CONNACK:
                    if self.on_connect:
                        self.on_connect(self, None, None, body[1])
                elif ptype == PUBLISH:
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode()
                    if self.on_message:
                        self.on_message(self, None,
                                        _Msg(topic, body[2 + tlen:]))
                elif ptype in (SUBACK, PINGRESP):
                    pass
            except (ConnectionError, OSError, IndexError):
                if not self._running:
                    return
                # broker went away: reconnect and refire on_connect so the
                # owner re-subscribes (QoS-0: in-flight messages are lost)
                log.warning("mqtt client %s: connection lost, reconnecting",
                            self._id)
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                while self._running:
                    try:
                        time.sleep(self._backoff)
                        self._dial()
                        break
                    except OSError:
                        continue
