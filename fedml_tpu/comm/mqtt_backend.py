"""MQTT pub/sub transport over a real broker socket.

Reference: fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:
14-126 — broker pub/sub with per-pair topics: server→client on
``topic0_<clientID>``, client→server on ``topic<clientID>``
(:47-70, :99-120). The same topic scheme is kept here; payloads are the
flat-buffer Message wire format (base64-free raw bytes — MQTT payloads are
binary-safe).

Client stack: paho-mqtt when installed (the reference's client); otherwise
the in-repo socket client (comm/mqtt_client.py) speaking MQTT 3.1.1 over
plain TCP — against ``comm/mqtt_broker.MqttBroker`` or any standard broker
— so the wire semantics run over REAL sockets in this image too
(VERDICT r4 #4), including reconnect-and-resubscribe on broker restart.
"""

from __future__ import annotations

import queue
from typing import Optional

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message

try:
    import paho.mqtt.client as _mqtt

    HAS_PAHO = True
except ImportError:  # image has no paho: the socket client takes over
    from fedml_tpu.comm import mqtt_client as _mqtt

    HAS_PAHO = False

_STOP = object()


class MqttCommManager(BaseCommunicationManager):
    def __init__(self, host: str, port: int, client_id: int, client_num: int,
                 topic: str = "fedml", codec: str = "raw", inbox_cap: int = 0):
        super().__init__(codec=codec)
        self.client_id = int(client_id)
        self.client_num = int(client_num)
        self.topic = topic
        # inbox_cap > 0 bounds the inbox (--wire_inbox_cap): a full inbox
        # blocks the broker network loop, so TCP flow control throttles the
        # broker -> this node stream. 0 keeps the historical unbounded queue.
        self._inbox: "queue.Queue" = queue.Queue(maxsize=int(inbox_cap))
        self._running = False
        self._client = _mqtt.Client(client_id=f"{topic}_node{client_id}", protocol=_mqtt.MQTTv311)
        self._client.on_connect = self._on_connect
        self._client.on_message = self._on_message
        self._client.connect(host, port)
        self._client.loop_start()

    # server (id 0) listens on topic<cid> for every client; clients listen
    # on topic0_<own id>  (reference mqtt_comm_manager.py:47-70)
    def _on_connect(self, client, userdata, flags, rc):
        if self.client_id == 0:
            for cid in range(1, self.client_num + 1):
                client.subscribe(f"{self.topic}{cid}")
        else:
            client.subscribe(f"{self.topic}0_{self.client_id}")

    def _on_message(self, client, userdata, msg):
        self._inbox.put(Message.from_bytes(msg.payload))

    def send_message(self, msg: Message) -> None:
        receiver = int(msg.get_receiver_id())
        if self.client_id == 0:
            topic = f"{self.topic}0_{receiver}"          # server -> client
        elif receiver == 0:
            topic = f"{self.topic}{self.client_id}"      # client -> server
        else:
            # the per-pair topic scheme is star-only (reference
            # mqtt_comm_manager.py:47-70 has the same shape); routing a
            # client->client message via the server topic would misdeliver it
            raise NotImplementedError(
                "MQTT backend supports star (client<->server) routing only; "
                "peer-to-peer algorithms need the LOCAL or gRPC backend"
            )
        self._client.publish(topic, payload=msg.to_bytes(msg.codec or self.codec))

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(item)
        self._client.loop_stop()
        self._client.disconnect()

    def stop_receive_message(self) -> None:
        self._running = False
        # teardown must not deadlock on a full bounded inbox: drop the
        # oldest queued item to make room (the loop is exiting anyway; an
        # unacked drop under the reliable layer is retransmitted)
        while True:
            try:
                self._inbox.put(_STOP, timeout=0.05)
                return
            except queue.Full:
                try:
                    self._inbox.get_nowait()
                except queue.Empty:
                    pass
