"""Gateway flow layers: bounded lane inboxes + tenant channel/link.

The three bare transports are single-federation by construction: one
process, one rank space, one unbounded inbox per rank. A multi-tenant
federation gateway (distributed/gateway.py) multiplexes N federations over
ONE shared transport listener, which needs exactly three mechanisms — all
transport-agnostic, so they live here rather than in any backend:

- :class:`BoundedInbox` — the per-tenant lane queue the gateway routes
  into. Bounded (``--wire_inbox_cap``) with explicit overflow handling:
  the mux either sheds a strictly-older queued upload or answers the
  sender with WIRE_BUSY — never a silent drop, never unbounded growth.
  Control items (the lane's shutdown sentinel, local injections, wire
  acks) bypass the cap so backpressure can't wedge teardown or ack flow.
- :class:`TenantChannel` — the WORKER-side shim between the wire
  middleware stack and the bare transport: stamps every outgoing envelope
  with the tenant id and the worker's global transport rank (the reply
  address for gateway push-back), so even layer-generated traffic the
  managers never see (reliable acks) arrives at the gateway routable.
- :class:`TenantLink` — the GATEWAY-side lane transport: a
  BaseCommunicationManager whose receive loop drains the lane's
  BoundedInbox and whose send path translates tenant-LOCAL receiver ranks
  to the shared transport's global rank space. Everything above it — the
  lane's reliable layer, the unmodified FedAvg server manager — runs in
  tenant-local rank space (rank 0 + workers 1..W), exactly as standalone;
  the translation is one shallow envelope copy per send (the reliable
  layer retransmits the SAME Message object, so in-place rewrites would
  double-translate).

Rank spaces: the shared transport has global ranks 0 (gateway) and
``base_rank + r`` for tenant-local worker rank ``r`` (1..W), where
``base_rank`` is the tenant's cumulative worker offset. Worker→gateway
traffic needs NO translation (local receiver 0 == global 0, and the lane
needs the LOCAL sender — the server computes the worker index from it);
only gateway→worker sends translate, in :meth:`TenantLink.send_message`.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_RECEIVER,
    MSG_ARG_KEY_TENANT,
    MSG_ARG_KEY_WIRE_MID,
    Message,
)

#: the sender's GLOBAL transport rank, stamped by TenantChannel on every
#: outgoing envelope — the gateway's reply address for WIRE_BUSY push-back
#: and eviction NACKs (the envelope's ``sender`` stays tenant-local; the
#: lane's server manager derives the worker index from it)
MSG_ARG_KEY_GW_SRC = "__gw_src__"

#: lane shutdown sentinel (same pattern as the bare transports' _STOP)
STOP = object()


class BoundedInbox:
    """Bounded FIFO lane queue with mid-tracking and stale-shed support.

    ``cap`` <= 0 means unbounded. ``try_put`` refuses when full (the mux
    then sheds or replies busy); ``put_control`` always succeeds (shutdown
    sentinel, local injections, acks). ``peak`` records the high-water
    depth — the backpressure pin asserts ``peak <= cap``.
    """

    def __init__(self, cap: int = 0):
        self.cap = int(cap)
        self._q: deque = deque()
        self._cv = threading.Condition()
        # wire mids currently queued: the mux drops a retransmitted copy of
        # a still-queued message instead of double-enqueueing it (the queued
        # copy is unacked, so the sender keeps retrying until the lane
        # processes and acks it — at-least-once is preserved)
        self._mids: set = set()
        self.peak = 0

    def _append(self, item) -> None:
        self._q.append(item)
        if isinstance(item, Message):
            mid = item.get(MSG_ARG_KEY_WIRE_MID)
            if mid is not None:
                self._mids.add(mid)
        if len(self._q) > self.peak:
            self.peak = len(self._q)
        self._cv.notify()

    def try_put(self, msg: Message) -> bool:
        with self._cv:
            if self.cap > 0 and len(self._q) >= self.cap:
                return False
            self._append(msg)
            return True

    def put_control(self, item) -> None:
        with self._cv:
            self._append(item)

    def take(self):
        with self._cv:
            while not self._q:
                self._cv.wait()
            item = self._q.popleft()
            if isinstance(item, Message):
                self._mids.discard(item.get(MSG_ARG_KEY_WIRE_MID))
            return item

    def has_mid(self, mid) -> bool:
        with self._cv:
            return mid in self._mids

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    def shed_older_than(self, round_tag: int) -> Optional[Message]:
        """Evict and return the queued message with the SMALLEST round tag,
        provided it is strictly older than ``round_tag`` (the incoming
        message's round) — the load-shed policy: a stale upload of an
        already-superseded round yields its slot to current-round traffic.
        Returns None when nothing qualifies (the mux then answers the
        incoming sender with WIRE_BUSY instead). The evicted message was
        never acked, so its sender's reliable layer still owns it."""
        with self._cv:
            best_i = best_rnd = None
            for i, item in enumerate(self._q):
                if not isinstance(item, Message):
                    continue
                rnd = item.get("round_idx")
                if rnd is None:
                    continue
                if best_rnd is None or int(rnd) < best_rnd:
                    best_i, best_rnd = i, int(rnd)
            if best_rnd is None or best_rnd >= int(round_tag):
                return None
            victim = self._q[best_i]
            del self._q[best_i]
            self._mids.discard(victim.get(MSG_ARG_KEY_WIRE_MID))
            return victim

    def drain(self) -> list:
        """Empty the queue (quarantine teardown); returns the drained
        Messages (sentinels excluded) so the caller can count them."""
        with self._cv:
            items = [m for m in self._q if isinstance(m, Message)]
            self._q.clear()
            self._mids.clear()
            self._cv.notify_all()
            return items


class TenantChannel(BaseCommunicationManager, Observer):
    """Worker-side shim under the wire middleware stack: stamps tenant id
    + global source rank on every OUTGOING envelope (idempotent — the same
    values land on a retransmit of the same Message object) and passes
    inbound traffic through untouched (nothing on the worker's inbound
    path reads the receiver field). Sits INSIDE chaos/reliable, so those
    layers see the same tenant-local ids they would standalone."""

    def __init__(self, inner: BaseCommunicationManager, tenant: str,
                 global_rank: int):
        super().__init__(codec=inner.codec)
        self.inner = inner
        self.tenant = str(tenant)
        self.global_rank = int(global_rank)
        inner.add_observer(self)

    def send_message(self, msg: Message) -> None:
        if MSG_ARG_KEY_TENANT not in msg:
            msg.add_params(MSG_ARG_KEY_TENANT, self.tenant)
        if MSG_ARG_KEY_GW_SRC not in msg:
            msg.add_params(MSG_ARG_KEY_GW_SRC, self.global_rank)
        self.inner.send_message(msg)

    def receive_message(self, msg_type, msg: Message) -> None:
        self._notify(msg)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self.inner.stop_receive_message()

    def inject_local(self, msg: Message) -> None:
        self.inner.inject_local(msg)

    def supports_local_injection(self) -> bool:
        return self.inner.supports_local_injection()


class TenantLink(BaseCommunicationManager):
    """Gateway-side lane transport: receive = drain the lane's
    BoundedInbox (the mux fills it); send = translate the tenant-local
    receiver rank to the shared transport's global rank space and forward.
    The lane's reliable layer and the unmodified server manager stack on
    top of this exactly as they would on a bare transport."""

    def __init__(self, transport: BaseCommunicationManager,
                 inbox: BoundedInbox, tenant: str, base_rank: int):
        super().__init__(codec=transport.codec)
        self.transport = transport
        self.inbox = inbox
        self.tenant = str(tenant)
        self.base_rank = int(base_rank)
        self._running = False

    def send_message(self, msg: Message) -> None:
        # shallow copy: the reliable layer retransmits the same Message
        # object, so an in-place receiver rewrite would translate twice.
        # Payload values are shared by reference — no pytree copy.
        out = Message()
        out.msg_params = dict(msg.msg_params)
        out.codec = msg.codec
        r = int(msg.get_receiver_id())
        if r >= 1:
            out.msg_params[MSG_ARG_KEY_RECEIVER] = self.base_rank + r
        out.msg_params.setdefault(MSG_ARG_KEY_TENANT, self.tenant)
        self.transport.send_message(out)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self.inbox.take()
            if item is STOP:
                break
            self._notify(item)

    def stop_receive_message(self) -> None:
        self._running = False
        self.inbox.put_control(STOP)

    def inject_local(self, msg: Message) -> None:
        # control injections (the straggler-deadline timer) must serialize
        # with real traffic but never bounce off the cap
        self.inbox.put_control(msg)
