"""Typed message envelope for the edge transport.

Reference: fedml_core/distributed/communication/message.py:5-74 — a dict of
``msg_type/sender/receiver`` plus arbitrary payload keys, JSON-serialized.
Here the envelope is JSON but pytree-valued params ride as flat binary
buffers (core/serialization.py) instead of nested lists, so a model update
costs one memcpy per leaf rather than a Python-list round trip.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from fedml_tpu.core.serialization import (
    frame_pack,
    frame_unpack,
    tree_from_bytes,
    tree_to_bytes,
)

_MAGIC = b"FMSG1"

# Reliable-wire envelope (comm/reliable.py). The reliable layer stamps every
# outgoing message with a per-(sender,receiver) monotonic sequence number and
# a message id; receivers ack by id and dedup by (sender, seq). Handlers
# never read these keys — an unstamped message (local control injection, or
# a peer without the reliable layer) bypasses dedup and delivers directly.
MSG_ARG_KEY_WIRE_SEQ = "__wire_seq__"
MSG_ARG_KEY_WIRE_MID = "__wire_mid__"
# incarnation id of the sending reliable layer: a restarted rank restarts
# its seq stream at 0, so dedup keys on (sender, incarnation) — otherwise a
# rejoining worker's first messages would be swallowed as duplicates
MSG_ARG_KEY_WIRE_INC = "__wire_inc__"
# ACKs are consumed inline by ReliableLayer (comm/reliable.py:220) before
# dispatch, deliberately outside the handler registry — registering one
# would deliver acks to application code.
MSG_TYPE_WIRE_ACK = "__wire_ack__"  # fedlint: disable=protocol-exhaustiveness
# Gateway backpressure signal (comm/flow.py, distributed/gateway.py): the
# gateway answers a send that found a tenant lane over its high-water mark
# with WIRE_BUSY carrying the message id and a retry-after derived from the
# retry schedule. Consumed inline by the reliable layer (it re-arms the
# pending send's clock without burning retry attempts — busy is not dead);
# with ``terminal`` set it is an eviction/NACK: the sender abandons its
# outstanding sends to that peer and tears down. Never dispatched to
# handlers, same rationale as the ACK above.
MSG_TYPE_WIRE_BUSY = "__wire_busy__"  # fedlint: disable=protocol-exhaustiveness
# Tenant id (distributed/gateway.py): stamped by _ManagerBase.send_message
# when the manager carries a ``tenant`` attribute (like the trace context
# below), and by the gateway flow layer on layer-generated control traffic
# (acks). The gateway routes by (tenant, rank) into per-tenant lanes;
# handlers never read it, and a tenant-less federation never stamps it.
MSG_ARG_KEY_TENANT = "__tenant__"
# fedflight cross-rank capture (obs/flight.py, DESIGN.md §21): when a
# flight trigger fires on the server (watchdog escalation, quarantine),
# it broadcasts FLIGHT_DUMP to every worker BEFORE re-raising, carrying
# the deterministic incident id + rule + round, so every rank flushes its
# full-rate flight ring into the SAME incident-<id>/ bundle. Each send is
# fire-and-forget (no acks awaited — a dead peer bounds the flush at the
# transport's send deadline instead of hanging teardown); the client
# managers register a handler that routes to obs.flight.handle_dump_message.
MSG_TYPE_FLIGHT_DUMP = "__flight_dump__"
MSG_ARG_KEY_FLIGHT_ID = "__flight_id__"
MSG_ARG_KEY_FLIGHT_RULE = "__flight_rule__"
MSG_ARG_KEY_FLIGHT_ROUND = "__flight_round__"
# Trace context (fedml_tpu/obs, DESIGN.md §12): (trace id, parent span id,
# message uid), stamped by the traced send in comm/managers.py and read
# back at dispatch so a recv span links to the send span that caused it —
# across ranks, transports, and the reliable/chaos middleware. Handlers
# never read it; messages from an untraced peer simply lack the key.
MSG_ARG_KEY_TRACE_CTX = "__trace_ctx__"

# Canonical arg keys (reference message.py:15-35).
MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"
MSG_ARG_KEY_MODEL_PARAMS = "model_params"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_TRAIN_CORRECT = "train_correct"
MSG_ARG_KEY_TRAIN_ERROR = "train_error"
MSG_ARG_KEY_TRAIN_NUM = "train_num_sample"


class Message:
    """msg_type/sender/receiver envelope with arbitrary payload keys."""

    MSG_ARG_KEY_TYPE = MSG_ARG_KEY_TYPE
    MSG_ARG_KEY_SENDER = MSG_ARG_KEY_SENDER
    MSG_ARG_KEY_RECEIVER = MSG_ARG_KEY_RECEIVER
    MSG_ARG_KEY_MODEL_PARAMS = MSG_ARG_KEY_MODEL_PARAMS
    MSG_ARG_KEY_NUM_SAMPLES = MSG_ARG_KEY_NUM_SAMPLES
    MSG_ARG_KEY_CLIENT_INDEX = MSG_ARG_KEY_CLIENT_INDEX

    #: per-message codec override (None = use the transport's default).
    #: Protocols set this when one direction must not share the link codec —
    #: e.g. full-weight downlinks ride raw while topk compresses delta uplinks.
    codec: "str | None" = None

    def __init__(self, msg_type: int | str = 0, sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: Dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender_id,
            MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # -- reference API (message.py:37-66) --
    def init_from_params(self, msg_params: Dict[str, Any]) -> "Message":
        self.msg_params = dict(msg_params)
        return self

    def get_sender_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[MSG_ARG_KEY_RECEIVER]

    def get_type(self):
        return self.msg_params[MSG_ARG_KEY_TYPE]

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    # alias used throughout the reference call sites
    add = add_params

    def get_params(self) -> Dict[str, Any]:
        return self.msg_params

    def get(self, key: str, default: Any = None) -> Any:
        return self.msg_params.get(key, default)

    def __contains__(self, key: str) -> bool:
        return key in self.msg_params

    def __repr__(self) -> str:
        keys = [k for k in self.msg_params if k not in (MSG_ARG_KEY_TYPE, MSG_ARG_KEY_SENDER, MSG_ARG_KEY_RECEIVER)]
        return (
            f"Message(type={self.get_type()!r}, {self.get_sender_id()}->"
            f"{self.get_receiver_id()}, payload={keys})"
        )

    # -- wire format -------------------------------------------------------
    # frame_pack layout; pytree/array values are replaced in the header by
    # {"__blob__": i} and appended as serialized buffers; JSON-native values
    # stay inline. ``codec`` (core/compression.py: raw | q8 | topk:<ratio>)
    # optionally compresses the blobs; frames are self-describing, so a
    # receiver decodes raw and compressed blobs interchangeably.
    def to_bytes(self, codec: str = "raw") -> bytes:
        from fedml_tpu.core.compression import encode_tree

        header: Dict[str, Any] = {}
        blobs: list[bytes] = []
        for k, v in self.msg_params.items():
            if _is_jsonable(v):
                header[k] = v
            else:
                header[k] = {"__blob__": len(blobs)}
                blobs.append(tree_to_bytes(v) if codec == "raw"
                             else encode_tree(v, codec))
        return frame_pack(_MAGIC, {"h": header, "lens": [len(b) for b in blobs]}, *blobs)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "Message":
        from fedml_tpu.core.compression import decode_tree, is_compressed_frame

        meta, off = frame_unpack(_MAGIC, buf)
        blobs = []
        for n in meta["lens"]:
            blobs.append(buf[off : off + n])
            off += n
        msg = cls()
        params: Dict[str, Any] = {}
        for k, v in meta["h"].items():
            if isinstance(v, dict) and set(v) == {"__blob__"}:
                blob = blobs[v["__blob__"]]
                params[k] = (decode_tree(blob) if is_compressed_frame(blob)
                             else tree_from_bytes(blob))
            else:
                params[k] = v
        msg.msg_params = params
        return msg


def _is_jsonable(v: Any) -> bool:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return True
    if isinstance(v, (list, tuple)):
        return all(_is_jsonable(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _is_jsonable(x) for k, x in v.items())
    if isinstance(v, (np.integer, np.floating)):
        return False  # force through blob path to preserve dtype
    return False
