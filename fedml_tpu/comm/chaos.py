"""Deterministic chaos injection for the edge transports.

:class:`ChaosCommManager` wraps a bare transport and misbehaves like a real
WAN on the SEND side: it drops, duplicates, delays, and reorders messages,
and can crash-stop its rank after a configured number of sends (the
killed-process failure model the straggler-deadline machinery exists for).
With ``restart_after_s`` the crash becomes a ``crash_restart`` fate: the
rank goes silent in BOTH directions (outbound swallowed, inbound dropped,
receive loop kept alive) and revives after the configured delay — the
recovery path (rejoin, catch-up, staleness accounting), not just death.
An ``on_restart`` hook lets the protocol layer re-announce itself (the
fedbuff client sends JOIN from it).

Every fault decision is drawn from ``np.random.default_rng`` seeded by
(chaos_seed, message identity, delivery attempt) — NOT from a shared
stream — so the fate of each transmission is a pure function of the seed
and the message, independent of thread interleaving: the retransmit thread
racing the protocol thread cannot change which copies the wire eats. A
sweep over seeds (tools/chaos_sweep.py) is therefore reproducible. The
crash trigger counts LOGICAL protocol messages (first attempts of non-ack
messages), not raw wire sends: retransmit storms and ack traffic are
timing-dependent, so a raw-send trigger would move the crash point between
replays — counting protocol progress keeps the set of messages a crashed
rank managed to originate a pure function of (seed, chaos_seed), which is
what makes fedbuff's deterministic mode bit-identical replayable under
crash chaos (tests/test_fedbuff.py).

Chaos sits UNDER the reliable layer (comm/reliable.py): acks ride the same
lossy wire, so a dropped ack exercises retransmit + dedup end to end.
Config gates which faults are legal without the reliable layer on top —
drop/dup/reorder would hang or double-count the message-counting barriers
(core/config.py validation).
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Optional

import numpy as np

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_WIRE_SEQ,
    MSG_TYPE_WIRE_ACK,
    Message,
)

LOG = logging.getLogger(__name__)

CHAOS_RATE_FIELDS = ("chaos_drop", "chaos_dup", "chaos_delay_ms",
                     "chaos_reorder")


def chaos_enabled(config) -> bool:
    if any(getattr(config, f, 0.0) for f in CHAOS_RATE_FIELDS):
        return True
    return getattr(config, "chaos_crash_rank", None) is not None


class ChaosCommManager(BaseCommunicationManager, Observer):
    def __init__(
        self,
        inner: BaseCommunicationManager,
        drop: float = 0.0,
        dup: float = 0.0,
        delay_ms: float = 0.0,
        reorder: float = 0.0,
        seed: int = 0,
        rank: int = 0,
        crash_after_sends: Optional[int] = None,
        restart_after_s: Optional[float] = None,
    ):
        super().__init__(codec=inner.codec)
        self.inner = inner
        self.drop = float(drop)
        self.dup = float(dup)
        self.delay_ms = float(delay_ms)
        self.reorder = float(reorder)
        self.seed = int(seed)
        self.rank = int(rank)
        self.crash_after_sends = crash_after_sends
        self.restart_after_s = (None if restart_after_s is None
                                else float(restart_after_s))
        #: protocol layers hook this to re-announce after a crash_restart
        #: revival (e.g. the fedbuff client's JOIN); called off-thread
        self.on_restart = None
        self._sends = 0                # LOGICAL protocol messages originated
        self._occurrence: dict = {}    # fate key -> times seen (attempt idx)
        self._held = None              # reorder buffer: (msg, delay_s)
        self._crashed = False
        self._crash_fired = False      # the crash fate is single-shot
        self._lock = threading.Lock()
        # registry-backed counter view (fedml_tpu/obs) — same keys/access
        from fedml_tpu.obs import default_registry

        self.stats = default_registry().group("chaos", rank=self.rank, keys=(
            "sent", "dropped", "duplicated", "delayed",
            "reordered", "crashed_dropped", "crash_stops", "crash_restarts",
        ))
        inner.add_observer(self)

    # -- deterministic fate ------------------------------------------------
    @staticmethod
    def _fate_ident(msg: Message) -> tuple:
        """Logical identity of a transmission: retransmits of one stamped
        message share the ident and are told apart by the attempt index."""
        if msg.get_type() == MSG_TYPE_WIRE_ACK:
            from fedml_tpu.comm.reliable import KEY_ACK_SEQ

            return ("ack", msg.get_sender_id(), msg.get_receiver_id(),
                    msg.get(KEY_ACK_SEQ))
        seq = msg.get(MSG_ARG_KEY_WIRE_SEQ)
        return ("msg", msg.get_sender_id(), msg.get_receiver_id(),
                seq if seq is not None else str(msg.get_type()))

    # -- send path ---------------------------------------------------------
    def send_message(self, msg: Message) -> None:
        ident = self._fate_ident(msg)
        with self._lock:
            if self._crashed:
                self.stats["crashed_dropped"] += 1
                return
            attempt = self._occurrence.get(ident, 0)
            self._occurrence[ident] = attempt + 1
            # crash trigger counts LOGICAL protocol messages (first attempt,
            # non-ack): retransmit/ack volume is thread-timing dependent, so
            # counting raw sends would move the crash point between replays
            if ident[0] != "ack" and (attempt == 0
                                      or msg.get(MSG_ARG_KEY_WIRE_SEQ) is None):
                self._sends += 1
            crash_now = (self.crash_after_sends is not None
                         and not self._crash_fired
                         and self._sends >= self.crash_after_sends)
            if crash_now:
                # mark the crash INSIDE the lock, the instant it is
                # decided: a concurrent retransmit entering send_message
                # in the window between deciding and executing the crash
                # would otherwise dispatch in one interleaving and be
                # swallowed in another — the delivered set must be pure
                # in (seed, protocol progress). The threshold send itself
                # (this call) still goes out, then the rank goes dark.
                self._crashed = True
                self._crash_fired = True
                self._held = None
                self.stats["crash_stops"] += 1
        # per-(message, attempt) generator: the fate of attempt N of a given
        # logical message is fixed by the seed alone — thread timing between
        # the protocol and retransmit threads cannot reshuffle the draws.
        # Always burn all four draws so each decision is independent of the
        # others' rates — changing one rate never re-deals the rest.
        digest = hashlib.blake2s(repr(ident).encode(), digest_size=8).digest()
        rng = np.random.default_rng(
            [self.seed, int.from_bytes(digest, "big"), attempt])
        r_drop, r_dup, r_reorder, u_delay = rng.random(4)
        try:
            if r_drop < self.drop:
                with self._lock:   # counters race: concurrent retransmit sends
                    self.stats["dropped"] += 1
                from fedml_tpu.obs import tracer_if_enabled

                tr = tracer_if_enabled(self.rank)
                if tr is not None:
                    tr.instant("chaos_drop", cat="wire", args={
                        "peer": int(msg.get_receiver_id()),
                        "msg_type": str(msg.get_type())})
                return
            copies = 2 if r_dup < self.dup else 1
            if copies == 2:
                with self._lock:
                    self.stats["duplicated"] += 1
            delay_s = (u_delay * self.delay_ms / 1000.0) if self.delay_ms else 0.0
            for _ in range(copies):
                self._dispatch(msg, r_reorder < self.reorder, delay_s)
        finally:
            if crash_now:
                self._crash()

    def _dispatch(self, msg: Message, reorder_hit: bool, delay_s: float) -> None:
        to_send = []
        with self._lock:
            if reorder_hit and self._held is None:
                self._held = (msg, delay_s)
                self.stats["reordered"] += 1
            else:
                to_send.append((msg, delay_s))
                if self._held is not None:
                    to_send.append(self._held)
                    self._held = None
        for m, d in to_send:
            self._send_later(m, d)

    def _send_later(self, msg: Message, delay_s: float) -> None:
        if delay_s <= 0.0:
            with self._lock:
                self.stats["sent"] += 1
            self.inner.send_message(msg)
            return

        def fire():
            try:
                self.inner.send_message(msg)
            except Exception as e:  # delayed send to a gone peer: wire loss
                LOG.debug("chaos rank %d: delayed send failed (%s)",
                          self.rank, e)

        with self._lock:
            self.stats["delayed"] += 1
            self.stats["sent"] += 1
        t = threading.Timer(delay_s, fire)
        t.daemon = True
        t.start()

    def _crash(self) -> None:
        """Finish the crash-stop marked in ``send_message`` (the mark —
        ``_crashed``/counters — happens under the lock at the instant the
        fate is decided; this out-of-lock half runs after the threshold
        send completes): the in-process equivalent of kill -9, the
        failure the straggler deadline + JOIN/rejoin machinery handles.
        Permanent crash exits the receive loop; a crash_restart fate
        (``restart_after_s``) keeps the loop alive (inbound is swallowed
        while down) and arms the revival timer instead."""
        restart = self.restart_after_s
        LOG.warning("chaos: rank %d crash-stopped after %d protocol sends%s",
                    self.rank, self._sends,
                    "" if restart is None else f" (restart in {restart:g}s)")
        if restart is None:
            self.inner.stop_receive_message()
            return
        t = threading.Timer(restart, self._restart)
        t.daemon = True
        t.start()

    def _restart(self) -> None:
        """crash_restart revival: traffic flows again in both directions.
        Everything the wire carried during the outage is gone (peers'
        reliable-layer retransmits recover what their retry budgets still
        cover); ``on_restart`` lets the protocol re-announce itself."""
        with self._lock:
            if not self._crashed:
                return
            self._crashed = False
            self.stats["crash_restarts"] += 1
            cb = self.on_restart
        LOG.warning("chaos: rank %d revived (crash_restart)", self.rank)
        if cb is not None:
            try:
                cb()
            except Exception:
                LOG.exception("chaos: rank %d on_restart hook failed",
                              self.rank)

    # -- receive path ------------------------------------------------------
    def receive_message(self, msg_type, msg: Message) -> None:
        # capture under the lock (the restart timer flips the flag from
        # its own thread), then dispatch OUTSIDE it — _notify fans out to
        # handlers that may send, and sending under _lock would stall the
        # crash/restart timers against the delivery path
        with self._lock:
            crashed = self._crashed
        if crashed:
            return
        self._notify(msg)

    # -- lifecycle ---------------------------------------------------------
    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        with self._lock:
            held, self._held = self._held, None
            crashed = self._crashed
        if held is not None and not crashed:
            # a reorder hold with no follow-up send would turn reorder into
            # silent drop at shutdown; flush it instead
            try:
                self.inner.send_message(held[0])
            except Exception:
                pass
        self.inner.stop_receive_message()

    def inject_local(self, msg: Message) -> None:
        self.inner.inject_local(msg)

    def supports_local_injection(self) -> bool:
        return self.inner.supports_local_injection()


def find_chaos(comm) -> Optional[ChaosCommManager]:
    """``comm.base.find_layer`` for the chaos wrapper — protocol layers
    use it to hook ``on_restart``."""
    from fedml_tpu.comm.base import find_layer

    return find_layer(comm, ChaosCommManager)
