"""Reliable wire delivery: at-least-once transport, exact-once handlers.

The three edge transports (local/grpc/mqtt) are fire-and-forget; every
message-driven protocol in distributed/ advances rounds by MESSAGE COUNTING
(e.g. base_framework.handle_result), so one dropped message hangs a barrier
and one duplicated message double-aggregates an upload. The reference
inherits delivery guarantees from MPI; real cross-device FL (FedML
arXiv:2007.13518) runs over a wire where loss, duplication, and reordering
are the normal case.

:class:`ReliableCommManager` wraps any BaseCommunicationManager and gives
the federation at-least-once delivery with exact-once handling, with no
per-protocol changes:

- SEND stamps a per-(sender,receiver) monotonic sequence number plus a
  message id, transmits synchronously (a transport-level send failure still
  raises, so the fault-tolerant mark-dead path keeps working), and tracks
  the message until acked — a retransmit thread re-sends with exponential
  backoff up to a bounded retry count;
- RECEIVE acks every stamped message on arrival, then dedups by
  (sender, sender-incarnation, seq) inside a sliding window before
  notifying observers, so a handler sees each logical message exactly once
  no matter how many copies the wire (or the retransmitter) produced — and
  a RESTARTED rank (fresh incarnation id, seq stream back at 0) is not
  mistaken for its predecessor's duplicates;
- STOP drains: the receive loop stays alive until outstanding sends are
  acked, retries are exhausted, or a drain timeout passes — a FINISH lost
  on a flaky wire is still retransmitted after the server decides it is
  done, so no worker hangs at teardown.

Acks are fire-and-forget (a lost ack just causes a retransmit that the
dedup window absorbs). Unstamped messages — local control injections like
the straggler-deadline timer, or peers without this layer — bypass both ack
and dedup and deliver directly, which is also what makes a zero-fault
reliable run deliver bit-identical message content in identical order to
the bare transport (pinned by tests/test_chaos.py).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Dict, Optional

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_WIRE_INC,
    MSG_ARG_KEY_WIRE_MID,
    MSG_ARG_KEY_WIRE_SEQ,
    MSG_TYPE_WIRE_ACK,
    MSG_TYPE_WIRE_BUSY,
    Message,
)

LOG = logging.getLogger(__name__)

KEY_ACK_MID = "ack_mid"
KEY_ACK_SEQ = "ack_seq"
# WIRE_BUSY payload (distributed/gateway.py produces, this layer consumes):
# the message id being pushed back, the seconds the sender should hold off
# before the next attempt, and — for admission NACKs / tenant eviction —
# a terminal flag plus a human-readable reason.
KEY_BUSY_MID = "busy_mid"
KEY_BUSY_RETRY_S = "retry_after_s"
KEY_BUSY_TERMINAL = "terminal"
KEY_BUSY_REASON = "reason"

#: busy re-arms allowed per pending message before WIRE_BUSY stops
#: resetting its retry clock: a receiver that answers busy forever must
#: eventually look dead (gave_up fires, the death oracle runs) instead of
#: holding the sender in a live-lock.
MAX_BUSY_REARMS_PER_RETRY = 4


class _Pending:
    __slots__ = ("msg", "receiver", "attempts", "next_due", "in_flight",
                 "busy_rearms")

    def __init__(self, msg: Message, receiver: int, next_due: float):
        self.msg = msg
        self.receiver = receiver
        self.attempts = 0          # retransmit attempts (first send excluded)
        self.next_due = next_due
        self.in_flight = False     # a retransmit send is currently executing
        self.busy_rearms = 0       # WIRE_BUSY retry-clock resets consumed


class ReliableCommManager(BaseCommunicationManager, Observer):
    """ACK/retransmit + dedup wrapper around any transport manager."""

    def __init__(
        self,
        inner: BaseCommunicationManager,
        rank: Optional[int] = None,
        retry_base_s: float = 0.05,
        retry_cap_s: float = 1.0,
        retry_max: int = 10,
        # covers full retry exhaustion (~6.6 s at the default schedule): the
        # drain must outlive the retries it exists to host
        drain_timeout_s: float = 8.0,
        dedup_window: int = 4096,
        # idle-pair GC horizon: a (sender, incarnation) dedup window idle
        # this long is dropped (None derives ~8x the retry budget — past
        # it no bounded-retry duplicate can still arrive). Bounds state in
        # a long-lived server hosting many short peer lifetimes.
        idle_gc_s: Optional[float] = None,
    ):
        super().__init__(codec=inner.codec)
        self.inner = inner
        self.rank = int(rank if rank is not None else getattr(inner, "rank", 0))
        self.retry_base_s = float(retry_base_s)
        self.retry_cap_s = float(retry_cap_s)
        self.retry_max = int(retry_max)
        self.drain_timeout_s = float(drain_timeout_s)
        self.dedup_window = int(dedup_window)
        self._seq: Dict[int, int] = {}                 # receiver -> next seq
        self._outstanding: Dict[str, _Pending] = {}    # mid -> pending send
        # dedup state keyed on (sender, sender incarnation): a restarted
        # rank restarts its seq stream, so each incarnation deduplicates
        # independently instead of colliding with its predecessor's window
        self._seen: Dict[tuple, set] = {}
        # last-activity clock per dedup pair, for the idle GC sweep
        self._seen_touch: Dict[tuple, float] = {}
        budget = sum(self._backoff_of(retry_base_s, retry_cap_s, i)
                     for i in range(self.retry_max + 1))
        self.idle_gc_s = (float(idle_gc_s) if idle_gc_s is not None
                          else max(30.0, 8.0 * budget))
        self._next_gc = time.monotonic() + self.idle_gc_s
        self._inc = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stopping = False
        self._closed = False
        # receivers that exhausted a message's full retry budget at least
        # once and have not acked since — peer_dead counts each transition
        # into this set (once per death, not per abandoned message)
        self._dead_peers: set = set()
        # counters are a CounterGroup view over the unified registry
        # (fedml_tpu/obs): same dict-style access and key names as before,
        # but registry.snapshot("wire") now sees every live layer at once
        from fedml_tpu.obs import default_registry

        self.stats = default_registry().group("wire", rank=self.rank, keys=(
            "sent", "retransmits", "retransmit_errors",
            "gave_up", "acked", "acks_sent",
            "delivered", "dup_dropped",
            "peer_dead", "busy_backoff", "evicted",
        ))
        #: optional ``(receiver_rank, msg) -> None`` hook invoked (off the
        #: registry lock) when a message to that peer exhausts its retries —
        #: the death oracle async protocols eject crash-stopped clients by
        #: (fedbuff: the hook injects a local PEER_GAVE_UP control event)
        self.on_gave_up = None
        inner.add_observer(self)
        self._retx = threading.Thread(
            target=self._retransmit_loop, daemon=True,
            name=f"wire-retx-{self.rank}")
        self._retx.start()

    # -- send path ---------------------------------------------------------
    def send_message(self, msg: Message) -> None:
        receiver = int(msg.get_receiver_id())
        with self._cv:
            if MSG_ARG_KEY_WIRE_SEQ not in msg:
                seq = self._seq.get(receiver, 0)
                self._seq[receiver] = seq + 1
                msg.add_params(MSG_ARG_KEY_WIRE_SEQ, seq)
                msg.add_params(MSG_ARG_KEY_WIRE_MID, uuid.uuid4().hex)
                msg.add_params(MSG_ARG_KEY_WIRE_INC, self._inc)
            mid = msg.get(MSG_ARG_KEY_WIRE_MID)
            pend = _Pending(msg, receiver,
                            time.monotonic() + self._backoff(0))
            # in_flight from the start: the retry clock must not run while
            # the initial (blocking) transmit is still serializing a large
            # payload — otherwise every send slower than retry_base_s earns
            # guaranteed spurious retransmits concurrent with itself
            pend.in_flight = True
            self._outstanding[mid] = pend
            self.stats["sent"] += 1
        try:
            self.inner.send_message(msg)
        except Exception:
            # The transport itself refused the send (dead gRPC peer, closed
            # broker socket): surface it to the caller exactly like the bare
            # transport would — the fault-tolerant server's mark-dead path
            # depends on that — and stop tracking; retransmits exist for
            # SILENT loss, not for peers the transport already declared gone.
            with self._cv:
                self._outstanding.pop(mid, None)
                self._cv.notify()
            raise
        with self._cv:
            # retry clock starts at transmit COMPLETION (the ack may already
            # have landed and popped the entry — then there is nothing to arm)
            if mid in self._outstanding:
                pend.in_flight = False
                pend.next_due = time.monotonic() + self._backoff(0)
            self._cv.notify()

    @staticmethod
    def _backoff_of(base: float, cap: float, attempt: int) -> float:
        return min(float(base) * (2 ** attempt), float(cap))

    def _backoff(self, attempt: int) -> float:
        return self._backoff_of(self.retry_base_s, self.retry_cap_s, attempt)

    def _retransmit_loop(self) -> None:
        while True:
            due = []
            gave_up = []
            with self._cv:
                if self._closed:
                    return
                now = time.monotonic()
                wait = 0.25
                for mid in list(self._outstanding):
                    p = self._outstanding[mid]
                    if p.in_flight:
                        continue   # a previous attempt is still on the wire
                    if p.next_due > now:
                        wait = min(wait, p.next_due - now)
                        continue
                    p.attempts += 1
                    if p.attempts > self.retry_max:
                        self._outstanding.pop(mid)
                        self.stats["gave_up"] += 1
                        if p.receiver not in self._dead_peers:
                            # one death per peer (cleared by a later ack):
                            # the watchdog's peer_dead delta rule and every
                            # edge paradigm's pulse stream see dead workers
                            # without wiring a per-protocol hook
                            self._dead_peers.add(p.receiver)
                            self.stats["peer_dead"] += 1
                        gave_up.append(p)
                        self._cv.notify_all()
                        LOG.warning(
                            "rank %d: message %r to %d unacked after %d "
                            "retries; giving up", self.rank,
                            p.msg.get_type(), p.receiver, self.retry_max)
                        continue
                    p.next_due = now + self._backoff(p.attempts)
                    p.in_flight = True
                    due.append(p)
                if now >= self._next_gc:
                    self._gc_idle_pairs(now)
                    self._next_gc = now + max(0.05, self.idle_gc_s / 4.0)
                if not due and not gave_up:
                    self._cv.wait(timeout=wait)
                    continue
            for p in gave_up:
                # fedflight peer_dead trigger (obs/flight.py): a message
                # just exhausted its full retry budget — dump the incident
                # bundle while the recent rounds are still in the rings.
                # Off-lock (bundle IO must not stall acks/retransmits) and
                # fully guarded: a recorder failure must never take down
                # the retransmit thread. No-op while the recorder is off
                # or the peer_dead trigger is not armed.
                try:
                    from fedml_tpu.obs import flight as _flight

                    rec = _flight.recorder_if_enabled()
                    if rec is not None:
                        rec.trigger(
                            "peer_dead",
                            int(p.msg.get("round_idx", 0) or 0),
                            kind="peer_dead",
                            reason=(f"rank {self.rank}: peer {p.receiver} "
                                    f"unacked after {self.retry_max} "
                                    "retries"))
                except Exception:
                    LOG.exception("rank %d: flight peer_dead dump failed",
                                  self.rank)
                cb = self.on_gave_up
                if cb is not None:
                    try:
                        cb(p.receiver, p.msg)
                    except Exception:
                        LOG.exception("rank %d: on_gave_up hook failed",
                                      self.rank)
            # one thread per due message: a blocking transport (gRPC
            # wait_for_ready on a dead peer) must not starve retransmits to
            # LIVE peers — that starvation is exactly how a lost FINISH to
            # one worker hangs the federation while another worker's corpse
            # blocks the queue. in_flight keeps a wedged send from stacking
            # repeat attempts for the same message.
            for p in due:
                threading.Thread(target=self._retransmit_one, args=(p,),
                                 daemon=True,
                                 name=f"wire-retx-{self.rank}-send").start()

    def _retransmit_one(self, p: _Pending) -> None:
        from fedml_tpu.obs import tracer_if_enabled

        tr = tracer_if_enabled(self.rank)
        if tr is not None:
            # tagged with the SAME message uid as the original send span, so
            # the analyzer collapses a retransmit storm onto its one logical
            # wire edge instead of counting phantom messages
            from fedml_tpu.comm.message import MSG_ARG_KEY_TRACE_CTX

            ctx = p.msg.get(MSG_ARG_KEY_TRACE_CTX)
            # p was published into _outstanding under _cv before this
            # thread was spawned (Thread.start() is the happens-before
            # edge) and the entry stays pinned in_flight=True until this
            # thread re-enters the lock below — attempts cannot move here.
            tr.instant("retransmit", cat="wire", args={
                # fedlint: disable=check-then-act
                "peer": p.receiver, "attempt": p.attempts,
                **({"mid": ctx[2]} if ctx else {})})
        key = "retransmits"
        try:
            self.inner.send_message(p.msg)
        except Exception as e:
            key = "retransmit_errors"
            LOG.debug("rank %d: retransmit to %s failed (%s)",
                      self.rank, p.receiver, e)
        finally:
            # counter bumped under the lock: these threads run concurrently
            with self._cv:
                self.stats[key] += 1
                p.in_flight = False
                self._cv.notify_all()

    # -- receive path (Observer of the inner transport) --------------------
    def receive_message(self, msg_type, msg: Message) -> None:
        if msg_type == MSG_TYPE_WIRE_ACK:
            with self._cv:
                p = self._outstanding.pop(msg.get(KEY_ACK_MID), None)
                if p is not None:
                    self.stats["acked"] += 1
                    # an ack is proof of life: a peer that died (retry
                    # exhaustion) and came back counts as a NEW death next
                    # time instead of being forever-dead
                    self._dead_peers.discard(p.receiver)
                    self._cv.notify_all()
            return
        if msg_type == MSG_TYPE_WIRE_BUSY:
            self._handle_busy(msg)
            return
        seq = msg.get(MSG_ARG_KEY_WIRE_SEQ)
        if seq is None:
            # unstamped: local control injection (deadline timer) or a peer
            # without the reliable layer — deliver directly
            self._notify(msg)
            return
        sender = int(msg.get_sender_id())
        with self._lock:
            stopping = self._stopping
        # ack BEFORE dispatch: the ack acknowledges receipt into the dedup
        # layer (at-least-once), not handler completion. Once we are
        # draining, stop acking: the peer that sent this is usually tearing
        # down too, and a blocking transport (gRPC wait_for_ready) would
        # pin the receive thread on a dead endpoint for its full send
        # timeout per late retransmit — the sender's retries are bounded,
        # so an unacked tail message resolves itself.
        if not stopping:
            ack = Message(MSG_TYPE_WIRE_ACK, self.rank, sender)
            ack.add_params(KEY_ACK_MID, msg.get(MSG_ARG_KEY_WIRE_MID))
            ack.add_params(KEY_ACK_SEQ, int(seq))
            try:
                self.inner.send_message(ack)
                # CounterGroup's documented contract (obs/registry.py) is
                # lock-free single-dict-store monotonic counters, and the
                # transport's single receive thread is the only writer of
                # the receive-side keys — taking _lock here would
                # serialize delivery against the retransmit sweep.
                # fedlint: disable=unguarded-shared-write
                self.stats["acks_sent"] += 1
            except Exception as e:  # lost == dropped ack: retransmit covers it
                LOG.debug("rank %d: ack to %d failed (%s)", self.rank, sender, e)
        with self._lock:
            dup = self._is_dup_and_mark(
                (sender, msg.get(MSG_ARG_KEY_WIRE_INC)), int(seq))
        if dup:
            # receive-thread-only counter, same contract as acks_sent above
            # fedlint: disable=unguarded-shared-write
            self.stats["dup_dropped"] += 1
            return
        # receive-thread-only counter, same contract as acks_sent above
        # fedlint: disable=unguarded-shared-write
        self.stats["delivered"] += 1
        self._notify(msg)

    def _handle_busy(self, msg: Message) -> None:
        """Gateway push-back consumer. Non-terminal WIRE_BUSY re-arms the
        pending message's retry clock at the receiver-suggested delay
        WITHOUT burning a retry (busy != dead) — bounded by
        MAX_BUSY_REARMS_PER_RETRY so a forever-busy receiver eventually
        falls through to normal retry exhaustion and the dead-peer oracle.
        Terminal WIRE_BUSY (admission NACK / tenant eviction) abandons all
        outstanding sends and stops the layer: the federation this worker
        belongs to no longer exists at the gateway."""
        if msg.get(KEY_BUSY_TERMINAL):
            with self._cv:
                if self._outstanding:
                    self._outstanding.clear()
                self.stats["evicted"] += 1
                self._cv.notify_all()
            LOG.warning("rank %d: evicted by receiver (%s)", self.rank,
                        msg.get(KEY_BUSY_REASON) or "no reason given")
            self.stop_receive_message()
            return
        retry_after = float(msg.get(KEY_BUSY_RETRY_S) or
                            self.retry_base_s * 4.0)
        with self._cv:
            p = self._outstanding.get(msg.get(KEY_BUSY_MID))
            if (p is not None and p.busy_rearms
                    < self.retry_max * MAX_BUSY_REARMS_PER_RETRY):
                p.busy_rearms += 1
                p.attempts = 0
                p.next_due = time.monotonic() + retry_after
                self.stats["busy_backoff"] += 1
                self._cv.notify_all()

    def _is_dup_and_mark(self, sender: tuple, seq: int) -> bool:
        self._seen_touch[sender] = time.monotonic()
        seen = self._seen.setdefault(sender, set())
        if seq in seen:
            return True
        seen.add(seq)
        if len(seen) > self.dedup_window:
            # bounded memory: anything this far behind the high-water mark
            # can no longer be retransmitted (retries are bounded)
            cutoff = max(seen) - self.dedup_window
            self._seen[sender] = {s for s in seen if s >= cutoff}
        return False

    def _gc_idle_pairs(self, now: float) -> None:
        """Drop dedup windows for (sender, incarnation) pairs idle past the
        GC horizon (runs under the lock, from the retransmit loop). Safe
        because retries are bounded: past ~the retry budget no duplicate of
        an already-seen message can still arrive, so forgetting the window
        cannot re-admit one. A long-lived gateway lane hosting thousands of
        short worker lifetimes keeps O(live peers) state, not O(ever-seen
        incarnations)."""
        cutoff = now - self.idle_gc_s
        for pair in [p for p, t in self._seen_touch.items() if t < cutoff]:
            self._seen.pop(pair, None)
            self._seen_touch.pop(pair, None)

    # -- lifecycle ---------------------------------------------------------
    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        # Drain before stopping the inner loop: stop is usually called from
        # a handler ON the receive thread, so the wait runs on a helper.
        with self._cv:
            if self._stopping:
                return
            self._stopping = True
        threading.Thread(target=self._drain_and_stop, daemon=True,
                         name=f"wire-drain-{self.rank}").start()

    def _drain_and_stop(self) -> None:
        deadline = time.monotonic() + self.drain_timeout_s
        with self._cv:
            while self._outstanding and time.monotonic() < deadline:
                self._cv.wait(timeout=0.05)
            self._closed = True
            self._cv.notify_all()
        self.inner.stop_receive_message()

    def inject_local(self, msg: Message) -> None:
        self.inner.inject_local(msg)

    def supports_local_injection(self) -> bool:
        return self.inner.supports_local_injection()


def retry_schedule(config) -> tuple[float, float, int]:
    """(base_s, cap_s, retry_max) from the config knobs. The cap scales
    with the base (20x — the default pair 0.05/1.0 preserved), so one base
    knob retunes the whole schedule."""
    base = float(getattr(config, "wire_retry_base_s", 0.05) or 0.05)
    return base, 20.0 * base, int(getattr(config, "wire_retry_max", 10) or 10)


def retry_budget_s(config) -> float:
    """Total worst-case backoff before a message gives up under ``config``'s
    retry schedule — the wire's detection latency for a dead peer. Probe
    and keepalive cadences derive from it so a liveness check never
    re-sends while the original could still legitimately deliver."""
    base, cap, retry_max = retry_schedule(config)
    return float(sum(min(base * (2 ** i), cap) for i in range(retry_max + 1)))


def build_wire_stack(comm: BaseCommunicationManager, config,
                     rank: int) -> BaseCommunicationManager:
    """Wrap a bare transport per config: chaos injection innermost (it IS
    the wire), the reliable layer on top (it recovers what chaos breaks)."""
    from fedml_tpu.comm.chaos import ChaosCommManager, chaos_enabled

    if chaos_enabled(config):
        crash_after = (config.chaos_crash_after
                       if getattr(config, "chaos_crash_rank", None) == rank
                       else None)
        comm = ChaosCommManager(
            comm,
            drop=getattr(config, "chaos_drop", 0.0),
            dup=getattr(config, "chaos_dup", 0.0),
            delay_ms=getattr(config, "chaos_delay_ms", 0.0),
            reorder=getattr(config, "chaos_reorder", 0.0),
            seed=getattr(config, "chaos_seed", 0),
            rank=rank,
            crash_after_sends=crash_after,
            restart_after_s=(getattr(config, "chaos_crash_restart_s", None)
                             if crash_after is not None else None),
        )
    if getattr(config, "wire_reliable", False):
        base, cap, retry_max = retry_schedule(config)
        comm = ReliableCommManager(
            comm, rank=rank, retry_base_s=base, retry_cap_s=cap,
            retry_max=retry_max,
            # the drain exists to host retry exhaustion: scale it with the
            # schedule instead of racing a fixed 8 s against a retuned one
            drain_timeout_s=retry_budget_s(config) + 0.5)
    return comm


def wire_wrap_factory(config):
    """``(rank, comm) -> comm`` wrapper for run_ranks, or None when neither
    the reliable layer nor chaos injection is configured (zero overhead —
    the bare transports are returned untouched)."""
    from fedml_tpu.comm.chaos import chaos_enabled

    if not (getattr(config, "wire_reliable", False) or chaos_enabled(config)):
        return None
    return lambda rank, comm: build_wire_stack(comm, config, rank)
