"""Minimal MQTT 3.1.1 broker over real TCP sockets.

The reference's MQTT transport ran against a live broker on :1883
(fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14-126);
this image has no broker and no paho, so until round 4 the backend had only
ever exercised an in-process fake. This broker implements the slice of
MQTT 3.1.1 the federation transport needs — CONNECT/CONNACK,
SUBSCRIBE/SUBACK, PUBLISH QoS 0, PINGREQ/PINGRESP, DISCONNECT — over plain
TCP, so the backend's topic scheme and binary Message framing run over a
REAL socket (wire framing, partial reads, concurrent publishers) both in
tests and in deployments without an external broker.

Scope: exact-match topic filters only (the federation's per-pair topics
never use wildcards), QoS 0 only (the reference manager publishes QoS 0),
no retained messages, no will, no auth — each documented as out of scope
rather than half-implemented.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading

log = logging.getLogger(__name__)


def _hard_close(sock: socket.socket) -> None:
    """shutdown() before close(): close() alone on a socket another thread
    is blocked in recv() on neither wakes that thread nor sends FIN (the fd
    stays referenced), leaving the connection ESTABLISHED and the port
    unreleasable — shutdown tears the TCP stream down immediately."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass

# control packet types (MQTT 3.1.1 §2.2.1)
CONNECT, CONNACK = 0x1, 0x2
PUBLISH = 0x3
SUBSCRIBE, SUBACK = 0x8, 0x9
UNSUBSCRIBE, UNSUBACK = 0xA, 0xB
PINGREQ, PINGRESP = 0xC, 0xD
DISCONNECT = 0xE


def encode_varlen(n: int) -> bytes:
    """Remaining-length varint (§2.2.3)."""
    out = bytearray()
    while True:
        d, n = n & 0x7F, n >> 7
        out.append(d | (0x80 if n else 0))
        if not n:
            return bytes(out)


def read_varlen(recv) -> int:
    mult, val = 1, 0
    for _ in range(4):
        b = recv(1)[0]
        val += (b & 0x7F) * mult
        if not b & 0x80:
            return val
        mult *= 128
    raise ValueError("malformed remaining length")


def mqtt_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def publish_packet(topic: str, payload: bytes) -> bytes:
    body = mqtt_str(topic) + payload
    return bytes([PUBLISH << 4]) + encode_varlen(len(body)) + body


class _Conn:
    def __init__(self, broker: "MqttBroker", sock: socket.socket, addr):
        self.broker = broker
        self.sock = sock
        self.addr = addr
        self.client_id = ""
        self.topics: set[str] = set()
        self._wlock = threading.Lock()

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def send_packet(self, pkt: bytes) -> None:
        with self._wlock:
            self.sock.sendall(pkt)

    def serve(self) -> None:
        try:
            while True:
                hdr = self._recv_exact(1)[0]
                ptype, flags = hdr >> 4, hdr & 0xF
                length = read_varlen(self._recv_exact)
                body = self._recv_exact(length) if length else b""
                if ptype == CONNECT:
                    # protocol name/level/flags/keepalive, then client id
                    off = 2 + body[1]  # skip protocol name
                    off += 4           # level + connect flags + keepalive
                    cid_len = struct.unpack(">H", body[off:off + 2])[0]
                    self.client_id = body[off + 2:off + 2 + cid_len].decode()
                    # session-present 0, return code 0
                    self.send_packet(bytes([CONNACK << 4, 2, 0, 0]))
                elif ptype == SUBSCRIBE:
                    pid = body[:2]
                    off, granted = 2, bytearray()
                    while off < len(body):
                        tlen = struct.unpack(">H", body[off:off + 2])[0]
                        topic = body[off + 2:off + 2 + tlen].decode()
                        off += 2 + tlen + 1  # + requested QoS byte
                        self.topics.add(topic)
                        self.broker.subscribe(topic, self)
                        granted.append(0)    # granted QoS 0
                    self.send_packet(bytes([SUBACK << 4])
                                     + encode_varlen(2 + len(granted))
                                     + pid + bytes(granted))
                elif ptype == UNSUBSCRIBE:
                    pid = body[:2]
                    off = 2
                    while off < len(body):
                        tlen = struct.unpack(">H", body[off:off + 2])[0]
                        topic = body[off + 2:off + 2 + tlen].decode()
                        off += 2 + tlen
                        self.topics.discard(topic)
                        self.broker.unsubscribe(topic, self)
                    self.send_packet(bytes([UNSUBACK << 4, 2]) + pid)
                elif ptype == PUBLISH:
                    qos = (flags >> 1) & 0x3
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode()
                    off = 2 + tlen + (2 if qos else 0)  # skip pid at QoS>0
                    self.broker.route(topic, body[off:])
                elif ptype == PINGREQ:
                    self.send_packet(bytes([PINGRESP << 4, 0]))
                elif ptype == DISCONNECT:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            self.broker.drop(self)


class MqttBroker:
    """``with MqttBroker(port) as b:`` — serves until close()."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._subs: dict[str, list[_Conn]] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen(64)
        self._conns: set[_Conn] = set()
        self._running = True
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="mqtt-broker", daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while self._running:
            try:
                sock, addr = self._srv.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(self, sock, addr)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=conn.serve, daemon=True,
                             name=f"mqtt-conn-{addr[1]}").start()

    def subscribe(self, topic: str, conn: _Conn):
        with self._lock:
            subs = self._subs.setdefault(topic, [])
            if conn not in subs:
                subs.append(conn)

    def unsubscribe(self, topic: str, conn: _Conn):
        with self._lock:
            if conn in self._subs.get(topic, []):
                self._subs[topic].remove(conn)

    def route(self, topic: str, payload: bytes):
        pkt = publish_packet(topic, payload)
        with self._lock:
            subs = list(self._subs.get(topic, []))
        for conn in subs:
            try:
                conn.send_packet(pkt)
            except OSError:
                self.drop(conn)

    def drop(self, conn: _Conn):
        with self._lock:
            self._conns.discard(conn)
            for subs in self._subs.values():
                if conn in subs:
                    subs.remove(conn)
        _hard_close(conn.sock)

    def close(self):
        self._running = False
        # the accept thread blocks in accept(): plain close() leaves the fd
        # referenced and the zombie listener keeps accepting (it would steal
        # reconnections from a restarted broker on the same port) — shutdown
        # wakes accept() with an error first
        _hard_close(self._srv)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            _hard_close(c.sock)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
