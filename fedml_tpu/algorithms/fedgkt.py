"""FedGKT — group knowledge transfer (feature/logit exchange, no weight
exchange).

Counterpart of reference fedml_api/distributed/fedgkt/: clients train a small
edge net with CE + alpha*KL(server logits) (GKTClientTrainer.py:66-89), then
run an inference pass extracting per-sample feature maps + soft logits
(GKTClientTrainer.py:108-120); the server trains the big net on the union of
client features with CE + alpha*KL(client logits) (GKTServerTrainer.py:110+)
and returns per-sample global logits to each client.

TPU re-design (vs the reference's MPI message loop + DataParallel server,
GKTServerTrainer.py:28-29):

- the WHOLE client phase — local distillation training of every client's
  private model plus the feature/logit extraction pass — is one jitted
  program: a ``vmap`` over a stacked pytree of per-client variables,
- the server phase consumes the stacked features [C, n_pad, h, w, f] as one
  dense dataset — large MXU-friendly batches instead of per-client loops,
- the "exchange" is just arrays staying on device between the two phases;
  nothing is serialized, and per-sample alignment replaces the reference's
  per-batch-index dicts (message_def.py:17-24).

Per-sample alignment note: the reference keys server logits by batch index
and never reshuffles between rounds (so the KL target stays aligned); here
logits are carried per SAMPLE and permuted together with x/y inside each
epoch, which is strictly more faithful under reshuffling.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.rng import round_key, seed_everything
from fedml_tpu.core.tasks import int_cross_entropy
from fedml_tpu.data import FedDataset
from fedml_tpu.models.gkt import GKTPair, create_gkt_pair, gkt_blocks_from_names

log = logging.getLogger(__name__)


def kl_distill(student_logits, teacher_logits, mask, temperature: float):
    """Masked batchmean KL(teacher || student) with temperature, matching
    reference utils.KL_Loss (fedgkt/utils.py:75-90): T^2 * KLDiv(
    log_softmax(student/T), softmax(teacher/T)+1e-7)."""
    T = temperature
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / T, axis=-1)
    t = jax.nn.softmax(teacher_logits.astype(jnp.float32) / T, axis=-1) + 1e-7
    per = (T * T) * jnp.sum(t * (jnp.log(t) - s), axis=-1)
    m = mask.astype(jnp.float32)
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


def masked_ce(logits, labels, mask):
    per = int_cross_entropy(logits, labels)
    m = mask.astype(jnp.float32)
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


def _gkt_optimizer(name: str, lr: float, wd: float) -> optax.GradientTransformation:
    """Reference GKT optimizers: SGD(momentum=0.9, nesterov) or
    Adam(amsgrad, wd=1e-4) — GKTClientTrainer.py:31-36."""
    if name.lower() == "sgd":
        return optax.chain(
            optax.add_decayed_weights(wd) if wd else optax.identity(),
            optax.sgd(lr, momentum=0.9, nesterov=True),
        )
    return optax.chain(optax.add_decayed_weights(1e-4), optax.amsgrad(lr))


class FedGKTAPI:
    """Standalone-simulation FedGKT (all clients participate every round,
    like the reference's one-MPI-rank-per-client deployment)."""

    def __init__(
        self,
        dataset: FedDataset,
        config: FedConfig,
        pair: Optional[GKTPair] = None,
        client_blocks: Optional[int] = None,
        server_blocks_per_stage: Optional[int] = None,
        server_mesh=None,
    ):
        # None -> honor the reference's --model_client/--model_server names
        # (resnet8 / resnet56_server by default, i.e. 3 and 9 blocks).
        # Derived lazily: explicit block counts must keep working for model
        # names the depth parser cannot read.
        if client_blocks is None or server_blocks_per_stage is None:
            derived = gkt_blocks_from_names(
                config.model_client, config.model_server)
            if client_blocks is None:
                client_blocks = derived[0]
            if server_blocks_per_stage is None:
                server_blocks_per_stage = derived[1]
        self.dataset = dataset
        self.config = config
        # optional ('batch',) mesh for the server phase — the TPU counterpart
        # of the reference's nn.DataParallel 4-GPU server
        # (GKTServerTrainer.py:28-29): GSPMD shards the feature batches and
        # all-reduces grads/BN moments; results match single-device exactly
        self.server_mesh = server_mesh
        input_shape = tuple(dataset.train_x.shape[2:])
        self.pair = pair or create_gkt_pair(
            dataset.class_num,
            input_shape=input_shape,
            client_blocks=client_blocks,
            server_blocks_per_stage=server_blocks_per_stage,
            dtype=jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32,
        )
        self.C = dataset.num_clients
        self.n_pad = int(dataset.train_x.shape[1])
        if self.n_pad % config.batch_size:
            raise ValueError("n_pad must be a multiple of batch_size")
        self.root_key = seed_everything(config.seed)

        ckeys = jax.random.split(jax.random.fold_in(self.root_key, 1), self.C)
        self.client_vars = jax.vmap(self.pair.client.init)(ckeys)
        self.server_vars = self.pair.server.init(jax.random.fold_in(self.root_key, 2))

        self._ctx = _gkt_optimizer(config.client_optimizer, config.lr, config.wd)
        self._stx = _gkt_optimizer(config.client_optimizer, config.lr, config.wd)
        self.client_opt = jax.vmap(lambda v: self._ctx.init(v["params"]))(self.client_vars)
        self.server_opt = self._stx.init(self.server_vars["params"])

        self.server_logits = jnp.zeros(
            (self.C, self.n_pad, dataset.class_num), jnp.float32
        )
        self._test_shards = self._build_test_shards()
        self._client_phase = self._build_client_phase()
        self._server_phase = self._build_server_phase()
        self._eval_fn = self._build_eval()
        self.history: list[dict] = []

    # ---------------------------------------------------------- test shards
    def _build_test_shards(self):
        """Per-client test shards [C, n, ...]: the reference has every client
        extract features of ITS local test set for server-side eval
        (GKTClientTrainer.py:108+, message_def.py feature_test/labels_test);
        with only a global pool we split it evenly across clients."""
        d = self.dataset
        if d.test_x_local is not None:
            return (
                np.asarray(d.test_x_local),
                np.asarray(d.test_y_local),
                np.asarray(d.test_mask_local),
            )
        n = len(d.test_x)
        per = -(-n // self.C)
        pad = per * self.C - n
        xi = np.concatenate([d.test_x, np.repeat(d.test_x[:1], pad, axis=0)], axis=0)
        yi = np.concatenate([d.test_y, np.repeat(d.test_y[:1], pad, axis=0)], axis=0)
        mi = np.concatenate([d.test_mask, np.zeros(pad, np.float32)])
        return (
            xi.reshape((self.C, per) + xi.shape[1:]),
            yi.reshape((self.C, per) + yi.shape[1:]),
            mi.reshape((self.C, per)),
        )

    # --------------------------------------------------------- client phase
    def _build_client_train_one(self):
        """One client's distillation training + extraction pass as a pure
        function — vmapped over the cohort by the simulation's client phase,
        jitted standalone by the message-driven edge client
        (distributed/fedgkt_edge.py), so both paradigms run the identical
        per-client program."""
        pair, cfg = self.pair, self.config
        tx = self._ctx
        bs = cfg.batch_size
        n_pad = self.n_pad
        steps = n_pad // bs
        epochs = cfg.epochs
        temperature = cfg.temperature
        grad_clip = cfg.grad_clip

        def train_one(cvars, copt, x, y, mask, count, slogits, kl_w, rng):
            steps_real = jnp.ceil(count.astype(jnp.float32) / bs).astype(jnp.int32)

            def epoch_fn(carry, ekey):
                cvars, copt = carry
                perm = jax.random.permutation(ekey, n_pad)
                order = perm[jnp.argsort(-mask[perm], stable=True)]
                xs = x[order].reshape((steps, bs) + x.shape[1:])
                ys = y[order].reshape((steps, bs))
                ms = mask[order].reshape((steps, bs))
                ts = slogits[order].reshape((steps, bs, slogits.shape[-1]))

                def step_fn(carry, batch):
                    cvars, copt = carry
                    bx, by, bm, bt, step_idx = batch
                    live = (step_idx < steps_real).astype(jnp.float32)

                    def loss_fn(p):
                        vin = dict(cvars)
                        vin["params"] = p
                        (logits, _), new_vars = pair.client.apply_train(vin, bx)
                        l = masked_ce(logits, by, bm)
                        l = l + kl_w * kl_distill(logits, bt, bm, temperature)
                        return l, new_vars

                    (l, new_vars), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                        cvars["params"]
                    )
                    if grad_clip:
                        gn = optax.global_norm(grads)
                        scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gn, 1e-12))
                        grads = jax.tree.map(lambda g: g * scale, grads)
                    updates, new_opt = tx.update(grads, copt, cvars["params"])
                    params = optax.apply_updates(cvars["params"], updates)

                    def freeze(new, old):
                        return jax.tree.map(
                            lambda n, o: live * n + (1.0 - live) * o
                            if jnp.issubdtype(n.dtype, jnp.floating)
                            else jnp.where(live > 0, n, o),
                            new, old,
                        )

                    new_opt = freeze(new_opt, copt)
                    out_vars = dict(freeze(
                        {k: v for k, v in new_vars.items() if k != "params"},
                        {k: v for k, v in cvars.items() if k != "params"},
                    ))
                    out_vars["params"] = freeze(params, cvars["params"])
                    return (out_vars, new_opt), l * live

                (cvars, copt), losses = jax.lax.scan(
                    step_fn, (cvars, copt),
                    (xs, ys, ms, ts, jnp.arange(steps)),
                )
                loss = jnp.sum(losses) / jnp.maximum(steps_real.astype(jnp.float32), 1.0)
                return (cvars, copt), loss

            (cvars, copt), ep_losses = jax.lax.scan(
                epoch_fn, (cvars, copt), jax.random.split(rng, epochs)
            )
            # extraction pass in eval mode (GKTClientTrainer.py:108-120)
            logits, feats = pair.client.apply_eval(cvars, x)
            return cvars, copt, feats, logits, ep_losses[-1]

        return train_one

    def _build_client_phase(self):
        train_one = self._build_client_train_one()

        @jax.jit
        def client_phase(cvars_stacked, copt_stacked, x, y, mask, counts, slogits, kl_w, rng):
            rngs = jax.random.split(rng, x.shape[0])
            return jax.vmap(train_one, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0))(
                cvars_stacked, copt_stacked, x, y, mask, counts, slogits, kl_w, rngs
            )

        return client_phase

    # --------------------------------------------------------- server phase
    def _build_server_phase(self):
        pair, cfg = self.pair, self.config
        tx = self._stx
        # server batch: union dataset is C× bigger, keep batches MXU-sized
        bs = cfg.batch_size
        temperature = cfg.temperature
        alpha = cfg.alpha_distill
        epochs = max(cfg.epochs_server, 1)
        C, n_pad = self.C, self.n_pad
        N = C * n_pad
        steps = N // bs

        def server_phase(svars, sopt, feats, y, mask, clogits, rng):
            fx = feats.reshape((N,) + feats.shape[2:])
            fy = y.reshape((N,))
            fm = mask.reshape((N,))
            fl = clogits.reshape((N, clogits.shape[-1]))
            n_real = jnp.sum(fm)
            steps_real = jnp.ceil(n_real / bs).astype(jnp.int32)

            def epoch_fn(carry, ekey):
                svars, sopt = carry
                perm = jax.random.permutation(ekey, N)
                order = perm[jnp.argsort(-fm[perm], stable=True)]
                xs = fx[order].reshape((steps, bs) + fx.shape[1:])
                ys = fy[order].reshape((steps, bs))
                ms = fm[order].reshape((steps, bs))
                ts = fl[order].reshape((steps, bs, fl.shape[-1]))

                def step_fn(carry, batch):
                    svars, sopt = carry
                    bx, by, bm, bt, step_idx = batch
                    live = (step_idx < steps_real).astype(jnp.float32)

                    def loss_fn(p):
                        vin = dict(svars)
                        vin["params"] = p
                        logits, new_vars = pair.server.apply_train(vin, bx)
                        l = masked_ce(logits, by, bm)
                        l = l + alpha * kl_distill(logits, bt, bm, temperature)
                        return l, new_vars

                    (l, new_vars), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                        svars["params"]
                    )
                    updates, new_opt = tx.update(grads, sopt, svars["params"])
                    params = optax.apply_updates(svars["params"], updates)

                    def freeze(new, old):
                        return jax.tree.map(
                            lambda n, o: live * n + (1.0 - live) * o
                            if jnp.issubdtype(n.dtype, jnp.floating)
                            else jnp.where(live > 0, n, o),
                            new, old,
                        )

                    new_opt = freeze(new_opt, sopt)
                    out_vars = dict(freeze(
                        {k: v for k, v in new_vars.items() if k != "params"},
                        {k: v for k, v in svars.items() if k != "params"},
                    ))
                    out_vars["params"] = freeze(params, svars["params"])
                    return (out_vars, new_opt), l * live

                (svars, sopt), losses = jax.lax.scan(
                    step_fn, (svars, sopt),
                    (xs, ys, ms, ts, jnp.arange(steps)),
                )
                loss = jnp.sum(losses) / jnp.maximum(steps_real.astype(jnp.float32), 1.0)
                return (svars, sopt), loss

            (svars, sopt), ep_losses = jax.lax.scan(
                epoch_fn, (svars, sopt), jax.random.split(rng, epochs)
            )
            # fresh global logits for every client sample, batched scan
            def logits_body(_, bx):
                return None, pair.server.apply_eval(svars, bx)

            _, out = jax.lax.scan(
                logits_body, None, fx.reshape((steps, bs) + fx.shape[1:])
            )
            new_slogits = out.reshape((C, n_pad, out.shape[-1]))
            return svars, sopt, new_slogits, ep_losses[-1]

        if self.server_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self.server_mesh
            axis = mesh.axis_names[0]
            repl = NamedSharding(mesh, P())
            shard = NamedSharding(mesh, P(axis))  # client axis of the stacks
            return jax.jit(
                server_phase,
                in_shardings=(repl, repl, shard, shard, shard, shard, repl),
                out_shardings=(repl, repl, shard, repl),
            )
        return jax.jit(server_phase)

    # ----------------------------------------------------------------- eval
    def _build_eval(self):
        pair = self.pair

        @jax.jit
        def evaluate(cvars_stacked, svars, tx_, ty_, tm_):
            def one(cvars, x):
                _, feats = pair.client.apply_eval(cvars, x)
                return pair.server.apply_eval(svars, feats)

            logits = jax.vmap(one)(cvars_stacked, tx_)  # [C, n, classes]
            pred = jnp.argmax(logits, axis=-1)
            m = tm_.astype(jnp.float32)
            per = int_cross_entropy(logits, ty_)
            return {
                "correct": jnp.sum((pred == ty_).astype(jnp.float32) * m),
                "loss_sum": jnp.sum(per * m),
                "count": jnp.sum(m),
            }

        return evaluate

    # ---------------------------------------------------------------- train
    def train(self) -> dict:
        d, cfg = self.dataset, self.config
        x = jnp.asarray(d.train_x)
        y = jnp.asarray(d.train_y)
        mask = jnp.asarray(d.train_mask)
        counts = jnp.asarray(d.train_counts)
        tx_, ty_, tm_ = (jnp.asarray(a) for a in self._test_shards)
        last = {}
        for rnd in range(cfg.comm_round):
            kl_w = jnp.float32(0.0 if rnd == 0 else cfg.alpha_distill)
            rkey = round_key(self.root_key, rnd)
            (self.client_vars, self.client_opt, feats, clogits, closs) = (
                self._client_phase(
                    self.client_vars, self.client_opt, x, y, mask, counts,
                    self.server_logits, kl_w, jax.random.fold_in(rkey, 1),
                )
            )
            (self.server_vars, self.server_opt, self.server_logits, sloss) = (
                self._server_phase(
                    self.server_vars, self.server_opt, feats, y, mask, clogits,
                    jax.random.fold_in(rkey, 2),
                )
            )
            if rnd % cfg.frequency_of_the_test == 0 or rnd == cfg.comm_round - 1:
                sums = jax.device_get(
                    self._eval_fn(self.client_vars, self.server_vars, tx_, ty_, tm_)
                )
                acc = float(sums["correct"]) / max(float(sums["count"]), 1.0)
                loss = float(sums["loss_sum"]) / max(float(sums["count"]), 1.0)
                last = {
                    "round": rnd,
                    "Test/Acc": acc,
                    "Test/Loss": loss,
                    "Train/ClientLoss": float(jnp.mean(closs)),
                    "Train/ServerLoss": float(sloss),
                }
                self.history.append(last)
                log.info("GKT round %d: test acc %.4f", rnd, acc)
        return last
