"""FedAGC — adaptive-gradient-clipping aggregation (fork-specific algorithm).

Counterpart of the fork's fedml_api/standalone/fedagc/silo_fedagc.py: each
client's round update is clipped unit-wise relative to the global weights
(NFNet-style AGC, silo_fedagc.py:12-29) before the weighted average
(SiloFedAGC._aggregate :50-69). The clip math lives in
fedml_tpu.core.aggregation.agc_clip_update.
"""

from __future__ import annotations

import jax

from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI, FedAvgAPI
from fedml_tpu.core.aggregation import agc_clip_update
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.parallel.local import LocalResult


class FedAGCAPI(FedAvgAPI):
    #: AGC clipping ratio lambda (fork default 1e-2)
    clipping: float = 1e-2

    def aggregate(self, variables, stacked_vars, counts, infos: LocalResult, rng, server_state):
        clipped_params = jax.vmap(
            lambda local: agc_clip_update(variables["params"], local, self.clipping)
        )(stacked_vars["params"])
        stacked = dict(stacked_vars)
        stacked["params"] = clipped_params
        return tree_weighted_mean(stacked, counts), server_state

    def crosssilo_hooks(self):
        """The hook form of :meth:`aggregate` — on the BASE class because
        the unit-wise clip is a pure per-client transform that both
        non-vmap execution forms apply at the same point: pre-psum on the
        mesh path, at lane emit on the packed schedule
        (FedAvgAPI._packing_hooks) — so FedAGC rides the packed MXU fast
        path in every paradigm."""
        clipping = self.clipping

        def client_transform(gvars, stacked):
            out = dict(stacked)
            out["params"] = jax.vmap(
                lambda local: agc_clip_update(gvars["params"], local, clipping)
            )(stacked["params"])
            return out

        return dict(client_transform=client_transform)


class CrossSiloFedAGCAPI(CrossSiloFedAvgAPI, FedAGCAPI):
    """FedAGC on the cross-silo mesh path: the unit-wise AGC clip runs on
    each device BEFORE the weighted psum — no server rank needed at all
    (the fork's SiloFedAGC._aggregate, silo_fedagc.py:50-69, does the same
    math after an MPI gather; hooks on FedAGCAPI.crosssilo_hooks)."""
